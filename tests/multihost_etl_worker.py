"""Worker process for the two-process ETL sharding test (invoked by
tests/test_parallel_etl.py as a subprocess, one per simulated host).

Each process joins the 2-process gloo-backed distributed runtime and
builds a ParallelImageDataSetIterator with shardByHost="auto" over the
SAME image tree; it prints its shard's file basenames and label list so
the parent can assert per-host disjointness + full coverage, plus its
first batch's checksum so the parent can verify both hosts decode their
own (different) shards."""

import os
import sys


def main():
    coord, n_proc, pid, root = (sys.argv[1], int(sys.argv[2]),
                                int(sys.argv[3]), sys.argv[4])
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    from deeplearning4j_tpu.parallel.multihost import (
        MultiHost, VoidConfiguration)

    topo = MultiHost.initialize(
        VoidConfiguration(controllerAddress=coord),
        num_processes=n_proc, process_id=pid)
    print(f"TOPOLOGY {topo['process_index']} {topo['process_count']}",
          flush=True)

    import numpy as np

    from deeplearning4j_tpu.datasets import (
        FileSplit, ParallelImageDataSetIterator)

    it = ParallelImageDataSetIterator(
        FileSplit(root), 8, 8, 3, batchSize=4, numWorkers=2,
        shuffle=True)
    names = sorted(os.path.basename(os.path.dirname(f)) + "/" +
                   os.path.basename(f) for f in it._files)
    print("SHARD " + ",".join(names), flush=True)
    print("LABELS " + ",".join(it.getLabels()), flush=True)
    ds = it.next()
    feats = np.asarray(ds.getFeatures())
    print(f"BATCHSUM {float(feats.sum()):.3f} {it._n_batches}",
          flush=True)

    # host-sharded batches are per-process DISTINCT: assembling them
    # through mesh.host_sharded_batch must concatenate both hosts'
    # rows into the global batch (nothing silently dropped)
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel.mesh import (
        MeshConfig, host_sharded_batch)

    assert it.hostSharded
    mesh = MeshConfig.data_parallel()
    g = host_sharded_batch(mesh, feats)
    gsum = jax.jit(jnp.sum)(g)
    print(f"GLOBALSUM {float(gsum):.3f} {g.shape[0]}", flush=True)
    it.close()


if __name__ == "__main__":
    main()
