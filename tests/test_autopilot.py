"""ISSUE 20 tests: the closed-loop fleet.

Fast tier: train-class admission arbitration (train tickets shed
first, the throttled iterator holds exactly one standing slot and
releases on job end), a real capture → fine-tune → publish → promote
run over in-process workers, respawner backoff/give-up semantics with
injectable process/clock seams, autoscaler hysteresis (flapping load
produces zero actions) + capacity-planner gating, capture
append/rotation with bit-identical replay, and decode-path rollouts
(token-stream agreement promotes, a diverging canary rolls back with
the incumbent engine untouched).

Slow tier (armed lock witness): the end-to-end closed-loop scenario —
a spawned fleet serving while a fine-tune job trains at ``train``
priority from its own captured traffic, publishes the checkpoint
through a canary, survives a SIGKILLed worker via the respawner, and
scales up under sustained overload / back down when idle, every
transition a flight event visible at /debug/fleet.
"""

import json
import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.fleet import (
    Autopilot, Autoscaler, CaptureReplayIterator, FleetFineTuner,
    FleetRouter, Respawner, TrafficCapture, WorkerHandle)
from deeplearning4j_tpu.fleet.autopilot import ThrottledIterator
from deeplearning4j_tpu.fleet.capture import capture_files, load_capture
from deeplearning4j_tpu.fleet.router import _http
from deeplearning4j_tpu.serving import AdmissionController
from deeplearning4j_tpu.serving.admission import ShedError
from deeplearning4j_tpu.telemetry import flight
from deeplearning4j_tpu.telemetry.memledger import CapacityError

from tests.test_fleet import (
    CPU_ENV, _drive_until, _Fleet, _InprocWorker, _spec)


def _tiny_net(seed=3, n_in=3, n_out=2):
    from deeplearning4j_tpu.nn import (
        DenseLayer, InputType, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam

    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer.Builder(nOut=8, activation="tanh")
                   .build())
            .layer(OutputLayer.Builder().nOut(n_out)
                   .activation("softmax").build())
            .setInputType(InputType.feedForward(n_in))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def _fill_capture(cap, n=8, n_in=3, n_out=2, model="m", seed=0):
    """Synthesize n captured requests with distillation labels."""
    rng = np.random.default_rng(seed)
    for _ in range(n):
        x = rng.normal(size=(2, n_in)).astype(np.float32)
        p = rng.dirichlet(np.ones(n_out), size=2).astype(np.float32)
        cap.maybe_record(
            model, json.dumps({"instances": x.tolist()}).encode(),
            json.dumps({"predictions": p.tolist(),
                        "version": 1}).encode())
    return cap


def _events(kind):
    return flight.get_recorder().events(kind)


def _batches(n=3, n_in=3, n_out=2, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.normal(size=(2, n_in)).astype(np.float32),
             np.eye(n_out, dtype=np.float32)[[0, 1]])
            for _ in range(n)]


# ---------------------------------------------------------------------------
# train-class admission arbitration
# ---------------------------------------------------------------------------

class TestTrainClassArbitration:
    def test_train_tickets_shed_first(self):
        adm = AdmissionController(default_budget=8)
        # budget 8: train cap = 2, normal cap = 6, high cap = 8
        t1 = adm.admit("m", "train")
        t2 = adm.admit("m", "train")
        with pytest.raises(ShedError) as ei:
            adm.admit("m", "train")
        assert ei.value.priority == "train"
        assert ei.value.retry_after > 0
        # the SAME standing load does not shed normal or high traffic:
        # train is the first class over its share
        n = adm.admit("m", "normal")
        h = adm.admit("m", "high")
        for t in (t1, t2, n, h):
            t.release()
        assert adm.describe()["m"]["standing"] == 0

    def test_throttled_iterator_one_slot_released_at_end(self):
        adm = AdmissionController(default_budget=8)
        it = ThrottledIterator(ListDataSetIterator(_batches(3), 2),
                               adm, "m")
        seen = []
        standing = []
        it.reset()
        while it.hasNext():
            seen.append(it.next())
            standing.append(adm.describe()["m"]["standing"])
        # each handed-out batch held exactly ONE train slot
        assert len(seen) == 3
        assert standing == [1, 1, 1]
        # epoch end released the last ticket
        assert adm.describe()["m"]["standing"] == 0
        # a second epoch works (__iter__ resets)
        assert len(list(it)) == 3
        it.close()
        assert adm.describe()["m"]["standing"] == 0

    def test_throttled_iterator_waits_out_shed(self):
        adm = AdmissionController(default_budget=8)
        blockers = [adm.admit("m", "train"), adm.admit("m", "train")]
        slept = []

        def sleep(dt):
            # serving load drains while the trainer is parked
            if blockers:
                blockers.pop().release()
            slept.append(dt)

        it = ThrottledIterator(ListDataSetIterator(_batches(1), 2),
                               adm, "m", sleep=sleep)
        out = list(it)
        assert len(out) == 1
        assert it.sheds >= 1 and slept
        it.close()
        for b in blockers:
            b.release()
        # the iterator's own ticket is gone; only the un-drained
        # blocker was left standing
        assert adm.describe()["m"]["standing"] == 0

    def test_throttled_iterator_gives_up_past_max_wait(self):
        adm = AdmissionController(default_budget=8)
        blockers = [adm.admit("m", "train"), adm.admit("m", "train")]
        it = ThrottledIterator(ListDataSetIterator(_batches(1), 2),
                               adm, "m", sleep=lambda dt: None,
                               max_wait=0.0)
        with pytest.raises(ShedError):
            list(it)
        for b in blockers:
            b.release()


# ---------------------------------------------------------------------------
# fine-tune → publish → promote (in-process fleet)
# ---------------------------------------------------------------------------

class TestFineTuner:
    def test_capture_to_promoted_version(self, tmp_path):
        cap = _fill_capture(TrafficCapture(), n=8)
        path = cap.save(str(tmp_path / "traffic.jsonl"))
        adm = AdmissionController(default_budget=8)
        with _Fleet(n=2) as f:
            ft = FleetFineTuner(
                f.router, "m", path, _tiny_net,
                str(tmp_path / "ckpt"), admission=adm, epochs=2,
                batch_size=4,
                spec_extra={"example_shape": [3]},
                rollout_kw={"fraction": 1.0, "min_samples": 4,
                            "p99_ratio": 100.0},
                everyNIterations=1)
            ctl = ft.run()
            assert ft.state == "complete"
            assert ft.checkpoint and os.path.exists(ft.checkpoint)
            assert ft.published_version == 2
            # the canary judges the fine-tuned model; agreement is
            # relaxed (min_agreement defaults to 0.0 on this path), so
            # the verdict rides errors/latency and must promote
            _drive_until(f, ctl, timeout=30.0)
            assert ctl.state == "complete", ctl.describe()
            # every worker now serves the checkpoint build as v2
            status, _, body = f.predict([[1.0, 2.0, 3.0]])
            assert status == 200
            assert json.loads(body)["version"] == 2
        # the job's train tickets are all released
        assert adm.describe()["m"]["standing"] == 0
        kinds = [e["kind"] for e in flight.get_recorder().events()]
        for k in ("finetune_start", "finetune_publish",
                  "finetune_complete"):
            assert k in kinds
        done = _events("finetune_complete")[-1]
        assert done["outcome"] == "ok" and done["version"] == 2

    def test_empty_capture_fails_cleanly(self, tmp_path):
        cap = TrafficCapture()
        path = cap.save(str(tmp_path / "empty.jsonl"))
        router = FleetRouter([WorkerHandle("w0", "http://127.0.0.1:1")])
        ft = FleetFineTuner(router, "m", path, _tiny_net,
                            str(tmp_path / "ckpt"))
        with pytest.raises(ValueError):
            ft.run()
        assert ft.state == "failed" and "no examples" in ft.error
        assert _events("finetune_complete")[-1]["outcome"] == "failed"


# ---------------------------------------------------------------------------
# respawner
# ---------------------------------------------------------------------------

class _FakeProc:
    def __init__(self, rc=None):
        self.rc = rc
        self.returncode = rc
        self.pid = 4242

    def poll(self):
        return self.rc

    def kill(self):
        self.rc = self.returncode = -9


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _respawnable(tmp_path, name="w0"):
    return WorkerHandle(
        name, "http://127.0.0.1:1", proc=_FakeProc(rc=1),
        spawn={"cmd": ["true"], "env": {},
               "port_file": str(tmp_path / f"{name}.port")})


class TestRespawner:
    def test_respawn_success_updates_handle(self, tmp_path):
        w = _respawnable(tmp_path)
        router = FleetRouter([w])
        port_file = w.spawn["port_file"]

        def popen(cmd, env):
            with open(port_file, "w") as f:
                f.write("5123")
            return _FakeProc(rc=None)   # alive

        clock = _Clock()
        rs = Respawner(router, max_respawns=3, spawn_timeout=2.0,
                       clock=clock, popen=popen)
        assert rs.tick() == [("w0", "ok")]
        assert w.url == "http://127.0.0.1:5123"
        assert w.proc.poll() is None
        # an alive worker is not touched on subsequent ticks
        clock.t += 100.0
        assert rs.tick() == []
        assert _events("worker_respawn")[-1]["outcome"] == "ok"

    def test_gives_up_after_budget(self, tmp_path):
        from deeplearning4j_tpu.resilience.supervisor import (
            SupervisorConfig)

        w = _respawnable(tmp_path)
        router = FleetRouter([w])
        clock = _Clock()
        rs = Respawner(
            router, max_respawns=2, spawn_timeout=0.2, clock=clock,
            popen=lambda cmd, env: _FakeProc(rc=7),   # dies instantly
            config=SupervisorConfig(backoff_base=1.0,
                                    backoff_factor=2.0))
        outcomes = []
        for _ in range(10):
            outcomes += rs.tick()
            clock.t += 0.4
        # backoff gates the attempts: after attempt 1 the next try
        # waits backoff(1)=1.0s of injected clock, then backoff(2)=2.0
        assert outcomes == [("w0", "failed"), ("w0", "failed"),
                            ("w0", "gave_up")]
        st = rs.describe()["workers"]["w0"]
        assert st["gave_up"] and st["attempts"] == 2
        # terminal: no further attempts however long we wait
        clock.t += 1000.0
        assert rs.tick() == []
        evs = _events("worker_respawn")
        assert [e["outcome"] for e in evs[-3:]] == \
            ["failed", "failed", "gave_up"]

    def test_adopted_workers_skipped(self):
        # no proc / no spawn record -> nothing to respawn
        router = FleetRouter([WorkerHandle("w0", "http://127.0.0.1:1")])
        assert Respawner(router).tick() == []


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------

def _scaler(router, load, **kw):
    state = {"v": load}
    clock = _Clock()
    kw.setdefault("sustain_ticks", 2)
    kw.setdefault("cooldown", 5.0)
    sc = Autoscaler(
        router, _spec(), "k", worker_rps=4.0, min_workers=1,
        max_workers=3, load_fn=lambda: state["v"],
        spawn_fn=lambda spec, name: WorkerHandle(
            name, "http://127.0.0.1:1"), clock=clock, **kw)
    return sc, clock, state


class TestAutoscaler:
    def test_flapping_load_no_flapping_workers(self):
        router = FleetRouter([WorkerHandle("w0", "http://127.0.0.1:1")])
        sc, clock, state = _scaler(router, 12.0)
        # alternates 12 rps (wants 3 workers) and 0 (wants 1) — the
        # sustain requirement is never met, so nothing ever happens
        for i in range(20):
            state["v"] = 12.0 if i % 2 == 0 else 0.0
            assert sc.tick() is None
            clock.t += 1.0
        assert len(router.workers) == 1

    def test_sustained_load_scales_up_then_idle_scales_down(self):
        router = FleetRouter([WorkerHandle("w0", "http://127.0.0.1:1")])
        sc, clock, state = _scaler(router, 9.0)   # wants ceil(9/4) = 3
        assert sc.tick() is None             # sustain 1/2
        assert sc.tick() == "scale_up"       # acts, one per action
        assert [w.name for w in router.workers] == ["w0", "auto0"]
        # cooldown: no second action until the clock passes it
        assert sc.tick() is None and sc.tick() is None
        clock.t += 6.0
        assert sc.tick() is None             # re-sustain after cooldown
        assert sc.tick() == "scale_up"
        assert len(router.workers) == 3
        assert sc.last_desired == 3
        # idle: back down, retiring the autoscaler's own workers first,
        # never below min_workers
        state["v"] = 0.0
        clock.t += 6.0
        decisions = []
        for _ in range(12):
            d = sc.tick()
            if d:
                decisions.append(d)
                clock.t += 6.0
        assert decisions == ["scale_down", "scale_down"]
        assert [w.name for w in router.workers] == ["w0"]
        for _ in range(4):
            assert sc.tick() is None         # floor holds
        evs = _events("autoscale")
        assert [e["decision"] for e in evs[-4:]] == \
            ["scale_up", "scale_up", "scale_down", "scale_down"]
        kinds = [e["kind"] for e in flight.get_recorder().events()]
        assert "worker_added" in kinds and "worker_retired" in kinds

    def test_capacity_planner_blocks_spawn(self, monkeypatch):
        from deeplearning4j_tpu.telemetry import memledger

        router = FleetRouter([WorkerHandle("w0", "http://127.0.0.1:1")])
        sc, clock, state = _scaler(router, 9.0, need_bytes=1 << 40)

        def deny(site, need_bytes, detail=None, **kw):
            raise CapacityError(f"{site}: no headroom for {need_bytes}")

        monkeypatch.setattr(memledger, "plan_capacity", deny)
        assert sc.tick() is None
        assert sc.tick() == "blocked"
        assert len(router.workers) == 1      # never spawned
        assert _events("autoscale")[-1]["decision"] == "blocked"
        # the demand is still pending: once capacity appears the next
        # tick acts without re-sustaining from zero
        monkeypatch.setattr(memledger, "plan_capacity",
                            lambda *a, **kw: None)
        assert sc.tick() == "scale_up"
        assert len(router.workers) == 2

    def test_desired_clamps_to_bounds(self):
        router = FleetRouter([WorkerHandle("w0", "http://127.0.0.1:1")])
        sc, _, _ = _scaler(router, 0.0)
        assert sc.desired(1e9) == 3 and sc.desired(0.0) == 1


# ---------------------------------------------------------------------------
# capture append + rotation
# ---------------------------------------------------------------------------

class TestCaptureAppendRotation:
    def test_append_commits_only_new_records(self, tmp_path):
        cap = TrafficCapture()
        _fill_capture(cap, n=3, seed=1)
        path = str(tmp_path / "c.jsonl")
        cap.save(path, append=True)
        assert len(load_capture(path)) == 3
        _fill_capture(cap, n=2, seed=2)
        cap.save(path, append=True)
        recs = load_capture(path)
        assert [r["seq"] for r in recs] == [1, 2, 3, 4, 5]
        # idempotent: appending with nothing new changes no bytes
        with open(path, "rb") as f:
            before = f.read()
        cap.save(path, append=True)
        with open(path, "rb") as f:
            assert f.read() == before

    def test_rotation_and_bit_identical_replay(self, tmp_path):
        cap = TrafficCapture()
        path = str(tmp_path / "c.jsonl")
        # force rotations: max_bytes smaller than two appends' records
        for seed in range(4):
            _fill_capture(cap, n=2, seed=seed)
            cap.save(path, append=True, max_bytes=400)
        files = capture_files(path)
        assert len(files) > 1
        assert files[-1] == path           # base file is newest
        # the rotated set reads oldest-first: seq strictly increasing
        seqs = [r["seq"] for r in load_capture(path)]
        assert seqs == sorted(seqs) and len(seqs) == 8
        # replay of the rotated set is bit-identical to an unrotated
        # save of the same ring
        flat = str(tmp_path / "flat.jsonl")
        cap.save(flat)                     # full ring, one file
        a = [ds.features for ds in CaptureReplayIterator(path)]
        b = [ds.features for ds in CaptureReplayIterator(flat)]
        assert len(a) == len(b) > 0
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_rotated_files_survive_multiple_sweeps(self, tmp_path):
        cap = TrafficCapture()
        path = str(tmp_path / "c.jsonl")
        for seed in range(6):
            _fill_capture(cap, n=2, seed=10 + seed)
            cap.save(path, append=True, max_bytes=200)
        # every record is still present exactly once across the set
        assert [r["seq"] for r in load_capture(path)] == \
            list(range(1, 13))


# ---------------------------------------------------------------------------
# decode-path rollouts
# ---------------------------------------------------------------------------

def _dspec(seed=0, name="d", version=1):
    return {"name": name, "version": version, "kind": "decoder",
            "seed": seed, "vocab": 16, "hidden": 8, "n_layers": 1,
            "n_heads": 2, "max_len": 32, "max_slots": 2, "page": 4,
            "max_pages_per_slot": 8}


def _decode(fleet, prompt=(1, 2, 3), n=4, model="d"):
    body = json.dumps({"prompt": list(prompt),
                       "max_new_tokens": n}).encode()
    return _http(f"{fleet.url}/serving/v1/models/{model}:decode",
                 body=body, timeout=30.0)


def _drive_decode_until(fleet, ctl, timeout=60.0):
    # like _drive_until: no per-request status assert — a router poll
    # can transiently mark a worker not-ready under suite load (503),
    # and the rollout verdict below is the oracle
    deadline = time.monotonic() + timeout
    while not ctl.terminal() and time.monotonic() < deadline:
        _decode(fleet)
        time.sleep(0.005)
    assert ctl.terminal(), \
        f"decode rollout stuck in {ctl.state}: {ctl.describe()}"


class TestDecodeRollout:
    def test_agreeing_decode_canary_promotes(self):
        with _Fleet(n=2, specs=[_spec(), _dspec()]) as f:
            status, rh, body = _decode(f)
            assert status == 200
            baseline = json.loads(body)["tokens"]
            # the worker reports TTFT; the router passes the header on
            st = {k.lower(): v for k, v in rh.items()}
            assert "ttft" in st.get("server-timing", "")
            ctl = f.router.start_rollout(
                "d", _dspec(seed=0), version=2, fraction=1.0,
                min_samples=3, p99_ratio=100.0, push_timeout=120.0)
            assert ctl.kind == "decode"
            assert ctl.mirror_name == "d@v2"
            # while canarying, the alias engine exists on the canary
            canary = next(w for w in f.workers
                          if w.handle.name == ctl.canary.name)
            assert "d@v2" in canary.session._decoders
            _drive_decode_until(f, ctl)
            assert ctl.state == "complete", ctl.describe()
            s = ctl.describe()
            assert s["agreement"] == 1.0 and s["errors"] == 0
            # promotion replaced the bare name everywhere and dropped
            # the judging alias
            for w in f.workers:
                assert "d" in w.session._decoders
                assert "d@v2" not in w.session._decoders
            status, _, body = _decode(f)
            assert status == 200
            assert json.loads(body)["tokens"] == baseline

    def test_diverging_decode_canary_rolls_back(self):
        with _Fleet(n=2, specs=[_spec(), _dspec()]) as f:
            engines = [w.session._decoders["d"] for w in f.workers]
            ctl = f.router.start_rollout(
                "d", _dspec(seed=99), version=2, fraction=1.0,
                min_samples=3, p99_ratio=100.0, push_timeout=120.0)
            _drive_decode_until(f, ctl)
            assert ctl.state == "rolled_back", ctl.describe()
            assert "agreement" in ctl.decision["reason"]
            # rollback retracted ONLY the alias: the incumbent engines
            # were never touched
            for w, engine in zip(f.workers, engines):
                assert w.session._decoders["d"] is engine
                assert "d@v2" not in w.session._decoders

    def test_decode_rollout_does_not_pin_predict(self):
        with _Fleet(n=2, specs=[_spec(), _dspec()]) as f:
            ctl = f.router.start_rollout(
                "d", _dspec(seed=0), version=2, fraction=1.0,
                min_samples=10_000, p99_ratio=100.0,
                push_timeout=120.0)
            try:
                assert not ctl.pins("d") and not ctl.pins("m")
                # predict traffic flows un-pinned during a decode canary
                status, _, body = f.predict([[1.0, 2.0, 3.0]])
                assert status == 200
                assert json.loads(body)["version"] == 1
            finally:
                ctl._rollback("test over", ctl._stats())


# ---------------------------------------------------------------------------
# autopilot control loop
# ---------------------------------------------------------------------------

class TestAutopilot:
    def test_tick_survives_controller_errors_and_describe(self):
        router = FleetRouter([WorkerHandle("w0", "http://127.0.0.1:1")])

        class Boom:
            def tick(self):
                raise RuntimeError("boom")

            def describe(self):
                return {"boom": True}

        ap = Autopilot(router, respawner=Boom(), interval=0.01)
        ap.tick()   # must not raise
        assert ap.ticks == 1
        assert ap.describe()["respawner"] == {"boom": True}

    def test_thread_attaches_to_router_and_stops(self):
        router = FleetRouter([WorkerHandle("w0", "http://127.0.0.1:1")])
        rs = Respawner(router)
        with Autopilot(router, respawner=rs, interval=0.01) as ap:
            ap.start()
            assert router.autopilot is ap
            deadline = time.monotonic() + 5.0
            while ap.ticks == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert ap.ticks > 0
            assert "respawner" in router.describe()["autopilot"]
        assert not ap._thread.is_alive()
        assert _events("autopilot_start")


# ---------------------------------------------------------------------------
# the closed loop, end to end (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestClosedLoop:
    def test_capture_finetune_publish_respawn_autoscale(self, tmp_path):
        import signal as _signal

        from deeplearning4j_tpu.fleet.router import spawn_local_workers

        mlp = {"name": "m", "version": 1, "kind": "mlp", "n_in": 3,
               "n_out": 2, "width": 8, "seed": 7,
               "example_shape": [3], "ladder": [1, 4]}
        spec = {"models": [mlp]}
        handles = spawn_local_workers(
            2, spec, base_dir=str(tmp_path / "fleet"), timeout=120.0,
            extra_env=CPU_ENV)
        cap = TrafficCapture(sample_interval=1, max_records=256)
        router = FleetRouter(handles, poll_interval=0.1, capture=cap,
                             owns_workers=True,
                             retry_budget=4).start(port=0)
        url = f"http://127.0.0.1:{router.port}"
        rng = np.random.default_rng(5)
        stats = {"sent": 0, "ok": 0, "lat": []}

        def predict_once():
            x = rng.normal(size=(2, 3)).astype(np.float32)
            t0 = time.perf_counter()
            status, _, rb = _http(
                f"{url}/serving/v1/models/m:predict",
                body=json.dumps({"instances": x.tolist()}).encode(),
                timeout=30.0)
            stats["sent"] += 1
            stats["ok"] += status == 200
            if status != 200:
                stats.setdefault("bad", []).append((status, rb[:300]))
            stats["lat"].append(time.perf_counter() - t0)
            return status

        try:
            # ---- phase 1: serve + capture --------------------------
            for _ in range(30):
                assert predict_once() == 200
            path = cap.save(str(tmp_path / "traffic.jsonl"),
                            append=True)
            assert len(load_capture(path)) >= 30

            # ---- phase 2: fine-tune at train priority while serving
            # continues; serving p99 stays bounded -------------------
            adm = AdmissionController(default_budget=8)
            ft = FleetFineTuner(
                router, "m", path, lambda: _tiny_net(seed=7),
                str(tmp_path / "ckpt"), admission=adm, epochs=2,
                batch_size=8, spec_extra={"example_shape": [3]},
                rollout_kw={"fraction": 1.0, "min_samples": 5,
                            "p99_ratio": 100.0, "push_timeout": 120.0},
                everyNIterations=1).start()
            base_lat = list(stats["lat"])
            while ft._thread.is_alive():
                predict_once()
                time.sleep(0.005)
            ft.join(30.0)
            during = stats["lat"][len(base_lat):]
            assert ft.state == "complete", ft.describe()
            # serving kept answering during the concurrent fit, and
            # its p99 stayed within a generous bound of the unloaded
            # baseline (CPU box; this catches seconds-long stalls, not
            # microseconds of jitter)
            assert during, "no serving traffic during fine-tune"
            p99 = float(np.quantile(during, 0.99))
            base = max(float(np.quantile(base_lat, 0.99)), 0.005)
            assert p99 < 50 * base, (p99, base)
            assert adm.describe()["m"]["standing"] == 0

            # ---- phase 3: the published canary promotes ------------
            ctl = router.rollout
            assert ctl is not None and ctl.version == 2
            deadline = time.monotonic() + 120.0
            while not ctl.terminal() and time.monotonic() < deadline:
                predict_once()
                time.sleep(0.005)
            assert ctl.state == "complete", ctl.describe()
            status, _, body = _http(
                f"{url}/serving/v1/models/m:predict",
                body=json.dumps(
                    {"instances": [[0.1, 0.2, 0.3]]}).encode(),
                timeout=30.0)
            assert status == 200 and json.loads(body)["version"] == 2

            # ---- phase 4: SIGKILL a worker; the autopilot respawns
            # it with zero client-visible errors ---------------------
            rs = Respawner(router, max_respawns=3, spawn_timeout=120.0)
            ap = Autopilot(router, respawner=rs, interval=0.1).start()
            # steady state first: the promote just pushed v2 to the
            # non-canary worker, which reports warming until its ladder
            # compiles — kill only once BOTH workers are routable, or
            # the fleet legitimately has zero capacity for a moment
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                _, _, hb = _http(url + "/healthz", timeout=10.0)
                if json.loads(hb)["fleet"]["routable"] == 2:
                    break
                time.sleep(0.05)
            victim = router.workers[0]
            # the flight ring is process-global: earlier tests in this
            # process (TestRespawner's fakes, also named w0) may have
            # left worker_respawn events — count only events after the
            # kill
            seen = len(_events("worker_respawn"))

            def _respawned():
                return any(e["outcome"] == "ok"
                           for e in _events("worker_respawn")[seen:])

            os.kill(victim.proc.pid, _signal.SIGKILL)
            ok_before, sent_before = stats["ok"], stats["sent"]
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                predict_once()
                if _respawned() and victim.up:
                    break
                time.sleep(0.01)
            assert _respawned()
            # retries absorbed the death: zero failed requests
            assert stats["ok"] - ok_before == \
                stats["sent"] - sent_before, stats.get("bad")
            # the respawned worker rejoined routing
            deadline = time.monotonic() + 60.0
            while not victim.up and time.monotonic() < deadline:
                time.sleep(0.05)
            assert victim.up and victim.proc.poll() is None

            # ---- phase 5: sustained overload scales up; idle scales
            # down — driven deterministically via tick() -------------
            clock = _Clock()
            load = {"v": 9.0}   # 2x what two workers handle at 4 rps
            sc = Autoscaler(
                router, spec, "k", worker_rps=4.0, min_workers=2,
                max_workers=3, sustain_ticks=2, cooldown=1.0,
                load_fn=lambda: load["v"],
                spawn_fn=lambda s, name: spawn_local_workers(
                    1, s, base_dir=str(tmp_path / "auto"),
                    timeout=120.0, extra_env=CPU_ENV,
                    name_prefix="auto",
                    start_index=int(name[4:]))[0],
                clock=clock)
            ap.autoscaler = sc
            decisions = []
            for _ in range(6):
                d = sc.tick()
                if d:
                    decisions.append(d)
                    clock.t += 2.0
            assert decisions == ["scale_up"]
            assert len(router.workers) == 3
            # the new worker serves traffic too
            for _ in range(10):
                assert predict_once() == 200
            load["v"] = 0.0
            clock.t += 2.0
            for _ in range(8):
                d = sc.tick()
                if d:
                    decisions.append(d)
                    clock.t += 2.0
            assert decisions == ["scale_up", "scale_down"]
            assert len(router.workers) == 2

            # ---- every transition observable -----------------------
            events = {e["kind"]
                      for e in flight.get_recorder().events()}
            for k in ("finetune_start", "finetune_publish",
                      "finetune_complete", "rollout_start",
                      "rollout_complete", "worker_respawn",
                      "worker_added", "worker_retired", "autoscale",
                      "autopilot_start"):
                assert k in events, f"missing flight event {k}"
            desc = router.describe()
            assert "autopilot" in desc
            assert desc["autopilot"]["respawner"]["workers"]
            ap.close()
        finally:
            router.close()
