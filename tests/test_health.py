"""Training-health diagnostics tests (ISSUE 3): in-step per-layer
stats, divergence policies (WARN / HALT / SKIP_BATCH), the flight
recorder, /healthz + /debug/flightrecorder routes, the disabled-path
zero-overhead contract, and the metric-name drift check."""

import json
import subprocess
import sys
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import MetricsRegistry, flight, health, prometheus
from deeplearning4j_tpu.utils.listeners import HealthListener


@pytest.fixture(autouse=True)
def clean_health_state():
    """Every test starts with default health config, clean divergence
    status, and an empty flight ring; telemetry flag restored after."""
    was_enabled = telemetry.enabled()
    prev_cfg = health.get_config()
    health.reset_status()
    health.configure(enabled=True, policy=health.WARN, ratio_max=None,
                     ratio_min=None, check_every=1, dump_dir=None)
    flight.get_recorder().clear()
    yield
    health._state["config"] = prev_cfg
    health._state["enabled"] = True
    health.reset_status()
    (telemetry.enable if was_enabled else telemetry.disable)()


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry()
    prev = telemetry.set_registry(reg)
    telemetry.enable()
    yield reg
    telemetry.set_registry(prev)


def _tiny_net(seed=1):
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)

    conf = (NeuralNetConfiguration.Builder().seed(seed).list()
            .layer(DenseLayer.Builder().nIn(4).nOut(8)
                   .activation("relu").build())
            .layer(OutputLayer.Builder().nOut(2).activation("softmax")
                   .lossFunction(LossFunction.MCXENT).build())
            .build())
    return MultiLayerNetwork(conf).init()


def _tiny_data(n=16, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return X, y


class TestLayerStats:
    def test_stats_values(self):
        import jax.numpy as jnp

        grad = {"W": jnp.asarray([[3.0, 4.0]])}       # L2 = 5
        upd = {"W": jnp.asarray([[0.0, 2.0]])}        # L2 = 2
        par = {"W": jnp.asarray([[8.0, 6.0]])}        # L2 = 10
        s = np.asarray(health.layer_stats(grad, upd, par))
        assert s[0] == pytest.approx(5.0)
        assert s[1] == pytest.approx(2.0)
        assert s[2] == pytest.approx(10.0)
        assert s[3] == pytest.approx(0.2)             # update:param
        assert s[4] == 0.0

    def test_nonfinite_counted(self):
        import jax.numpy as jnp

        grad = {"W": jnp.asarray([np.nan, 1.0, np.inf])}
        s = np.asarray(health.layer_stats(grad, grad, grad))
        assert s[4] == 6.0            # 2 each in grad, update, new params
        # a NaN confined to the PARAMS still counts (relu backprop can
        # zero the offending layer's own gradient)
        fin = {"W": jnp.asarray([1.0, 2.0])}
        nanp = {"W": jnp.asarray([np.nan, 2.0])}
        assert np.asarray(health.layer_stats(fin, fin, nanp))[4] == 1.0

    def test_step_ok_gate(self):
        import jax.numpy as jnp

        good = jnp.zeros((2, health.N_STATS), jnp.float32)
        bad = good.at[1, health.STAT_NAMES.index("nonfinite")].set(1.0)
        assert bool(health.step_ok(good))
        assert not bool(health.step_ok(bad))
        # a non-finite loss flows in via its own loss_stats row, so the
        # gate and the host monitor read one condition
        with_loss = jnp.concatenate(
            [good, health.loss_stats(jnp.float32(np.nan))[None]])
        assert not bool(health.step_ok(with_loss))
        assert np.asarray(health.loss_stats(jnp.float32(1.0)))[4] == 0.0


class TestWarnPolicy:
    def test_ratio_metrics_in_exposition(self, fresh_registry):
        net = _tiny_net()
        X, y = _tiny_data()
        net.fit([(X, y)], 3)
        text = prometheus.render(fresh_registry, collect_system=False)
        assert "dl4j_health_update_param_ratio" in text
        assert 'loop="fit",layer="0:DenseLayer"' in text
        assert 'layer="1:OutputLayer"' in text
        # 3 steps, one-behind + flush => all 3 processed
        parsed = prometheus.parse(text)
        key = ('dl4j_health_update_param_ratio_count'
               '{loop="fit",layer="0:DenseLayer"}')
        assert parsed[key] == 3.0

    def test_nan_warns_but_continues(self, fresh_registry):
        net = _tiny_net(2)
        X, y = _tiny_data()
        Xnan = X.copy()
        Xnan[0, 0] = np.nan
        net.fit([(X, y), (Xnan, y), (X, y)], 1)   # no raise under WARN
        snap = fresh_registry.snapshot()
        viol = [k for k in snap
                if k.startswith("dl4j_health_violations_total") and
                'kind="nonfinite"' in k and snap[k] > 0]
        assert viol
        events = flight.get_recorder().events("health_violation")
        assert events and events[-1]["violation"] == "nonfinite"

    def test_ratio_threshold_trips(self, fresh_registry):
        net = _tiny_net(3)
        net.setListeners(HealthListener(policy="warn", ratio_max=1e-12))
        X, y = _tiny_data()
        net.fit([(X, y)], 2)
        events = flight.get_recorder().events("health_violation")
        assert any(e["violation"] == "ratio_high" for e in events)

    def test_health_listener_receives_stats(self, fresh_registry):
        net = _tiny_net(4)
        listener = HealthListener()
        net.setListeners(listener)
        X, y = _tiny_data()
        net.fit([(X, y)], 3)
        assert len(listener.history) == 3
        stats = listener.lastStats()
        assert "0:DenseLayer" in stats
        row = stats["0:DenseLayer"]
        assert set(row) == set(health.STAT_NAMES)
        assert row["grad_norm"] > 0 and row["param_norm"] > 0
        assert row["nonfinite"] == 0.0


class TestHaltPolicy:
    def test_nan_gradient_halts_with_dump(self, fresh_registry, tmp_path):
        net = _tiny_net(5)
        net.setListeners(HealthListener(policy="halt",
                                        dump_dir=str(tmp_path)))
        # seed a NaN parameter -> NaN loss and NaN gradients on step 0
        net.setParam(0, "W", np.full((4, 8), np.nan, np.float32))
        X, y = _tiny_data()
        with pytest.raises(telemetry.DivergenceError) as ei:
            net.fit([(X, y)], 1)
        err = ei.value
        assert err.step == 0
        assert "0:DenseLayer" in err.layers
        assert "0:DenseLayer" in str(err)
        # the JSONL dump exists and names the offending layer and step
        assert err.dump_path and Path(err.dump_path).exists()
        events = [json.loads(line)
                  for line in Path(err.dump_path).read_text().splitlines()]
        div = [e for e in events if e["kind"] == "divergence"]
        assert div and div[-1]["step"] == 0
        assert "0:DenseLayer" in div[-1]["layers"]
        # /healthz payload reports the divergence with a 503
        payload, status = health.healthz()
        assert status == 503
        assert payload["status"] == "diverged"
        assert payload["divergence"]["step"] == 0
        assert "0:DenseLayer" in payload["divergence"]["layers"]

    def test_process_default_config_applies(self, fresh_registry):
        health.configure(policy=health.HALT)
        net = _tiny_net(6)
        net.setParam(0, "W", np.full((4, 8), np.inf, np.float32))
        X, y = _tiny_data()
        with pytest.raises(telemetry.DivergenceError):
            net.fit([(X, y)], 1)

    def test_net_usable_after_midloop_halt(self, fresh_registry):
        """HALT raising from the one-behind monitor mid-loop (while the
        NEXT step already donated the old buffers) must leave the net
        holding live params — callers catch DivergenceError to
        checkpoint/inspect."""
        health.configure(policy=health.HALT)
        net = _tiny_net(22)
        X, y = _tiny_data()
        Xnan = X.copy()
        Xnan[0, 0] = np.nan
        # bad batch in the middle: its stats are processed during the
        # following step's on_step call, after that step donated buffers
        with pytest.raises(telemetry.DivergenceError) as ei:
            net.fit([(X, y), (Xnan, y), (X, y)], 1)
        assert ei.value.step == 1
        w = net.getParam(0, "W").numpy()      # must not be deleted
        assert w.shape == (4, 8)
        out = net.output(X).numpy()           # net still drivable
        assert out.shape == (16, 2)


class TestSkipBatchPolicy:
    def test_bad_batch_discarded_on_device(self, fresh_registry):
        net = _tiny_net(7)
        net.setListeners(HealthListener(policy="skip_batch"))
        X, y = _tiny_data()
        net.fit([(X, y)], 1)            # healthy step applies
        before = net.getParam(0, "W").numpy().copy()
        Xnan = X.copy()
        Xnan[3, 1] = np.nan
        net.fit([(Xnan, y)], 1)         # diverged step is discarded
        after = net.getParam(0, "W").numpy()
        assert np.array_equal(before, after)
        net.fit([(X, y)], 1)            # training continues
        assert not np.array_equal(after, net.getParam(0, "W").numpy())
        assert np.isfinite(net.getParam(0, "W").numpy()).all()
        snap = fresh_registry.snapshot()
        assert snap['dl4j_health_skipped_steps_total{loop="fit"}'] == 1.0


class TestDisabledModeZeroOverhead:
    def test_zero_registry_calls_and_no_health_output(self):
        class CountingStub:
            calls = 0

            def __getattr__(self, name):
                CountingStub.calls += 1
                raise AssertionError(
                    f"registry.{name} touched while disabled")

        net = _tiny_net(8)
        X, y = _tiny_data()
        prev = telemetry.set_registry(CountingStub())
        telemetry.disable()
        try:
            net.fit([(X, y)], 3)
            assert CountingStub.calls == 0
        finally:
            telemetry.set_registry(prev)
            telemetry.enable()
        # the step was compiled WITHOUT health: pre-PR output structure
        assert net._train_step_plan == health.INACTIVE

    def test_output_bit_identical_and_one_dispatch_per_step(
            self, fresh_registry):
        X, y = _tiny_data()
        # same seed, health on vs telemetry disabled: params bit-equal
        net_on = _tiny_net(9)
        net_off = _tiny_net(9)
        net_on.fit([(X, y)], 3)
        telemetry.disable()
        try:
            net_off.fit([(X, y)], 3)
        finally:
            telemetry.enable()
        for k in ("W", "b"):
            assert np.array_equal(net_on.getParam(0, k).numpy(),
                                  net_off.getParam(0, k).numpy())
        # dispatch count: exactly one jitted-step call per batch
        telemetry.disable()
        try:
            net = _tiny_net(10)
            net.fit([(X, y)], 1)        # build + warm
            inner = net._train_step
            calls = []

            def counting(*a, **kw):
                calls.append(1)
                return inner(*a, **kw)

            net._train_step = counting
            net.fit([(X, y)], 3)
            assert len(calls) == 3
        finally:
            telemetry.enable()

    def test_health_off_while_telemetry_on(self, fresh_registry):
        health.configure(enabled=False)
        net = _tiny_net(11)
        X, y = _tiny_data()
        net.fit([(X, y)], 2)
        assert net._train_step_plan == health.INACTIVE
        snap = fresh_registry.snapshot()
        assert not any(k.startswith("dl4j_health") for k in snap)
        # step timing still recorded by the loop instruments
        assert snap['dl4j_step_seconds_count{loop="fit"}'] == 2.0


class TestTrainerIntegration:
    def test_sharded_trainer_health(self, fresh_registry):
        from deeplearning4j_tpu.datasets import DataSet
        from deeplearning4j_tpu.parallel.trainer import ShardedTrainer

        net = _tiny_net(12)
        X, y = _tiny_data()
        ShardedTrainer(net).fit([DataSet(X, y)], epochs=3)
        snap = fresh_registry.snapshot()
        key = ('dl4j_health_update_param_ratio_count'
               '{loop="sharded",layer="0:DenseLayer"}')
        assert snap[key] == 3.0

    def test_graph_fit_health_and_step_metrics(self, fresh_registry):
        from deeplearning4j_tpu.nn import (
            ComputationGraph, ComputationGraphConfiguration, DenseLayer,
            LossFunction, NeuralNetConfiguration, OutputLayer)

        conf = (NeuralNetConfiguration.Builder().seed(13)
                .graphBuilder()
                .addInputs("in")
                .addLayer("d", DenseLayer.Builder().nIn(4).nOut(8)
                          .activation("relu").build(), "in")
                .addLayer("out", OutputLayer.Builder().nIn(8).nOut(2)
                          .activation("softmax")
                          .lossFunction(LossFunction.MCXENT).build(), "d")
                .setOutputs("out")
                .build())
        assert isinstance(conf, ComputationGraphConfiguration)
        net = ComputationGraph(conf).init()
        X, y = _tiny_data()
        net.fit([(X, y)], 2)
        snap = fresh_registry.snapshot()
        assert snap['dl4j_step_seconds_count{loop="graph"}'] == 2.0
        ratio_keys = [k for k in snap
                      if k.startswith("dl4j_health_update_param_ratio_"
                                      "count") and 'loop="graph"' in k
                      and 'layer="d:DenseLayer"' in k]
        assert ratio_keys and snap[ratio_keys[0]] == 2.0

    def test_graph_halt_names_node(self, fresh_registry):
        from deeplearning4j_tpu.nn import (
            ComputationGraph, DenseLayer, LossFunction,
            NeuralNetConfiguration, OutputLayer)

        conf = (NeuralNetConfiguration.Builder().seed(14)
                .graphBuilder()
                .addInputs("in")
                .addLayer("enc", DenseLayer.Builder().nIn(4).nOut(8)
                          .activation("relu").build(), "in")
                .addLayer("out", OutputLayer.Builder().nIn(8).nOut(2)
                          .activation("softmax")
                          .lossFunction(LossFunction.MCXENT).build(),
                          "enc")
                .setOutputs("out")
                .build())
        net = ComputationGraph(conf).init()
        net._params["enc"]["W"] = np.full((4, 8), np.nan, np.float32)
        health.configure(policy=health.HALT)
        X, y = _tiny_data()
        with pytest.raises(telemetry.DivergenceError) as ei:
            net.fit([(X, y)], 1)
        assert any("enc" in name for name in ei.value.layers)

    def test_fit_multi_batch_health(self, fresh_registry):
        net = _tiny_net(15)
        X, y = _tiny_data()
        net.fitMultiBatch(np.stack([X] * 4), np.stack([y] * 4))
        snap = fresh_registry.snapshot()
        key = ('dl4j_health_update_param_ratio_count'
               '{loop="fit",layer="0:DenseLayer"}')
        assert snap[key] == 4.0


class TestFlightRecorder:
    def test_ring_bound_and_dump(self, tmp_path):
        rec = flight.FlightRecorder(capacity=8)
        for i in range(20):
            rec.record("step", step=i)
        events = rec.events()
        assert len(events) == 8
        assert events[0]["step"] == 12 and events[-1]["step"] == 19
        path = rec.dump(str(tmp_path / "f.jsonl"))
        lines = Path(path).read_text().splitlines()
        assert len(lines) == 8
        assert json.loads(lines[-1])["step"] == 19

    def test_disabled_records_nothing(self):
        rec = flight.get_recorder()
        flight.disable()
        try:
            flight.record("step", step=1)
            assert len(rec.events("step")) == 0
        finally:
            flight.enable()

    def test_step_events_from_fit(self, fresh_registry):
        net = _tiny_net(16)
        X, y = _tiny_data()
        net.fit([(X, y)], 3)
        steps = [e for e in flight.get_recorder().events("step")
                 if e["loop"] == "fit"]
        assert [e["step"] for e in steps] == [0, 1, 2]
        assert all(e["nonfinite"] == 0 for e in steps)

    def test_serving_request_summaries(self, fresh_registry):
        from deeplearning4j_tpu.serving import BucketLadder, InferenceSession

        net = _tiny_net(17)
        with InferenceSession(max_latency=0.001) as session:
            session.register("m", net, example_shape=(4,),
                             ladder=BucketLadder((1, 4)), warmup=True)
            x = np.zeros((4,), np.float32)
            session.predict("m", x)
            session.predict("m", x)
        warm = flight.get_recorder().events("model_warmup")
        assert warm and warm[-1]["model"] == "m"
        served = [e for e in flight.get_recorder().events("serving")
                  if e["model"] == "m" and e["outcome"] == "ok"]
        assert len(served) == 2
        # request ids are unique and correlate the two predicts
        assert served[0]["req_id"] != served[1]["req_id"]
        assert all(e["queue_s"] >= 0 for e in served)


class TestHealthzRoutes:
    def _get(self, url):
        try:
            r = urllib.request.urlopen(url)
            return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def test_healthz_ok_then_diverged(self, fresh_registry):
        from deeplearning4j_tpu.ui.server import UIServer

        ui = UIServer().start(port=0)
        try:
            base = f"http://127.0.0.1:{ui.port}"
            net = _tiny_net(18)
            X, y = _tiny_data()
            net.fit([(X, y)], 2)
            status, body = self._get(f"{base}/healthz")
            payload = json.loads(body)
            assert status == 200
            assert payload["status"] == "ok" and payload["ready"]
            assert payload["loops"]["fit"]["step"] == 1
            assert payload["loops"]["fit"]["last_step_age_seconds"] >= 0
            # acceptance: per-layer ratio samples served by GET /metrics
            status, metrics = self._get(f"{base}/metrics")
            assert status == 200
            assert ('dl4j_health_update_param_ratio_count'
                    '{loop="fit",layer="0:DenseLayer"}'
                    in metrics.decode())
            # now diverge under HALT
            health.configure(policy=health.HALT)
            net2 = _tiny_net(19)
            net2.setParam(0, "W", np.full((4, 8), np.nan, np.float32))
            with pytest.raises(telemetry.DivergenceError):
                net2.fit([(X, y)], 1)
            status, body = self._get(f"{base}/healthz")
            payload = json.loads(body)
            assert status == 503
            assert payload["status"] == "diverged"
            assert "0:DenseLayer" in payload["divergence"]["layers"]
        finally:
            ui.stop()

    def test_healthz_serving_readiness(self, fresh_registry):
        from deeplearning4j_tpu.serving import BucketLadder, InferenceSession
        from deeplearning4j_tpu.ui.server import UIServer

        net = _tiny_net(20)
        with InferenceSession() as session:
            session.register("m", net, example_shape=(4,),
                             ladder=BucketLadder((1, 4)), warmup=False)
            ui = UIServer()
            ui.serveModels(session)
            ui.start(port=0)
            try:
                base = f"http://127.0.0.1:{ui.port}"
                status, body = self._get(f"{base}/healthz")
                payload = json.loads(body)
                assert status == 503
                assert payload["status"] == "warming"
                assert payload["serving"]["warmed"] is False
                session.warmup()
                status, body = self._get(f"{base}/healthz")
                payload = json.loads(body)
                assert status == 200 and payload["serving"]["warmed"]
            finally:
                ui.stop()

    def test_flightrecorder_route(self, fresh_registry):
        from deeplearning4j_tpu.ui.server import UIServer

        net = _tiny_net(21)
        X, y = _tiny_data()
        net.fit([(X, y)], 2)
        ui = UIServer().start(port=0)
        try:
            base = f"http://127.0.0.1:{ui.port}"
            status, body = self._get(f"{base}/debug/flightrecorder")
            assert status == 200
            events = [json.loads(line)
                      for line in body.decode().splitlines() if line]
            assert any(e["kind"] == "step" for e in events)
        finally:
            ui.stop()


class TestMetricNameDrift:
    def test_tool_passes_on_current_tree(self):
        tool = Path(__file__).resolve().parent.parent / "tools" / \
            "check_metrics.py"
        proc = subprocess.run([sys.executable, str(tool)],
                              capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_tool_detects_drift(self):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                               / "tools"))
        try:
            import check_metrics
        finally:
            sys.path.pop(0)
        problems = check_metrics.check(
            names={"my_metric": ["x.py"],
                   "dl4j_undocumented_total": ["y.py"]},
            docs_text="nothing here")
        assert len(problems) == 3  # bad prefix + 2 undocumented
