"""AutoEncoder + VariationalAutoencoder layer tests.

Reference capability under test: conf.layers.AutoEncoder and
conf.layers.variational.VariationalAutoencoder with the
MultiLayerNetwork.pretrain/pretrainLayer path (SURVEY.md §2.5).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn import (
    AutoEncoder, DenseLayer, MultiLayerConfiguration, MultiLayerNetwork,
    NeuralNetConfiguration, OutputLayer, VariationalAutoencoder)
from deeplearning4j_tpu.nn.conf.variational import (
    BernoulliReconstructionDistribution, GaussianReconstructionDistribution)
from deeplearning4j_tpu.optimize.updaters import Adam


def _data(n=64, d=12, seed=0):
    rng = np.random.RandomState(seed)
    x = (rng.rand(n, d) > 0.5).astype(np.float32)
    y = np.zeros((n, 2), np.float32)
    y[np.arange(n), (x.sum(1) > d / 2).astype(int)] = 1.0
    return x, y


def _net(layers, seed=12345):
    b = (NeuralNetConfiguration.Builder()
         .seed(seed)
         .updater(Adam(1e-2))
         .list())
    for lr in layers:
        b = b.layer(lr)
    return MultiLayerNetwork(b.build()).init()


class TestAutoEncoder:
    def test_pretrain_reduces_reconstruction_loss(self):
        x, _ = _data()
        net = _net([
            AutoEncoder.Builder(nIn=12, nOut=6, corruptionLevel=0.2).build(),
            OutputLayer.Builder(nIn=6, nOut=2).build(),
        ])
        lr = net.layers[0]
        before = float(lr.pretrain_loss(net._params[0], x, None))
        net.pretrainLayer(0, (x, None))
        after = float(lr.pretrain_loss(net._params[0], x, None))
        # one batch, many implicit iterations? one step only: still must drop
        net.pretrainLayer(0, (x, None), epochs=30)
        final = float(lr.pretrain_loss(net._params[0], x, None))
        assert after < before
        assert final < after

    def test_supervised_forward_shape_and_fit(self):
        x, y = _data()
        net = _net([
            AutoEncoder.Builder(nIn=12, nOut=6).build(),
            OutputLayer.Builder(nIn=6, nOut=2).build(),
        ])
        out = net.output(x).numpy()
        assert out.shape == (64, 2)
        net.fit((x, y))
        s0 = net.score()
        net.fit([(x, y)] * 20)
        assert net.score() < s0

    def test_json_round_trip(self):
        net = _net([
            AutoEncoder.Builder(nIn=12, nOut=6, corruptionLevel=0.1,
                                sparsity=0.05,
                                lossFunction="mse").build(),
            OutputLayer.Builder(nIn=6, nOut=2).build(),
        ])
        js = net.conf.to_json()
        conf2 = MultiLayerConfiguration.from_json(js)
        ae = conf2.layers[0]
        assert isinstance(ae, AutoEncoder)
        assert ae.corruptionLevel == pytest.approx(0.1)
        assert ae.sparsity == pytest.approx(0.05)


class TestVariationalAutoencoder:
    def test_pretrain_improves_elbo(self):
        x, _ = _data(n=128)
        net = _net([
            VariationalAutoencoder.Builder(
                nIn=12, nOut=3, encoderLayerSizes=(16,),
                decoderLayerSizes=(16,),
                reconstructionDistribution="bernoulli").build(),
            OutputLayer.Builder(nIn=3, nOut=2).build(),
        ])
        vae = net.layers[0]
        import jax

        key = jax.random.key(7)
        before = float(vae.pretrain_loss(net._params[0], x, key))
        net.pretrain([(x, None)] * 60)
        after = float(vae.pretrain_loss(net._params[0], x, key))
        assert after < before

    def test_latent_and_generate_shapes(self):
        x, _ = _data(n=8)
        net = _net([
            VariationalAutoencoder.Builder(
                nIn=12, nOut=3, encoderLayerSizes=(10,),
                decoderLayerSizes=(10,)).build(),
            OutputLayer.Builder(nIn=3, nOut=2).build(),
        ])
        vae = net.layers[0]
        mean, log_var = vae.activate_latent(net._params[0], x)
        assert mean.shape == (8, 3) and log_var.shape == (8, 3)
        gen = vae.generate_at_mean_given_z(net._params[0],
                                           np.zeros((5, 3), np.float32))
        assert gen.shape == (5, 12)
        assert np.all(np.asarray(gen) >= 0) and np.all(np.asarray(gen) <= 1)

    def test_reconstruction_log_probability(self):
        x, _ = _data(n=16)
        net = _net([
            VariationalAutoencoder.Builder(
                nIn=12, nOut=3, encoderLayerSizes=(10,),
                decoderLayerSizes=(10,)).build(),
            OutputLayer.Builder(nIn=3, nOut=2).build(),
        ])
        vae = net.layers[0]
        lp = np.asarray(vae.reconstruction_log_probability(
            net._params[0], x, num_samples=4))
        assert lp.shape == (16,)
        assert np.all(np.isfinite(lp))
        assert np.all(lp <= 0.0 + 1e-6)  # bernoulli log-probs

    def test_gaussian_distribution(self):
        x = np.random.RandomState(0).randn(32, 6).astype(np.float32)
        net = _net([
            VariationalAutoencoder.Builder(
                nIn=6, nOut=2, encoderLayerSizes=(8,),
                decoderLayerSizes=(8,),
                reconstructionDistribution=GaussianReconstructionDistribution(
                    "identity")).build(),
            OutputLayer.Builder(nIn=2, nOut=2, lossFunction="mse",
                                activation="identity").build(),
        ])
        import jax

        key = jax.random.key(3)
        before = float(net.layers[0].pretrain_loss(net._params[0], x, key))
        net.pretrainLayer(0, [(x, None)] * 50)
        after = float(net.layers[0].pretrain_loss(net._params[0], x, key))
        assert after < before

    def test_json_round_trip_with_distribution(self):
        net = _net([
            VariationalAutoencoder.Builder(
                nIn=12, nOut=3, encoderLayerSizes=(16, 8),
                decoderLayerSizes=(8, 16),
                reconstructionDistribution=BernoulliReconstructionDistribution(
                )).build(),
            OutputLayer.Builder(nIn=3, nOut=2).build(),
        ])
        conf2 = MultiLayerConfiguration.from_json(net.conf.to_json())
        vae = conf2.layers[0]
        assert isinstance(vae, VariationalAutoencoder)
        assert isinstance(vae.reconstructionDistribution,
                          BernoulliReconstructionDistribution)
        assert vae.encoderLayerSizes == (16, 8)
        net2 = MultiLayerNetwork(conf2).init()
        x, _ = _data(n=4)
        assert net2.output(x).numpy().shape == (4, 2)

    def test_pretrain_rejects_non_pretrainable(self):
        net = _net([
            DenseLayer.Builder(nIn=12, nOut=6).build(),
            OutputLayer.Builder(nIn=6, nOut=2).build(),
        ])
        with pytest.raises(ValueError):
            net.pretrainLayer(0, (np.zeros((2, 12), np.float32), None))


class TestPretrainPlumbing:
    def test_generator_feeds_every_pretrainable_layer(self):
        # regression: a one-shot generator must be materialized so the
        # SECOND pretrainable layer doesn't see an exhausted iterator
        x, _ = _data(n=32)
        net = _net([
            AutoEncoder.Builder(nIn=12, nOut=8).build(),
            AutoEncoder.Builder(nIn=8, nOut=4).build(),
            OutputLayer.Builder(nIn=4, nOut=2).build(),
        ])
        import jax
        before1 = jax.tree_util.tree_map(
            lambda a: np.asarray(a).copy(), net._params[1])
        net.pretrain(((x, None) for _ in range(5)))
        after1 = net._params[1]
        changed = any(
            not np.allclose(before1[k], np.asarray(after1[k]))
            for k in before1)
        assert changed, "layer 1 params untouched: generator was exhausted"

    def test_params_usable_after_each_pretrain_step(self):
        # regression: donated buffers must be rebound per step, not at the
        # end, so an interrupted loop can't leave deleted arrays behind
        x, _ = _data(n=16)
        net = _net([
            AutoEncoder.Builder(nIn=12, nOut=4).build(),
            OutputLayer.Builder(nIn=4, nOut=2).build(),
        ])
        net.pretrainLayer(0, (x, None))
        out = net.output(x).numpy()  # must not raise "Array deleted"
        assert out.shape == (16, 2)

    def test_iwae_bound_tightens_with_samples(self):
        # log p(x) estimate: more samples -> estimate must not get worse
        # (IWAE bound is monotone in S in expectation)
        x, _ = _data(n=32)
        net = _net([
            VariationalAutoencoder.Builder(
                nIn=12, nOut=3, encoderLayerSizes=(10,),
                decoderLayerSizes=(10,)).build(),
            OutputLayer.Builder(nIn=3, nOut=2).build(),
        ])
        net.pretrainLayer(0, [(x, None)] * 30)
        import jax
        vae = net.layers[0]
        key = jax.random.key(11)
        lp1 = float(np.mean(np.asarray(vae.reconstruction_log_probability(
            net._params[0], x, key, num_samples=1))))
        lp64 = float(np.mean(np.asarray(vae.reconstruction_log_probability(
            net._params[0], x, key, num_samples=64))))
        assert lp64 >= lp1 - 0.5

    def test_global_activation_default_propagates(self):
        # regression: a builder-level .activation(...) must reach AE/VAE
        # (fallbacks apply only when NO global default exists)
        b = (NeuralNetConfiguration.Builder().activation("tanh").list()
             .layer(AutoEncoder.Builder(nIn=6, nOut=4).build())
             .layer(VariationalAutoencoder.Builder(
                 nIn=4, nOut=2, encoderLayerSizes=(5,),
                 decoderLayerSizes=(5,)).build())
             .layer(OutputLayer.Builder(nIn=2, nOut=2).build()))
        conf = b.build()
        assert conf.layers[0].activation == "tanh"
        assert conf.layers[1].activation == "tanh"
        # and without a global default the layer fallbacks hold
        conf2 = _net([
            AutoEncoder.Builder(nIn=6, nOut=4).build(),
            OutputLayer.Builder(nIn=4, nOut=2).build(),
        ]).conf
        assert conf2.layers[0].activation == "sigmoid"

    def test_bernoulli_distribution_honors_activation(self):
        # identity activation: decoder output IS the probability
        dist = BernoulliReconstructionDistribution(activation="identity")
        import jax.numpy as jnp
        x = jnp.asarray([[1.0, 0.0]])
        p = jnp.asarray([[0.9, 0.2]])
        lp = float(dist.log_prob(x, p)[0])
        assert lp == pytest.approx(np.log(0.9) + np.log(0.8), abs=1e-5)
        m = np.asarray(dist.sample_mean(p))
        assert np.allclose(m, np.asarray(p))
