"""Tests for serialization, listeners, early stopping, transfer learning
(reference test style: regression/serialization round-trips + trainer
behavior, SURVEY.md §4)."""

import os
import zipfile

import numpy as np

from deeplearning4j_tpu.nn import (
    ComputationGraph, DenseLayer, ElementWiseVertex, InputType,
    MultiLayerNetwork, NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.utils import (
    CheckpointListener, ClassificationScoreCalculator,
    CollectScoresIterationListener, DataSetLossCalculator,
    EarlyStoppingConfiguration, EarlyStoppingTrainer,
    MaxEpochsTerminationCondition, ModelSerializer,
    ScoreImprovementEpochTerminationCondition, ScoreIterationListener,
    TransferLearning)


def _xy(n=32, fin=10, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, fin)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return X, y


def _net(seed=1):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer.Builder().nIn(10).nOut(16).activation("relu")
                   .build())
            .layer(OutputLayer.Builder().nOut(3).activation("softmax")
                   .lossFunction("mcxent").build())
            .build())
    return MultiLayerNetwork(conf).init()


class TestModelSerializer:
    def test_write_restore_multilayer(self, tmp_path):
        net = _net()
        X, y = _xy()
        net.fit([(X, y)], 10)
        p = str(tmp_path / "model.zip")
        ModelSerializer.writeModel(net, p, saveUpdater=True)
        net2 = ModelSerializer.restoreMultiLayerNetwork(p)
        np.testing.assert_allclose(net.output(X).numpy(),
                                   net2.output(X).numpy(), rtol=1e-5)
        # updater state restored: continued training matches
        assert net2._iteration == net._iteration

    def test_restore_continues_training(self, tmp_path):
        net = _net()
        X, y = _xy()
        net.fit([(X, y)], 5)
        p = str(tmp_path / "model.zip")
        ModelSerializer.writeModel(net, p, saveUpdater=True)
        net2 = ModelSerializer.restoreMultiLayerNetwork(p, loadUpdater=True)
        net.fit([(X, y)], 5)
        net2.fit([(X, y)], 5)
        np.testing.assert_allclose(net.params().numpy(),
                                   net2.params().numpy(), rtol=1e-4,
                                   atol=1e-6)

    def test_restore_graph(self, tmp_path):
        conf = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.1))
                .graphBuilder()
                .addInputs("in")
                .addLayer("d", DenseLayer.Builder().nIn(10).nOut(8)
                          .activation("relu").build(), "in")
                .addLayer("out", OutputLayer.Builder().nIn(8).nOut(3)
                          .activation("softmax").lossFunction("mcxent")
                          .build(), "d")
                .setOutputs("out").build())
        g = ComputationGraph(conf).init()
        X, y = _xy()
        g.fit([(X, y)], 3)
        p = str(tmp_path / "graph.zip")
        ModelSerializer.writeModel(g, p)
        g2 = ModelSerializer.restoreComputationGraph(p)
        np.testing.assert_allclose(g.output(X)[0].numpy(),
                                   g2.output(X)[0].numpy(), rtol=1e-5)

    def test_wrong_kind_rejected(self, tmp_path):
        net = _net()
        p = str(tmp_path / "m.zip")
        ModelSerializer.writeModel(net, p)
        try:
            ModelSerializer.restoreComputationGraph(p)
            assert False, "should reject"
        except ValueError:
            pass

    def test_normalizer_embedding(self, tmp_path):
        from deeplearning4j_tpu.datasets import (
            DataSet, NormalizerStandardize)

        net = _net()
        p = str(tmp_path / "m.zip")
        ModelSerializer.writeModel(net, p)
        X, y = _xy()
        norm = NormalizerStandardize().fit(DataSet(X, y))
        ModelSerializer.addNormalizerToModel(p, norm)
        norm2 = ModelSerializer.restoreNormalizerFromFile(p)
        np.testing.assert_allclose(norm2.mean, norm.mean)
        # model still restorable after zip rewrite
        ModelSerializer.restoreMultiLayerNetwork(p)


class TestListeners:
    def test_score_listener_collects(self):
        net = _net()
        listener = CollectScoresIterationListener(frequency=1)
        net.setListeners(listener)
        X, y = _xy()
        net.fit([(X, y)], 5)
        assert len(listener.scores) == 5
        assert listener.scores[-1][1] < listener.scores[0][1] * 1.5

    def test_checkpoint_listener_rotates(self, tmp_path):
        net = _net()
        listener = CheckpointListener(str(tmp_path), saveEveryNIterations=2,
                                      keepLast=2)
        net.setListeners(listener)
        X, y = _xy()
        net.fit([(X, y)], 10)
        files = [f for f in os.listdir(tmp_path) if f.endswith(".zip")]
        assert len(files) == 2
        restored = ModelSerializer.restoreMultiLayerNetwork(
            listener.lastCheckpoint())
        assert restored.numParams() == net.numParams()


class TestEarlyStopping:
    def test_stops_at_max_epochs(self):
        net = _net()
        X, y = _xy(64)
        val_it = [(X, y)]
        cfg = (EarlyStoppingConfiguration.Builder()
               .epochTerminationConditions(MaxEpochsTerminationCondition(4))
               .scoreCalculator(DataSetLossCalculator(val_it))
               .build())
        result = EarlyStoppingTrainer(cfg, net, [(X, y)]).fit()
        assert result.totalEpochs == 4  # exactly maxEpochs epochs
        assert result.getBestModel() is not None
        assert result.terminationReason == "EpochTerminationCondition"

    def test_patience_stops_early(self):
        net = _net()
        X, y = _xy(32)
        cfg = (EarlyStoppingConfiguration.Builder()
               .epochTerminationConditions(
                   MaxEpochsTerminationCondition(200),
                   ScoreImprovementEpochTerminationCondition(3))
               .scoreCalculator(ClassificationScoreCalculator([(X, y)]))
               .build())
        result = EarlyStoppingTrainer(cfg, net, [(X, y)]).fit()
        assert result.totalEpochs < 200

    def test_best_model_is_best(self):
        net = _net()
        X, y = _xy(64)
        cfg = (EarlyStoppingConfiguration.Builder()
               .epochTerminationConditions(MaxEpochsTerminationCondition(5))
               .scoreCalculator(DataSetLossCalculator([(X, y)]))
               .build())
        result = EarlyStoppingTrainer(cfg, net, [(X, y)]).fit()
        best = result.getBestModel()
        assert abs(best.score((X, y)) - result.getBestModelScore()) < 1e-4


class TestTransferLearning:
    def test_freeze_feature_extractor(self):
        net = _net()
        X, y = _xy()
        net.fit([(X, y)], 5)
        frozen_w = net.getParam(0, "W").numpy().copy()
        new_net = (TransferLearning.Builder(net)
                   .setFeatureExtractor(0)
                   .build())
        new_net.fit([(X, y)], 5)
        np.testing.assert_allclose(new_net.getParam(0, "W").numpy(),
                                   frozen_w, rtol=1e-6)
        # unfrozen output layer did move
        assert not np.allclose(new_net.getParam(1, "W").numpy(),
                               net.getParam(1, "W").numpy())

    def test_nout_replace(self):
        net = _net()
        X, y = _xy()
        net.fit([(X, y)], 3)
        new_net = (TransferLearning.Builder(net)
                   .nOutReplace(1, 5)
                   .build())
        assert new_net.output(X).shape() == (32, 5)
        # layer 0 weights carried over
        np.testing.assert_allclose(new_net.getParam(0, "W").numpy(),
                                   net.getParam(0, "W").numpy(), rtol=1e-6)

    def test_replace_output_layer(self):
        net = _net()
        new_net = (TransferLearning.Builder(net)
                   .removeOutputLayer()
                   .addLayer(OutputLayer.Builder().nIn(16).nOut(7)
                             .activation("softmax").lossFunction("mcxent")
                             .build())
                   .build())
        X, _ = _xy()
        assert new_net.output(X).shape() == (32, 7)
        y7 = np.eye(7, dtype=np.float32)[
            np.random.default_rng(0).integers(0, 7, 32)]
        s0 = new_net.score((X, y7))
        new_net.fit([(X, y7)], 10)
        assert new_net.score((X, y7)) < s0
