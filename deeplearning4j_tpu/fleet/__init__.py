"""Fleet tier: a multi-process serving router (ISSUE 15 tentpole).

Everything below `serving/` runs ONE process: an InferenceSession, its
batchers/replicas, one UIServer. The production shape (ROADMAP item 3)
is a fleet — a thin router in front of N worker processes, each a full
UIServer + InferenceSession:

- :mod:`fleet.worker` — the worker process entry point
  (``python -m deeplearning4j_tpu.fleet.worker``): builds servables
  from a JSON spec, serves them on a UIServer, and exposes the
  versioned-registry admin seam (:register / :unregister) rollouts
  push through;
- :mod:`fleet.router` — :class:`FleetRouter`: spawns/adopts workers,
  polls their /healthz + load gauges, routes :predict/:decode to the
  least-loaded ready worker with a retry budget (a worker death never
  surfaces to the client), ejects on consecutive transport failures
  (the PR-8 circuit-breaker shape) and re-admits on recovered healthz;
- :mod:`fleet.rollout` — :class:`RolloutController`: canary a vN+1
  model spec on one worker, mirror a traffic fraction to it, compare
  p99 + output agreement against the incumbent via PR-1 histogram
  snapshots, then promote worker-by-worker or auto-roll back — every
  decision a flight event;
- :mod:`fleet.capture` — :class:`TrafficCapture`: head-sampled live
  requests into a replayable on-disk dataset
  (:class:`CaptureReplayIterator` is a DataSetIterator), the first hop
  of the train-from-traffic loop;
- :mod:`fleet.autopilot` — the closed loop (ISSUE 20):
  :class:`FleetFineTuner` trains from a saved capture at ``train``
  admission priority and publishes the checkpoint back through a
  canary rollout; :class:`Respawner` restarts dead spawned workers
  with bounded backoff; :class:`Autoscaler` sizes the fleet from
  sustained load, gated by the capacity planner; :class:`Autopilot`
  is the control thread that ties them together.

See docs/FLEET.md for the architecture and the rollout state machine.
"""

from deeplearning4j_tpu.fleet.autopilot import (
    Autopilot, Autoscaler, FleetFineTuner, Respawner)
from deeplearning4j_tpu.fleet.capture import (
    CaptureReplayIterator, TrafficCapture)
from deeplearning4j_tpu.fleet.rollout import (
    ROLLOUT_STATES, RolloutController)
from deeplearning4j_tpu.fleet.router import (
    FleetRouter, WorkerHandle, spawn_local_workers)

# fleet.worker is ALSO the `python -m deeplearning4j_tpu.fleet.worker`
# entry point: importing it eagerly here would make runpy warn (module
# in sys.modules before -m executes it), so its exports resolve lazily
_WORKER_EXPORTS = ("LinearServable", "WorkerAdmin", "build_servable")


def __getattr__(name):
    if name in _WORKER_EXPORTS:
        from deeplearning4j_tpu.fleet import worker

        return getattr(worker, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "Autopilot", "Autoscaler", "CaptureReplayIterator",
    "FleetFineTuner", "FleetRouter", "LinearServable",
    "ROLLOUT_STATES", "Respawner", "RolloutController",
    "TrafficCapture", "WorkerAdmin", "WorkerHandle", "build_servable",
    "spawn_local_workers",
]
