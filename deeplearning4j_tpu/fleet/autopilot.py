"""Closed-loop fleet: capture-driven fine-tuning, checkpoint rollouts,
and a self-driving fleet (ISSUE 20 tentpole).

PR 15 built the fleet's open loop: a router in front of N worker
processes, traffic capture, canary rollouts. This module closes it —
three cooperating controllers that turn the fleet from *operated* into
*self-operating*, each reusing an existing subsystem rather than
growing a new one:

- :class:`FleetFineTuner` — train **from** the fleet's own traffic,
  **on** the serving host, **back into** the fleet. A saved
  :class:`~deeplearning4j_tpu.fleet.capture.TrafficCapture` replays
  through ``CaptureReplayIterator`` (the served predictions are the
  distillation labels), the fit runs under the PR-5
  :class:`~deeplearning4j_tpu.resilience.supervisor.Supervisor` (crash
  = resume from checkpoint, not a lost job), and every training step
  holds a ``train``-class admission ticket — the PR-8 controller
  arbitrates trainer-vs-serving on the shared host, shedding the
  trainer FIRST so serving p99 degradation is bounded (and measured:
  ``bench.py --only fleet_loop``). On completion the newest checkpoint
  auto-publishes through ``router.start_rollout`` with the
  ``from_checkpoint`` spec kind, so the PR-15 canary machinery judges
  the fine-tuned model against its own parent before clients see it.

- :class:`Respawner` — a spawned worker that dies is restarted from
  its recorded spawn command with bounded exponential backoff (the
  supervisor's restart shape at process granularity). Every attempt is
  a ``worker_respawn`` flight event and a
  ``dl4j_fleet_respawns_total{worker,outcome}`` tick; the budget is
  TOTAL per worker (never reset on success), so a crash-looping binary
  gives up instead of flapping forever.

- :class:`Autoscaler` — desired fleet size from a sustained windowed
  request rate (the PR-16 timeseries ring) against per-worker
  capacity, gated by the PR-14 capacity planner
  (``memledger.plan_capacity`` — never spawn a worker the device
  cannot hold), with hysteresis (a direction must persist
  ``sustain_ticks`` consecutive ticks) and a post-action cooldown so
  flapping load does not flap workers. Decisions are ``autoscale``
  flight events; the target is the ``dl4j_fleet_target_workers``
  gauge.

:class:`Autopilot` owns the control loop: ONE daemon thread
(``dl4j:fleet:autopilot``) ticking the respawner and autoscaler;
``router.autopilot`` surfaces every controller's state on
``GET /debug/fleet``. Controllers also expose explicit ``tick()`` so
tests drive them deterministically without the thread.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time

from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.serving.admission import ShedError
from deeplearning4j_tpu.telemetry import flight

log = logging.getLogger(__name__)

__all__ = ["Autopilot", "Autoscaler", "FleetFineTuner", "Respawner",
           "ThrottledIterator"]

FINETUNE_STATES = ("idle", "training", "publishing", "complete",
                   "failed")


class ThrottledIterator(DataSetIterator):
    """A DataSetIterator that holds a ``train``-class admission ticket
    for the duration of every batch it hands out: the ticket is
    admitted before a batch is returned and released when the NEXT one
    is requested (or the epoch ends), so each in-flight training step
    occupies exactly one standing slot of the model's admission budget
    — the same ledger serving requests are admitted against. When the
    ``train`` class is over its share (serving load holds the budget),
    ``admit`` sheds and the iterator SLEEPS the computed retry_after
    and retries: training pauses, serving proceeds. That is the whole
    arbitration — no second scheduler."""

    def __init__(self, inner, admission, model, sleep=time.sleep,
                 max_wait=60.0):
        super().__init__(inner.batch())
        self.inner = inner
        self.admission = admission
        self.model = model
        self.sleep = sleep
        self.max_wait = float(max_wait)
        self.sheds = 0
        self._ticket = None

    def _release(self):
        if self._ticket is not None:
            self._ticket.release()
            self._ticket = None

    def _admit(self):
        deadline = time.monotonic() + self.max_wait
        while True:
            try:
                self._ticket = self.admission.admit(self.model,
                                                    priority="train")
                return
            except ShedError as e:
                self.sheds += 1
                if time.monotonic() >= deadline:
                    raise
                self.sleep(min(e.retry_after, 1.0))

    def reset(self):
        self._release()
        self._peek = None
        self.inner.reset()

    def _next_batch(self):
        self._release()
        batch = self.inner._next_batch()
        if batch is None:
            return None
        self._admit()
        return batch

    def close(self):
        self._release()


def _incumbent_version(router, model) -> int:
    """Highest served version of ``model`` across live workers (the
    rollout's own incumbent-discovery rule)."""
    with router._lock:
        return max((m.get("version") or 0
                    for w in router.workers if w.up for m in w.models
                    if m.get("name") == model), default=0)


class FleetFineTuner:
    """Capture → fine-tune → publish, one job per instance.

    factory: zero-arg callable building the net to fine-tune when no
        checkpoint exists yet — typically loads the serving model's
        weights (first attempt only; restarts resume from checkpoint);
    capture_path: a saved TrafficCapture (rotated sets replay whole);
    checkpoint_dir: where the supervised fit checkpoints — its newest
        checkpoint is what gets published;
    admission: the worker-host AdmissionController to arbitrate
        against (None trains unthrottled — off-host training);
    spec_extra: merged into the published ``from_checkpoint`` spec
        (``example_shape`` etc.);
    rollout_kw: forwarded to ``router.start_rollout``. Fine-tuning
        legitimately CHANGES outputs, so ``min_agreement`` defaults to
        0.0 here — the canary is judged on errors and p99 (and SLO
        burn when configured), not on bit-agreement with its parent.
    """

    def __init__(self, router, model, capture_path, factory,
                 checkpoint_dir, admission=None, epochs=1,
                 batch_size=32, supervisor_config=None, spec_extra=None,
                 rollout_kw=None, sleep=time.sleep, **trainer_kw):
        self.router = router
        self.model = model
        self.capture_path = capture_path
        self.factory = factory
        self.checkpoint_dir = str(checkpoint_dir)
        self.admission = admission
        self.epochs = int(epochs)
        self.batch_size = int(batch_size)
        self.supervisor_config = supervisor_config
        self.spec_extra = dict(spec_extra or {})
        self.rollout_kw = dict(rollout_kw or {})
        self.sleep = sleep
        self.trainer_kw = trainer_kw
        self.state = "idle"
        self.error = None
        self.checkpoint = None
        self.published_version = None
        self.sheds = 0
        self._thread = threading.Thread(
            target=self._run_thread, daemon=True,
            name=f"dl4j:fleet:finetune-{model}")

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Run the job on its own daemon thread; ``join()`` to wait."""
        self._thread.start()
        return self

    def join(self, timeout=None):
        self._thread.join(timeout)
        return self

    def close(self):
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _run_thread(self):
        try:
            self.run()
        except Exception:
            log.exception("fine-tune job for %s failed", self.model)

    # -- the job -------------------------------------------------------------
    def run(self):
        """Synchronous capture → fit → publish. Returns the started
        RolloutController (the canary judges the result); raises and
        flips to ``failed`` when any stage does."""
        from deeplearning4j_tpu.fleet.capture import (
            CaptureReplayIterator)
        from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
        from deeplearning4j_tpu.resilience.supervisor import Supervisor

        self.state = "training"
        flight.record("finetune_start", model=self.model,
                      capture=str(self.capture_path),
                      epochs=self.epochs,
                      checkpoint_dir=self.checkpoint_dir)
        try:
            data = CaptureReplayIterator(self.capture_path,
                                         batch_size=self.batch_size,
                                         model=self.model)
            if data.totalExamples() == 0:
                raise ValueError(
                    f"capture {self.capture_path!r} holds no examples "
                    f"for model {self.model!r}")
            throttled = None
            if self.admission is not None:
                data = throttled = ThrottledIterator(
                    data, self.admission, self.model, sleep=self.sleep)
            sup = Supervisor(self.factory, self.checkpoint_dir,
                             config=self.supervisor_config,
                             sleep=self.sleep, **self.trainer_kw)
            try:
                sup.run(data, epochs=self.epochs)
            finally:
                if throttled is not None:
                    self.sheds = throttled.sheds
                    throttled.close()
            ckpt = ElasticTrainer.latest(self.checkpoint_dir)
            if ckpt is None:
                raise RuntimeError(
                    f"fine-tune finished but {self.checkpoint_dir!r} "
                    f"holds no checkpoint")
            self.checkpoint = ckpt
            self.state = "publishing"
            ctl = self._publish(ckpt)
        except BaseException as e:
            self.state = "failed"
            self.error = f"{type(e).__name__}: {e}"
            flight.record("finetune_complete", model=self.model,
                          outcome="failed", error=self.error)
            raise
        self.state = "complete"
        flight.record("finetune_complete", model=self.model,
                      outcome="ok", checkpoint=self.checkpoint,
                      version=self.published_version,
                      train_sheds=self.sheds)
        return ctl

    def _publish(self, ckpt):
        version = _incumbent_version(self.router, self.model) + 1
        spec = {"kind": "from_checkpoint", "checkpoint": ckpt,
                **self.spec_extra}
        kw = dict(self.rollout_kw)
        kw.setdefault("min_agreement", 0.0)
        flight.record("finetune_publish", model=self.model,
                      checkpoint=ckpt, version=version)
        ctl = self.router.start_rollout(self.model, spec, version, **kw)
        self.published_version = version
        return ctl

    def describe(self) -> dict:
        return {"model": self.model, "state": self.state,
                "capture": str(self.capture_path),
                "checkpoint": self.checkpoint,
                "published_version": self.published_version,
                "train_sheds": self.sheds, "error": self.error}


class Respawner:
    """Restart dead SPAWNED workers from their recorded spawn command.

    Only workers carrying a spawn record (``WorkerHandle.spawn``, set
    by ``spawn_local_workers``) are eligible — an adopted URL has no
    process to restart. Backoff follows the supervisor's shape
    (``SupervisorConfig.backoff``); the attempt budget is TOTAL per
    worker and never resets, so a binary that keeps crashing is given
    up on (outcome ``gave_up``) rather than respawned forever. The
    router's existing poll loop readmits a respawned worker once its
    /healthz answers — respawning and readmission stay two separate
    judgements, same as startup."""

    def __init__(self, router, config=None, max_respawns=3,
                 spawn_timeout=30.0, clock=time.monotonic, popen=None):
        from deeplearning4j_tpu.resilience.supervisor import (
            SupervisorConfig)

        self.router = router
        self.config = config or SupervisorConfig()
        self.max_respawns = int(max_respawns)
        self.spawn_timeout = float(spawn_timeout)
        self.clock = clock
        self._popen = popen
        self._state: dict = {}   # worker -> {attempts, next_at, gave_up}

    def _worker_state(self, name):
        st = self._state.get(name)
        if st is None:
            st = self._state[name] = {"attempts": 0, "next_at": 0.0,
                                      "gave_up": False}
        return st

    def tick(self) -> list:
        """One control round: respawn every eligible dead worker whose
        backoff has elapsed. Returns [(worker, outcome)] for the
        attempts made this round."""
        out = []
        if self.router._stop.is_set():
            # the router is tearing down: close() is terminating the
            # very processes a respawn would resurrect — a revived
            # worker here outlives the fleet as an orphan
            return out
        for w in list(self.router.workers):
            if w.proc is None or w.spawn is None:
                continue
            if w.proc.poll() is None:
                continue   # alive
            st = self._worker_state(w.name)
            if st["gave_up"] or self.clock() < st["next_at"]:
                continue
            if st["attempts"] >= self.max_respawns:
                st["gave_up"] = True
                self._note(w, "gave_up", st["attempts"])
                out.append((w.name, "gave_up"))
                continue
            st["attempts"] += 1
            try:
                self._respawn(w)
                outcome = "ok"
            except Exception as e:
                outcome = "failed"
                log.warning("respawn of %s failed: %s", w.name, e)
            st["next_at"] = self.clock() \
                + self.config.backoff(st["attempts"])
            self._note(w, outcome, st["attempts"])
            out.append((w.name, outcome))
        return out

    def _respawn(self, w):
        import subprocess

        spawn = w.spawn
        try:
            os.remove(spawn["port_file"])
        except OSError:
            pass
        popen = self._popen or subprocess.Popen
        proc = popen(spawn["cmd"], env=spawn["env"])
        deadline = time.monotonic() + self.spawn_timeout
        port = None
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"respawned worker {w.name} exited "
                    f"rc={proc.returncode} before binding a port")
            try:
                with open(spawn["port_file"]) as f:
                    port = int(f.read().strip())
                break
            except (OSError, ValueError):
                time.sleep(0.05)
        if port is None:
            proc.kill()
            raise TimeoutError(
                f"respawned worker {w.name} never bound a port "
                f"within {self.spawn_timeout}s")
        with self.router._lock:
            w.proc = proc
            w.url = f"http://127.0.0.1:{port}"

    def _note(self, w, outcome, attempt):
        flight.record("worker_respawn", worker=w.name, outcome=outcome,
                      attempt=attempt, max_respawns=self.max_respawns)
        inst = self.router._inst()
        if inst is not None:
            inst.respawn(w.name, outcome)
        lvl = log.info if outcome == "ok" else log.warning
        lvl("fleet worker %s respawn attempt %d: %s", w.name, attempt,
            outcome)

    def describe(self) -> dict:
        return {"max_respawns": self.max_respawns,
                "workers": {n: dict(st)
                            for n, st in self._state.items()}}


class Autoscaler:
    """Spawn/retire workers from sustained load.

    load_fn: zero-arg callable returning the current fleet request
        rate (requests/second); the default reads the PR-16 timeseries
        ring's windowed rate of ``load_key`` (None — sampler cold —
        reads as 0.0);
    worker_rps: one worker's capacity; the target size is
        ``ceil(load / worker_rps)`` clamped to [min_workers,
        max_workers];
    sustain_ticks: a target differing from the current size must hold
        for this many CONSECUTIVE ticks before any action (hysteresis);
    cooldown: seconds after an action during which no further action
        is taken (the just-changed fleet must show up in the window
        before being judged again);
    need_bytes: estimated device footprint of one more worker — gated
        through ``memledger.plan_capacity`` before every spawn, so the
        autoscaler never spawns what cannot be placed (decision
        ``blocked``);
    spawn_fn: ``(spec, name) -> WorkerHandle`` override for tests; the
        default shells out through ``spawn_local_workers``.

    One action per tick (a single spawn or retire) — small blast
    radius; convergence to a far target takes several sustained ticks
    by design. Scale-down prefers the newest autoscaler-spawned
    worker and never retires below ``min_workers``.
    """

    def __init__(self, router, spec, load_key, worker_rps,
                 min_workers=1, max_workers=4, sustain_ticks=3,
                 cooldown=10.0, window=None, need_bytes=0,
                 load_fn=None, spawn_fn=None, base_dir=None,
                 clock=time.monotonic):
        self.router = router
        self.spec = spec
        self.load_key = load_key
        self.worker_rps = float(worker_rps)
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.sustain_ticks = int(sustain_ticks)
        self.cooldown = float(cooldown)
        self.window = window
        self.need_bytes = int(need_bytes)
        self.load_fn = load_fn or self._timeseries_load
        self.spawn_fn = spawn_fn
        self.base_dir = base_dir
        self.clock = clock
        self.last_load = 0.0
        self.last_desired = None
        self.last_decision = None
        self._pending = None
        self._pending_ticks = 0
        self._cooldown_until = 0.0
        self._spawned = 0
        # ticks arrive from the autopilot thread AND direct callers;
        # two racing scale-ups would each spawn a same-named worker
        # and the loser's process would leak (add_worker refuses dupes)
        self._tick_lock = threading.Lock()

    def _timeseries_load(self) -> float:
        from deeplearning4j_tpu.telemetry import timeseries

        return timeseries.rate(self.load_key, self.window) or 0.0

    def desired(self, load) -> int:
        return max(self.min_workers,
                   min(self.max_workers,
                       int(math.ceil(load / self.worker_rps))))

    def tick(self):
        """One control round. Returns the decision taken this round
        (``scale_up`` / ``scale_down`` / ``blocked``) or None when the
        round held steady (satisfied, sustaining, or cooling down)."""
        with self._tick_lock:
            return self._tick()

    def _tick(self):
        load = float(self.load_fn())
        target = self.desired(load)
        self.last_load, self.last_desired = load, target
        inst = self.router._inst()
        if inst is not None:
            inst.target_workers.set(float(target))
        current = len(self.router.workers)
        if target == current:
            self._pending, self._pending_ticks = None, 0
            return None
        if self.clock() < self._cooldown_until:
            return None
        if self._pending != target:
            # direction (or magnitude) changed: restart the sustain
            # count — flapping load keeps resetting this and never acts
            self._pending, self._pending_ticks = target, 1
        else:
            self._pending_ticks += 1
        if self._pending_ticks < self.sustain_ticks:
            return None
        decision = self._act(target, current, load)
        if decision is not None and decision != "blocked":
            self._cooldown_until = self.clock() + self.cooldown
            self._pending, self._pending_ticks = None, 0
        self.last_decision = decision
        return decision

    def _act(self, target, current, load):
        from deeplearning4j_tpu.telemetry import memledger

        if target > current:
            name = f"auto{self._spawned}"
            try:
                memledger.plan_capacity(
                    "fleet:autoscale", self.need_bytes,
                    detail={"worker": name})
            except memledger.CapacityError as e:
                flight.record("autoscale", decision="blocked",
                              worker=name, load=round(load, 3),
                              desired=target, current=current,
                              error=str(e))
                log.warning("autoscale blocked by capacity planner: %s",
                            e)
                return "blocked"
            try:
                w = self._spawn(name)
            except Exception as e:
                flight.record("autoscale", decision="blocked",
                              worker=name, load=round(load, 3),
                              desired=target, current=current,
                              error=f"{type(e).__name__}: {e}")
                log.warning("autoscale spawn failed: %s", e)
                return "blocked"
            self._spawned += 1
            try:
                self.router.add_worker(w)
            except Exception:
                # never orphan the process we just spawned: a handle
                # the router refused has no owner to terminate it
                if w.proc is not None and w.proc.poll() is None:
                    w.proc.kill()
                raise
            flight.record("autoscale", decision="scale_up",
                          worker=w.name, load=round(load, 3),
                          desired=target, current=current + 1)
            return "scale_up"
        victim = self._victim()
        if victim is None:
            return None
        self.router.retire_worker(victim.name)
        flight.record("autoscale", decision="scale_down",
                      worker=victim.name, load=round(load, 3),
                      desired=target, current=current - 1)
        return "scale_down"

    def _spawn(self, name):
        if self.spawn_fn is not None:
            return self.spawn_fn(self.spec, name)
        from deeplearning4j_tpu.fleet.router import spawn_local_workers

        idx = int(name[len("auto"):])
        return spawn_local_workers(
            1, self.spec, base_dir=self.base_dir,
            name_prefix="auto", start_index=idx)[0]

    def _victim(self):
        with self.router._lock:
            if len(self.router.workers) <= self.min_workers:
                return None
            auto = [w for w in self.router.workers
                    if w.name.startswith("auto")]
            return (auto or self.router.workers)[-1]

    def describe(self) -> dict:
        return {"load": round(self.last_load, 3),
                "desired": self.last_desired,
                "current": len(self.router.workers),
                "min_workers": self.min_workers,
                "max_workers": self.max_workers,
                "worker_rps": self.worker_rps,
                "sustain_ticks": self.sustain_ticks,
                "pending": self._pending,
                "pending_ticks": self._pending_ticks,
                "cooldown_until": self._cooldown_until,
                "last_decision": self.last_decision}


class Autopilot:
    """The control loop that makes the fleet self-driving: one daemon
    thread ticking the :class:`Respawner` and :class:`Autoscaler` at
    ``interval``; fine-tune jobs run on their own threads and are only
    tracked here. ``start()`` attaches the autopilot to the router, so
    ``GET /debug/fleet`` shows every controller's live state."""

    def __init__(self, router, respawner=None, autoscaler=None,
                 interval=0.5):
        self.router = router
        self.respawner = respawner
        self.autoscaler = autoscaler
        self.interval = float(interval)
        self.finetuners: list = []
        self.ticks = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dl4j:fleet:autopilot")

    def start(self):
        self.router.autopilot = self
        self._thread.start()
        flight.record("autopilot_start",
                      respawner=self.respawner is not None,
                      autoscaler=self.autoscaler is not None,
                      interval=self.interval)
        return self

    def close(self, timeout=5.0):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        for ft in self.finetuners:
            ft.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def fine_tune(self, *args, **kw) -> FleetFineTuner:
        """Start a :class:`FleetFineTuner` job (its own thread) and
        track it for /debug/fleet."""
        ft = FleetFineTuner(self.router, *args, **kw)
        self.finetuners.append(ft)
        return ft.start()

    def tick(self):
        """One explicit control round (what the thread does each
        interval) — deterministic handle for tests."""
        self.ticks += 1
        if self.respawner is not None:
            try:
                self.respawner.tick()
            except Exception:
                log.exception("respawner tick failed")
        if self.autoscaler is not None:
            try:
                self.autoscaler.tick()
            except Exception:
                log.exception("autoscaler tick failed")

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.tick()

    def describe(self) -> dict:
        out = {"interval": self.interval, "ticks": self.ticks,
               "running": self._thread.is_alive()}
        if self.respawner is not None:
            out["respawner"] = self.respawner.describe()
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.describe()
        if self.finetuners:
            out["finetune"] = [ft.describe() for ft in self.finetuners]
        return out
