"""Fleet worker: one serving process of the fleet tier (ISSUE 15).

A worker is deliberately nothing new — a full :class:`InferenceSession`
behind a full :class:`UIServer`, exactly the single-process stack every
prior PR built, plus two fleet seams:

- **spec-built models**: the router (and its rollouts) cannot ship live
  Python objects across the process boundary, so models arrive as JSON
  specs and :func:`build_servable` turns a spec into a servable in the
  worker process. ``kind: "mlp"`` builds a real jitted
  MultiLayerNetwork (cold start hits the PR-13 compile store);
  ``kind: "linear"`` is the deterministic host-side stand-in the fleet
  tests and the router-overhead bench lean on (y = scale·x + bias,
  optional injected service delay — the knob a deliberately-regressed
  canary uses); ``kind: "sharded"`` (ISSUE 19) builds a GSPMD
  mesh-partitioned servable over ``model_parallel`` of the worker's
  devices (spec key ``host_devices`` forces N virtual CPU devices at
  process start), serving models bigger than one device behind the
  same router, health polling, and canary machinery;
- **the admin surface**: :class:`WorkerAdmin` exposes the versioned
  re-register seam (``POST /serving/v1/models/<name>:register`` /
  ``:unregister`` on the worker's UIServer, serving/http.py) that
  rolling updates push vN+1 specs through and rollbacks retract them.

Run one with::

    python -m deeplearning4j_tpu.fleet.worker \
        --spec spec.json --port 0 --port-file /tmp/w0.port

The worker writes its bound port to ``--port-file`` (tmp + rename, so a
reader never sees a half-written file) once the server is up, then
serves until SIGTERM/SIGINT.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import threading
import time

import numpy as np

from deeplearning4j_tpu.serving.servable import Servable, as_servable

log = logging.getLogger("deeplearning4j_tpu")


class LinearServable(Servable):
    """Deterministic host-side servable: ``y = scale * x + bias`` in
    float32, with an optional per-dispatch service delay. No device
    work, no compile — which makes it exactly the model the fleet tier
    wants for measuring its OWN overhead (the router hop must be
    measured against a ~free model, PAPERS.md off-math-path rule) and
    for bit-identical canary agreement checks across processes."""

    def __init__(self, example_shape=(4,), scale=1.0, bias=0.0,
                 delay_ms=0.0):
        super().__init__(example_shape, dtype=np.float32)
        self.scale = float(scale)
        self.bias = float(bias)
        self.delay_s = float(delay_ms) / 1e3

    def warmup(self, ladder):
        return []   # nothing to compile

    def infer(self, x):
        if self.delay_s:
            time.sleep(self.delay_s)
        x = np.ascontiguousarray(x, dtype=np.float32)
        return (x * np.float32(self.scale)
                + np.float32(self.bias)).astype(np.float32)


def _build_linear(spec):
    return LinearServable(
        example_shape=tuple(spec.get("example_shape", (4,))),
        scale=spec.get("scale", 1.0), bias=spec.get("bias", 0.0),
        delay_ms=spec.get("delay_ms", 0.0))


def _build_mlp(spec):
    """A real jitted network (the production worker path — its cold
    warmup exercises the PR-13 executable store end to end)."""
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)

    n_in = int(spec.get("n_in", 8))
    n_out = int(spec.get("n_out", 4))
    width = int(spec.get("width", 16))
    b = (NeuralNetConfiguration.Builder().seed(int(spec.get("seed", 7)))
         .list()
         .layer(DenseLayer.Builder().nIn(n_in).nOut(width)
                .activation("tanh").build())
         .layer(OutputLayer.Builder().nOut(n_out).activation("softmax")
                .lossFunction(LossFunction.MCXENT).build()))
    net = MultiLayerNetwork(b.build()).init()
    return as_servable(net, (n_in,), None)


def _build_sharded(spec):
    """A GSPMD mesh-sharded servable (ISSUE 19): a column-parallel MLP
    partitioned over ``model_parallel`` devices. The worker process
    builds its own mesh from its own visible devices — on CPU the spec
    sets ``host_devices`` and main() forces the virtual device count
    BEFORE the first backend touch. Bit-identical to the ``mlp``-style
    single-device reference by construction (serving/sharded.py), so
    canary agreement checks work across sharded and unsharded groups."""
    import jax

    from deeplearning4j_tpu.parallel.mesh import MeshConfig
    from deeplearning4j_tpu.serving.sharded import sharded_mlp_servable

    tp = int(spec.get("model_parallel", 2))
    devices = jax.devices()
    if len(devices) < tp:
        raise ValueError(
            f"sharded spec wants model_parallel={tp} but the worker "
            f"sees only {len(devices)} device(s); set host_devices in "
            f"the spec (CPU) or run on a bigger slice")
    mesh = MeshConfig(data=1, model=tp, devices=devices[:tp]).build()
    sizes = tuple(int(s) for s in spec.get(
        "sizes", (int(spec.get("n_in", 8)), int(spec.get("width", 32)),
                  int(spec.get("n_out", 4)))))
    return sharded_mlp_servable(
        mesh, sizes, example_shape=(sizes[0],),
        seed=int(spec.get("seed", 7)),
        batch_axis=spec.get("batch_axis"))


def _build_from_checkpoint(spec):
    """ISSUE 20: serve a trained checkpoint — the fleet fine-tuner's
    publish seam. ``checkpoint`` names a ModelSerializer zip (or a
    sharded checkpoint directory); ``checkpoint_dir`` picks the newest
    COMPLETE checkpoint in an ElasticTrainer directory instead. The
    restored net warms through the PR-13 compile store exactly like an
    ``mlp`` spec (NetworkServable's program digest is the net's own
    conf), so a fine-tuned canary costs zero XLA compiles on a warm
    host."""
    from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
    from deeplearning4j_tpu.utils.serializer import ModelSerializer

    path = spec.get("checkpoint")
    if path is None:
        cdir = spec.get("checkpoint_dir")
        if not cdir:
            raise ValueError('from_checkpoint spec needs "checkpoint" '
                             '(a zip / sharded dir) or "checkpoint_dir"'
                             ' (an ElasticTrainer directory)')
        path = ElasticTrainer.latest_agreed(cdir)
        if path is None:
            raise ValueError(f"no complete checkpoint under {cdir!r}")
    if not os.path.exists(path):
        raise ValueError(f"checkpoint {path!r} does not exist")
    # the updater is training state — a servable only needs params
    net = ModelSerializer.restoreMultiLayerNetwork(
        path, loadUpdater=False, sharded=os.path.isdir(path))
    shape = tuple(int(s) for s in spec.get("example_shape", ())) or None
    return as_servable(net, shape, None)


def _build_decoder(spec):
    """A seeded paged-KV transformer decode model (ISSUE 20 decode
    mirroring): identical seeds build bit-identical params in every
    worker process, and greedy decode is argmax — so a canary's token
    streams match the incumbent's EXACTLY unless the weights differ,
    which is the agreement oracle decode rollouts judge on."""
    from deeplearning4j_tpu.serving.decode import TransformerDecodeModel

    return TransformerDecodeModel.init(
        vocab=int(spec.get("vocab", 32)),
        hidden=int(spec.get("hidden", 16)),
        n_layers=int(spec.get("n_layers", 1)),
        n_heads=int(spec.get("n_heads", 2)),
        max_len=int(spec.get("max_len", 64)),
        seed=int(spec.get("seed", 0)),
        max_slots=int(spec.get("max_slots", 4)),
        page=int(spec.get("page", 8)),
        max_pages_per_slot=int(spec.get("max_pages_per_slot", 8)))


SPEC_BUILDERS = {"linear": _build_linear, "mlp": _build_mlp,
                 "sharded": _build_sharded,
                 "from_checkpoint": _build_from_checkpoint}

# decoder specs register through session.register_decoder (continuous
# batching engine) instead of the versioned predict registry
DECODER_SPEC_BUILDERS = {"decoder": _build_decoder}


def build_servable(spec) -> Servable:
    """A Servable from a JSON-able spec dict: ``{"kind": ..., ...}``.
    Raises ValueError on an unknown kind (HTTP 400 at the admin
    route)."""
    if not isinstance(spec, dict):
        raise ValueError(f"model spec must be a dict, got {type(spec)}")
    kind = spec.get("kind")
    builder = SPEC_BUILDERS.get(kind)
    if builder is None:
        raise ValueError(
            f"unknown model-spec kind {kind!r}; choose from "
            f"{sorted(SPEC_BUILDERS) + sorted(DECODER_SPEC_BUILDERS)}")
    return builder(spec)


class WorkerAdmin:
    """The worker-side half of the rollout seam: registers/unregisters
    spec-built model versions on the worker's InferenceSession.
    Attached to a UIServer via ``serveFleetAdmin`` — the router's
    RolloutController talks to it over
    ``POST /serving/v1/models/<name>:register`` / ``:unregister``."""

    def __init__(self, session):
        self.session = session

    def register_spec(self, name, spec, version, warmup=True):
        if isinstance(spec, dict) and \
                spec.get("kind") in DECODER_SPEC_BUILDERS:
            return self._register_decoder(name, spec, version,
                                          warmup=warmup)
        sv = build_servable(spec)
        kw = {}
        ladder = spec.get("ladder")
        if ladder:
            kw["ladder"] = tuple(int(b) for b in ladder)
        return self.session.register(name, sv, version=int(version),
                                     warmup=bool(warmup), **kw)

    def _register_decoder(self, name, spec, version, warmup=True):
        """Decoder specs (ISSUE 20 decode mirroring) attach a
        continuous-batching DecodeEngine under ``name`` — decoders are
        UNVERSIONED in the session, so rollouts canary them under an
        alias name (``m@v2``) and promotion re-registers the bare name
        (see fleet/rollout.py). Returns a registry-entry-shaped result
        for the :register route's response."""
        import types

        model = DECODER_SPEC_BUILDERS[spec["kind"]](spec)
        kw = {}
        if spec.get("chunk"):
            kw["chunk"] = int(spec["chunk"])
        engine = self.session.register_decoder(
            name, model, warmup=bool(warmup), **kw)
        return types.SimpleNamespace(version=int(version),
                                     warmed=engine._warmed)

    def unregister(self, name, version=None):
        if name in self.session._decoders:
            self.session.unregister_decoder(name)
            return
        self.session.registry.unregister(
            name, None if version is None else int(version))


def _write_port_file(path, port):
    """Commit the bound port via tmp + rename: the spawner polls this
    file and must never read a torn value."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(str(int(port)))
    os.replace(tmp, path)


def serve(spec, port=0, port_file=None, max_latency=0.0,
          admission_budget=None, stop_event=None):
    """Build the session from ``spec`` and serve until ``stop_event``
    is set (the testable core of main()). Returns the UIServer."""
    from deeplearning4j_tpu.serving import (
        AdmissionController, InferenceSession)
    from deeplearning4j_tpu.ui.server import UIServer

    admission = (None if admission_budget is None
                 else AdmissionController(default_budget=admission_budget))
    session = InferenceSession(max_latency=max_latency,
                               admission=admission)
    admin = WorkerAdmin(session)
    for m in spec.get("models", ()):
        admin.register_spec(m["name"], m, m.get("version", 1),
                            warmup=m.get("warmup", True))
    # fleet-wide SLOs (ISSUE 16): the spec can declare objectives and
    # tune the always-on time-series sampler — evaluation ticks ride
    # the sampler thread, breaches surface in the worker's /healthz
    # (degraded-not-503) and flight ring, which the router federates
    from deeplearning4j_tpu.telemetry import slo as slo_mod
    from deeplearning4j_tpu.telemetry import timeseries

    ts_spec = spec.get("timeseries") or {}
    timeseries.configure(
        interval=ts_spec.get("interval"),
        capacity=ts_spec.get("capacity"))
    for s in spec.get("slos", ()):
        slo_mod.declare(slo_mod.Slo(**s))
    timeseries.start()
    # continuous profiler (ISSUE 18): every worker samples its own
    # threads so the router's /debug/fleet/profile merge has per-worker
    # collapsed stacks to federate; spec-tunable, no-op (zero sampler
    # thread) while telemetry is disabled
    from deeplearning4j_tpu.telemetry import profiler

    prof_spec = spec.get("profiler") or {}
    profiler.configure(hz=prof_spec.get("hz"),
                       bucket_seconds=prof_spec.get("bucket_seconds"),
                       capacity=prof_spec.get("capacity"))
    profiler.start()
    # a fresh UIServer instance per worker process — the getInstance()
    # singleton is a same-process convenience the fleet must not share
    server = UIServer()
    server.serveModels(session).serveFleetAdmin(admin).start(port=port)
    if port_file:
        _write_port_file(port_file, server.port)
    log.info("fleet worker pid=%d serving on port %d", os.getpid(),
             server.port)
    if stop_event is not None:
        stop_event.wait()
        profiler.stop()
        timeseries.stop()
        server.stop()
        session.close()
    return server


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="fleet worker: UIServer + InferenceSession from a "
                    "JSON model spec")
    p.add_argument("--spec", required=True,
                   help="JSON file: {\"models\": [{name, version, "
                        "kind, ...}]}")
    p.add_argument("--port", type=int, default=0,
                   help="bind port (0 = OS-assigned)")
    p.add_argument("--port-file", default=None,
                   help="write the bound port here once serving")
    p.add_argument("--max-latency", type=float, default=0.0,
                   help="batcher coalescing window (seconds)")
    p.add_argument("--admission-budget", type=int, default=None,
                   help="attach an AdmissionController with this "
                        "per-model concurrency budget")
    args = p.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    # sharded workers on CPU (ISSUE 19): the spec can force N virtual
    # host devices for the mesh. XLA reads XLA_FLAGS lazily at first
    # backend init, and nothing above this line touches a device — so
    # setting it here (before serve() builds any servable) is in time.
    # A pre-set force (test harness, operator) wins over the spec.
    n_dev = spec.get("host_devices")
    flags = os.environ.get("XLA_FLAGS", "")
    if n_dev and "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{int(n_dev)}").strip()
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    serve(spec, port=args.port, port_file=args.port_file,
          max_latency=args.max_latency,
          admission_budget=args.admission_budget, stop_event=stop)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
