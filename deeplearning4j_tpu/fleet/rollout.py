"""Rolling updates with a regression-gated canary (ISSUE 15).

The versioned re-register seam has existed since PR 2 (re-register a
(name, version) = rolling update) and PR 8 swept replica specs through
it — but nothing DECIDED whether vN+1 deserved the traffic. This module
is that decision, as a small state machine:

    idle ──start()──► canary ──agree+p99 ok──► promoting ──► complete
                        │                         │
                        └──regression─────────────┴──► rolled_back

- **canary**: the spec is pushed to ONE worker through the admin route
  (``:register``). While canarying (and promoting), the router pins
  regular traffic for the model to the incumbent version — clients
  keep getting vN until the fleet-wide cutover, and mixed-version
  answers cannot happen mid-promotion. A configurable fraction of live
  :predict traffic is MIRRORED (deterministic 1-in-N, the PR-9
  head-sampling shape) to the canary worker pinned at vN+1 on a
  background thread — the client's latency never includes the mirror;
- **the verdict**: mirrored answers feed two PR-1 log-bucket Histograms
  (incumbent hop latency vs canary latency) and an output-agreement
  count (byte-equal JSON ``predictions``). After ``min_samples``
  mirrors: regression ⇔ canary p99 > ``p99_ratio`` × incumbent p99, or
  agreement < ``min_agreement``, or any mirror transport/HTTP errors
  beyond budget. Histogram p99 is read from bucket counts
  (:func:`histogram_quantile`) — the same snapshot shape Prometheus
  sees;
- **promote**: push the spec worker-by-worker (each must be up before
  its push), then unpin — the registry's newest-version default makes
  vN+1 live everywhere at once from the router's point of view;
- **rollback**: ``:unregister`` vN+1 from every worker that received
  it; the registry falls back to vN (newest remaining). Every
  transition and the final decision are flight events, and
  ``dl4j_fleet_rollout_state`` tracks the machine numerically.

Decode-path rollouts (ISSUE 20): a decoder spec (``kind`` in
``fleet.worker.DECODER_SPEC_BUILDERS``) is judged by the SAME machine
with three decode-specific bindings. Decoders are unversioned in the
session, so the canary engine registers under an ALIAS
(``name@v<version>``) on the canary worker while client ``:decode``
traffic keeps hitting the bare name — the alias is the pin, no body
rewriting needed (``pins()`` is always False for decode). Mirrored
requests replay the primary's prompt against the alias: agreement is
EXACT token-stream equality (greedy decode is argmax — identical
weights must produce identical streams), and latency is judged on
TTFT (the worker's ``Server-Timing: ttft`` phase; wall time when the
header is absent). Promotion registers the spec under the bare name on
every worker (replacing each engine at its next registration boundary)
then retracts the alias; rollback retracts only the alias — the
incumbent engines were never touched.
"""

from __future__ import annotations

import itertools
import json
import logging
import queue
import threading
import time

from deeplearning4j_tpu.telemetry import flight
from deeplearning4j_tpu.telemetry.registry import Histogram, log_buckets

log = logging.getLogger("deeplearning4j_tpu")

# gauge encoding for dl4j_fleet_rollout_state (docs/OBSERVABILITY.md)
ROLLOUT_STATES = {"idle": 0, "canary": 1, "promoting": 2,
                  "complete": 3, "rolled_back": -1}
_TERMINAL = ("complete", "rolled_back")

# finer than the default SECONDS_BUCKETS (per_decade=12 → 1.21× bound
# steps): the p99-vs-p99 verdict is quantized to bucket bounds, and a
# coarse ladder would alias a healthy canary into a "regression" one
# bucket up
_LATENCY_BUCKETS = log_buckets(1e-4, 10.0, per_decade=12)


def _spec_kind(spec) -> str:
    """``"decode"`` for decoder specs (judged on token streams + TTFT
    under an alias), ``"predict"`` otherwise."""
    from deeplearning4j_tpu.fleet.worker import DECODER_SPEC_BUILDERS

    if isinstance(spec, dict) and \
            spec.get("kind") in DECODER_SPEC_BUILDERS:
        return "decode"
    return "predict"


def histogram_quantile(hist, q=0.99):
    """The smallest bucket upper bound covering quantile ``q`` of a
    PR-1 cumulative Histogram — how Prometheus would read the same
    snapshot. 0.0 when empty; the top finite bound for +Inf-bucket
    observations."""
    total = hist.count
    if total == 0:
        return 0.0
    target = q * total
    acc = 0
    for bound, c in zip(hist.buckets, hist.counts):
        acc += c
        if acc >= target:
            return bound
    return hist.buckets[-1]


class RolloutController:
    """One rollout of ``spec`` as ``name`` version ``version`` across
    ``router``'s fleet. Built via :meth:`FleetRouter.start_rollout`."""

    def __init__(self, router, name, spec, version, fraction=0.25,
                 min_samples=20, p99_ratio=2.0, min_agreement=0.999,
                 max_mirror_errors=2, push_timeout=60.0, slo=None,
                 slo_burn_ratio=2.0):
        self.router = router
        self.name = name
        self.spec = spec
        self.version = int(version)
        self.fraction = float(fraction)
        self.min_samples = int(min_samples)
        self.p99_ratio = float(p99_ratio)
        self.min_agreement = float(min_agreement)
        self.max_mirror_errors = int(max_mirror_errors)
        self.push_timeout = float(push_timeout)
        # SLO-burn judgment (ISSUE 16): with a declared latency
        # objective (telemetry.slo.Slo), the canary is ALSO judged by
        # how fast it burns that budget relative to the incumbent —
        # a canary can pass the p99-ratio gate while pushing the tail
        # past the threshold the operators actually promised
        if slo is not None and slo.kind != "latency":
            raise ValueError("rollout SLO judgment needs a latency SLO")
        self.slo = slo
        self.slo_burn_ratio = float(slo_burn_ratio)
        self.kind = _spec_kind(spec)
        # decode canaries live under an alias name (decoders are
        # unversioned in the session — the alias IS the version pin)
        self.mirror_name = (f"{name}@v{int(version)}"
                            if self.kind == "decode" else name)
        self.state = "idle"
        self.history = ["idle"]
        self.incumbent_version = None
        self.canary = None          # WorkerHandle
        self.pushed = []            # worker names serving vN+1
        self.decision = None
        self._mirrors = 0
        self._agree = 0
        self._errors = 0
        self._hist_incumbent = Histogram(
            "rollout_incumbent_seconds", buckets=_LATENCY_BUCKETS)
        self._hist_canary = Histogram(
            "rollout_canary_seconds", buckets=_LATENCY_BUCKETS)
        self._counter = itertools.count()
        self._interval = max(1, round(1.0 / max(self.fraction, 1e-6)))
        self._lock = threading.Lock()
        self._closing = threading.Event()
        self._queue = queue.Queue(maxsize=64)
        self._thread = threading.Thread(
            target=self._mirror_loop, daemon=True,
            name=f"dl4j:fleet:mirror-{name}")

    # -- state ---------------------------------------------------------------
    def terminal(self) -> bool:
        return self.state in _TERMINAL

    def _set_state(self, state):
        with self._lock:
            self.state = state
            self.history.append(state)
        flight.record("rollout_state", model=self.name,
                      version=self.version, state=state)
        inst = self.router._inst()
        if inst is not None:
            inst.rollout_state.set(ROLLOUT_STATES[state])

    def pins(self, name) -> bool:
        """While canarying/promoting, regular traffic for the rollout
        model stays pinned to the incumbent version. Decode rollouts
        never pin — the canary lives under its alias, so bare-name
        traffic cannot reach it."""
        return (self.kind == "predict" and name == self.name
                and self.state in ("canary", "promoting")
                and self.incumbent_version is not None)

    def pin_body(self, body):
        """Add ``"version": incumbent`` to an unpinned request body.
        An explicit client pin — and anything unparsable — passes
        through untouched."""
        try:
            payload = json.loads(body or b"")
        except (ValueError, UnicodeDecodeError):
            return body
        if not isinstance(payload, dict) or "version" in payload:
            return body
        payload["version"] = self.incumbent_version
        return json.dumps(payload).encode()

    # -- admin pushes --------------------------------------------------------
    def _push(self, w, name=None):
        from deeplearning4j_tpu.fleet.router import _http

        body = json.dumps({"spec": self.spec, "version": self.version,
                           "warmup": True}).encode()
        status, _, rb = _http(
            f"{w.url}/serving/v1/models/{name or self.mirror_name}"
            f":register", body=body, timeout=self.push_timeout)
        if status != 200:
            raise RuntimeError(
                f"push to {w.name} failed: HTTP {status} "
                f"{rb[:200]!r}")

    def _retract(self, w, name=None):
        from deeplearning4j_tpu.fleet.router import (
            TransportFailure, _http)

        body = json.dumps({"version": self.version}).encode()
        try:
            _http(f"{w.url}/serving/v1/models/"
                  f"{name or self.mirror_name}:unregister",
                  body=body, timeout=self.push_timeout)
        except TransportFailure:
            pass   # a dead worker has nothing serving to retract

    def start(self):
        """Push to the canary worker and open the mirror window."""
        from deeplearning4j_tpu.fleet.router import TransportFailure

        with self.router._lock:
            live = [w for w in self.router.workers if w.up]
            incumbent = max(
                (m.get("version") or 0 for w in live for m in w.models
                 if m.get("name") == self.name), default=0)
        if not live:
            raise RuntimeError("no live worker to canary on")
        if self.kind == "decode":
            # decoders are unversioned and absent from the polled model
            # lists — the version is bookkeeping (it names the alias),
            # the incumbent is whatever engine serves the bare name
            incumbent = max(self.version - 1, 0)
        elif incumbent < 1:
            raise RuntimeError(
                f"model {self.name!r} is not served by any live "
                f"worker — nothing to roll out against")
        elif self.version <= incumbent:
            raise ValueError(
                f"rollout version {self.version} must exceed the "
                f"incumbent v{incumbent}")
        self.incumbent_version = incumbent
        self.canary = live[0]
        # enter the pinning state BEFORE the push: registration on the
        # canary worker makes vN+1 its newest version immediately, and
        # an unpinned client request routed there during the push/
        # warmup window would otherwise be served the canary build
        # before the rollout has even started judging it
        self._set_state("canary")
        try:
            self._push(self.canary)
        except (TransportFailure, RuntimeError) as e:
            self._rollback(f"canary push failed: {e}", self._stats())
            raise
        self.pushed = [self.canary.name]
        self._thread.start()
        flight.record("rollout_start", model=self.name,
                      version=self.version,
                      incumbent=self.incumbent_version,
                      canary=self.canary.name, fraction=self.fraction,
                      min_samples=self.min_samples)
        return self

    def close(self):
        self._closing.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self.push_timeout)

    # -- mirroring -----------------------------------------------------------
    def on_primary(self, name, body, response_body, latency,
                   kind="predict", ttft=None):
        """Router hot-path hook after a successful :predict/:decode:
        enqueue every Nth request for mirroring. Never blocks — a full
        mirror queue drops the sample (bounded, like the trace ring).
        For decode traffic ``ttft`` (the worker's Server-Timing phase)
        is the judged latency; the whole-hop ``latency`` is the
        fallback when the worker reported none."""
        if name != self.name or self.state != "canary" \
                or kind != self.kind:
            return
        if next(self._counter) % self._interval:
            return
        if kind == "decode" and ttft is not None:
            latency = ttft
        try:
            self._queue.put_nowait((body, response_body, latency))
        except queue.Full:
            pass

    def _mirror_loop(self):
        while not self._closing.is_set():
            try:
                item = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None or self.state != "canary":
                continue
            try:
                self._mirror_one(*item)
                if self.state == "canary" \
                        and self._mirrors >= self.min_samples:
                    self._decide()
            except Exception as e:
                # a dead mirror thread would wedge the rollout in
                # canary (and pin clients to vN forever): fail SAFE
                # by rolling back instead
                log.exception("rollout mirror loop failed")
                if not self.terminal():
                    self._rollback(f"mirror loop error: "
                                   f"{type(e).__name__}: {e}",
                                   self._stats())
            if self.terminal():
                return

    def _mirror_one(self, body, primary_body, primary_latency):
        from deeplearning4j_tpu.fleet.router import (
            TransportFailure, _http, _parse_server_timing)

        inst = self.router._inst()
        try:
            payload = json.loads(body)
            if self.kind == "predict":
                payload["version"] = self.version
            mirror_body = json.dumps(payload).encode()
        except (ValueError, UnicodeDecodeError, TypeError):
            return   # unparsable primary: not a comparison sample
        t0 = time.perf_counter()
        rh = {}
        try:
            status, rh, rb = _http(
                f"{self.canary.url}/serving/v1/models/"
                f"{self.mirror_name}:{self.kind}", body=mirror_body,
                timeout=self.router.request_timeout)
        except TransportFailure as e:
            status, rb = None, str(e).encode()
        dt = time.perf_counter() - t0
        if self.kind == "decode":
            # judged on TTFT, same as the primary (whole-hop wall time
            # would charge the canary for every generated token)
            st = next((v for k, v in rh.items()
                       if k.lower() == "server-timing"), None)
            dt = _parse_server_timing(st).get("ttft", dt)
        agree_key = "tokens" if self.kind == "decode" else "predictions"
        with self._lock:
            self._mirrors += 1
            if status != 200:
                self._errors += 1
                verdict = "error"
            else:
                self._hist_incumbent.observe(primary_latency)
                self._hist_canary.observe(dt)
                try:
                    agree = (json.loads(rb)[agree_key]
                             == json.loads(primary_body)[agree_key])
                except (ValueError, KeyError, TypeError):
                    agree = False
                if agree:
                    self._agree += 1
                verdict = "agree" if agree else "disagree"
        if inst is not None:
            inst.mirror(verdict)

    # -- the decision --------------------------------------------------------
    def _stats(self):
        with self._lock:
            compared = self._mirrors - self._errors
            out = {
                "mirrors": self._mirrors,
                "errors": self._errors,
                "agreement": (self._agree / compared if compared
                              else 0.0),
                "p99_incumbent": histogram_quantile(
                    self._hist_incumbent),
                "p99_canary": histogram_quantile(self._hist_canary),
            }
            if self.slo is not None:
                from deeplearning4j_tpu.telemetry.slo import (
                    histogram_burn)

                out["slo_burn_incumbent"] = round(histogram_burn(
                    self._hist_incumbent, self.slo.threshold,
                    self.slo.objective), 6)
                out["slo_burn_canary"] = round(histogram_burn(
                    self._hist_canary, self.slo.threshold,
                    self.slo.objective), 6)
            return out

    def _decide(self):
        s = self._stats()
        regressed = []
        if s["errors"] > self.max_mirror_errors:
            regressed.append(f"{s['errors']} mirror errors")
        if s["agreement"] < self.min_agreement:
            regressed.append(
                f"agreement {s['agreement']:.4f} < "
                f"{self.min_agreement}")
        # floor the incumbent p99 at one bucket so a ~0ms incumbent
        # cannot declare every canary a latency regression
        floor = max(s["p99_incumbent"], _LATENCY_BUCKETS[0])
        if s["p99_canary"] > self.p99_ratio * floor:
            regressed.append(
                f"p99 {s['p99_canary']:.4f}s > {self.p99_ratio}x "
                f"incumbent {s['p99_incumbent']:.4f}s")
        if self.slo is not None:
            # burn floored at 1.0: an incumbent comfortably inside its
            # budget (burn ~0) must not make every canary observation
            # above threshold an automatic rollback — the canary only
            # regresses by burning MORE than both the budget and
            # slo_burn_ratio x the incumbent's burn
            burn_floor = max(s["slo_burn_incumbent"], 1.0)
            if s["slo_burn_canary"] > self.slo_burn_ratio * burn_floor:
                regressed.append(
                    f"slo burn {s['slo_burn_canary']:.3f} > "
                    f"{self.slo_burn_ratio}x incumbent burn "
                    f"{s['slo_burn_incumbent']:.3f} "
                    f"({self.slo.name})")
        flight.record("rollout_decision", model=self.name,
                      version=self.version,
                      verdict="rollback" if regressed else "promote",
                      reasons=regressed, **s)
        if regressed:
            self._rollback("; ".join(regressed), s)
        else:
            self._promote(s)

    def _promote(self, stats):
        from deeplearning4j_tpu.fleet.router import TransportFailure

        self._set_state("promoting")
        # EVERY worker, not just the currently-up ones: skipping an
        # ejected worker and declaring "complete" would leave it
        # serving vN when it is readmitted — permanent version skew
        # with no reconciler. A fleet that cannot take the push
        # everywhere rolls back instead; retry when it is whole.
        if self.kind == "decode":
            # decode promotion replaces the BARE name everywhere — the
            # canary included: its alias engine is what was judged, the
            # bare-name engine is still the incumbent. Bare-name pushes
            # that already landed are final (the build passed judgement
            # before promotion began); a failed push only cleans up the
            # canary alias via the ordinary rollback path.
            with self.router._lock:
                rest = list(self.router.workers)
        else:
            with self.router._lock:
                rest = [w for w in self.router.workers
                        if w.name not in self.pushed]
        for w in rest:
            flight.record("rollout_promote", model=self.name,
                          version=self.version, worker=w.name)
            try:
                if self.kind == "decode":
                    self._push(w, name=self.name)
                else:
                    self._push(w)
            except (TransportFailure, RuntimeError) as e:
                self._rollback(f"promotion push to {w.name} "
                               f"failed: {e}", stats)
                return
            if self.kind != "decode":
                self.pushed.append(w.name)
        if self.kind == "decode":
            # drop the canary's judging alias; best-effort — a stale
            # alias is shadowed bookkeeping, not version skew
            try:
                self._retract(self.canary)
            except (TransportFailure, RuntimeError):
                log.warning("could not retract decode alias %s from %s",
                            self.mirror_name, self.canary.name)
            self.pushed = [w.name for w in rest]
        self.decision = {"verdict": "promote", **stats}
        self._set_state("complete")
        flight.record("rollout_complete", model=self.name,
                      version=self.version, workers=list(self.pushed),
                      **stats)

    def _rollback(self, reason, stats):
        self.decision = {"verdict": "rollback", "reason": reason,
                         **stats}
        # retract vN+1 BEFORE flipping terminal: the router unpins
        # only once every worker's newest version is vN again
        for wname in list(self.pushed):
            w = next((w for w in self.router.workers
                      if w.name == wname), None)
            if w is not None:
                self._retract(w)
        self._set_state("rolled_back")
        flight.record("rollout_rollback", model=self.name,
                      version=self.version, reason=reason,
                      restored=self.incumbent_version, **stats)

    def describe(self):
        return {"model": self.name, "kind": self.kind,
                "mirror_name": self.mirror_name,
                "version": self.version,
                "incumbent": self.incumbent_version,
                "state": self.state, "history": list(self.history),
                "canary": None if self.canary is None
                else self.canary.name,
                "pushed": list(self.pushed),
                "decision": self.decision, **self._stats()}
