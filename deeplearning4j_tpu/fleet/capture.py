"""Traffic capture: live requests → replayable training data
(ISSUE 15 — the first hop of the train-from-traffic loop, ROADMAP
item 3c).

The router samples successful ``:predict`` requests (deterministic
1-in-N head sampling, the PR-9 tracing shape — the keep/drop decision
is one modulo, an unsampled request costs one counter tick) into a
bounded in-memory ring. Each record keeps the request's ``instances``
AND the fleet's ``predictions`` — the served model's answers are free
distillation labels, which is what makes the capture a *dataset*
rather than a log.

``save()`` commits the ring as canonical JSONL (sorted keys, fixed
separators, tmp + os.replace) so the same ring always produces the
same bytes; :class:`CaptureReplayIterator` re-feeds a saved file as a
standard DataSetIterator whose arrays are bit-identical run to run —
JSON doubles round-trip exactly, and the float32 cast is the same cast
the serving path applied. Determinism is asserted in
tests/test_fleet.py (capture → save → replay → re-save byte-identical).

ISSUE 20: ``save(path, append=True)`` commits only records newer than
the last append (per-record ``seq`` high-water mark), so a
long-running loop can persist the ring continuously; ``max_bytes``
bounds the base file with a logrotate-style sweep
(``capture.jsonl.1`` newest rotated segment, higher suffixes older,
every move the same tmp/os.replace commit). :func:`load_capture` and
the replay iterator read a rotated set oldest-first, so replay of a
rotated capture stays bit-identical to an unrotated one.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
from collections import deque

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator


class TrafficCapture:
    """Bounded, head-sampled request ring. ``maybe_record`` is the
    router hot-path entry: one counter tick when not sampled; one JSON
    parse + one deque append when sampled. Never raises — a malformed
    body is the client's problem, not the capture's."""

    def __init__(self, sample_interval=1, max_records=1024):
        self.sample_interval = max(1, int(sample_interval))
        self.max_records = int(max_records)
        self._records: deque = deque(maxlen=self.max_records)
        self._counter = itertools.count()
        self._seq = itertools.count(1)
        self._sampled = 0
        self._saved_seq = 0   # append high-water mark (one target file)
        self._lock = threading.Lock()

    def maybe_record(self, model, body, response_body, inst=None):
        if next(self._counter) % self.sample_interval:
            return None
        try:
            payload = json.loads(body or b"")
            resp = json.loads(response_body or b"")
            instances = payload["instances"]
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            return None
        rec = {"model": model, "instances": instances,
               "predictions": resp.get("predictions"),
               "version": resp.get("version")}
        with self._lock:
            rec["seq"] = next(self._seq)
            self._records.append(rec)
            self._sampled += 1
        if inst is not None:
            inst.captured.inc()
        return rec

    def records(self) -> list:
        with self._lock:
            return list(self._records)

    def __len__(self):
        return len(self._records)

    def describe(self) -> dict:
        with self._lock:
            return {"sample_interval": self.sample_interval,
                    "max_records": self.max_records,
                    "sampled": self._sampled,
                    "buffered": len(self._records)}

    def save(self, path, append=False, max_bytes=None) -> str:
        """Commit the ring as canonical JSONL (sorted keys, fixed
        separators — the same ring always serializes to the same
        bytes) via tmp + os.replace, so a reader never sees a torn
        file.

        ``append=True`` commits only records newer than the previous
        append (the per-record ``seq`` is the high-water mark — a
        record evicted from the ring before a save is simply gone,
        the ring bound is the backpressure). ``max_bytes`` (append
        mode) rotates the base file logrotate-style before it would
        grow past the bound: ``path.1`` is the newest rotated
        segment, higher suffixes older. Every file movement is the
        same tmp + os.replace commit."""
        recs = self.records()
        if append:
            with self._lock:
                saved = self._saved_seq
            recs = [r for r in recs if r["seq"] > saved]
        lines = "".join(
            json.dumps(rec, sort_keys=True, separators=(",", ":"))
            + "\n" for rec in recs)
        existing = ""
        if append:
            try:
                with open(path) as f:
                    existing = f.read()
            except FileNotFoundError:
                existing = ""
            if max_bytes is not None and existing and \
                    len(existing) + len(lines) > int(max_bytes):
                _rotate(path)
                existing = ""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(existing)
            f.write(lines)
        os.replace(tmp, path)
        if append and recs:
            with self._lock:
                self._saved_seq = max(self._saved_seq,
                                      recs[-1]["seq"])
        return path


def _rotate(path):
    """Sweep ``path`` into the numbered set: existing ``path.N`` move
    to ``path.N+1`` (highest first, so nothing is clobbered), then the
    base file becomes ``path.1``."""
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    for i in range(n - 1, 0, -1):
        os.replace(f"{path}.{i}", f"{path}.{i + 1}")
    os.replace(path, f"{path}.1")


def capture_files(path) -> list:
    """The capture's file set in record order (oldest first): rotated
    segments ``path.N`` highest-N first, then the base file."""
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    files = [f"{path}.{i}" for i in range(n - 1, 0, -1)]
    if os.path.exists(path):
        files.append(path)
    return files


def load_capture(path) -> list:
    """The saved records, in capture order — a rotated set reads
    oldest segment first, so replay order (and therefore the replayed
    arrays) is identical to an unrotated save."""
    out = []
    for fp in (capture_files(path) or [path]):
        with open(fp) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out


class CaptureReplayIterator(DataSetIterator):
    """Replay a saved capture as a DataSetIterator: features are the
    captured ``instances``, labels the fleet's ``predictions``
    (distillation targets), both float32 — ready for
    ``net.fit(iterator)`` / ElasticTrainer on the training mesh.
    ``model=`` filters a multi-model capture; records missing
    predictions replay with ``labels=None``."""

    def __init__(self, path, batch_size=32, model=None,
                 dtype=np.float32):
        super().__init__(batch_size)
        self.path = path
        self.model = model
        recs = [r for r in load_capture(path)
                if model is None or r.get("model") == model]
        # one request = one or more examples; flatten in capture order
        feats, labels = [], []
        for r in recs:
            inst = r.get("instances") or []
            preds = r.get("predictions")
            feats.extend(inst)
            labels.extend(preds if preds is not None
                          else [None] * len(inst))
        self._batches = []
        for i in range(0, len(feats), batch_size):
            fb = np.asarray(feats[i:i + batch_size], dtype=dtype)
            lb = labels[i:i + batch_size]
            has_labels = all(l is not None for l in lb) and lb
            self._batches.append(
                (fb, np.asarray(lb, dtype=dtype) if has_labels
                 else None))
        self._pos = 0

    def reset(self):
        self._pos = 0
        self._peek = None

    def _next_batch(self):
        if self._pos >= len(self._batches):
            return None
        f, l = self._batches[self._pos]
        self._pos += 1
        return DataSet(f, l)

    def totalExamples(self) -> int:
        return sum(f.shape[0] for f, _ in self._batches)
