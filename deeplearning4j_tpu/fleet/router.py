"""FleetRouter: the thin HTTP front of the multi-process serving tier
(ISSUE 15 tentpole).

The router owns no model and runs no device work — it spawns (or
adopts) N worker processes, each a full UIServer + InferenceSession
(:mod:`fleet.worker`), and makes them one logical serving endpoint:

- **discovery**: a poll thread GETs each worker's ``/healthz``
  (readiness + the compile/memory/decoder sections prior PRs put
  there), ``/serving/v1/models`` (the merged model list the router
  re-serves), and ``/metrics`` (the ``dl4j_serving_replica_load`` /
  ``dl4j_serving_queue_depth`` gauges that feed load-aware picks);
- **routing**: ``POST /serving/v1/models/<name>:predict`` / ``:decode``
  forwards to the ready worker with the least (router-side in-flight,
  polled queue load). The request body and the worker's response pass
  through the hop unmodified — a 429's ``Retry-After`` and a 504's
  body reach the client byte-for-byte, and an upstream ``traceparent``
  is forwarded as-is so router + worker spans land in ONE trace;
- **death containment**: a transport failure (connection refused/reset,
  a SIGKILLed worker mid-batch) is retried on another worker within a
  retry budget — the client sees the survivor's answer, never the
  death. Consecutive transport failures trip the PR-8 circuit-breaker
  shape (:data:`FleetRouter.BREAKER`): the worker is ejected from
  routing and re-admitted when its ``/healthz`` reports ready again.
  Every ejection/readmission is a flight event;
- **observability**: ``dl4j_fleet_*`` metrics (docs/OBSERVABILITY.md),
  a ``/healthz`` fleet section (degraded — still HTTP 200 — while any
  worker is ejected), and ``GET /debug/fleet`` (workers, rollout state,
  capture stats);
- **federation** (ISSUE 16): one scrape of ``GET /debug/fleet/metrics``
  returns every live worker's families merged under a ``worker`` label
  (plus the router's own under ``worker="router"``),
  ``/debug/fleet/flight`` merges the worker flight rings with the
  router's, ordered by the events' wall-clock ``ts``, and
  ``/debug/fleet/traces?trace_id=`` fans out to the workers and
  returns the stitched cross-process span tree — the router's
  ``fleet.predict`` root plus the worker spans in ONE response;
- **hop decomposition** (ISSUE 16): workers answer predicts with a
  ``Server-Timing`` header (queue/execute from the per-request
  instruments they already capture); the router subtracts it from the
  measured hop to attribute the serialize+network+parse remainder,
  publishes ``dl4j_fleet_hop_seconds{phase}``, and attaches the phases
  to the ``fleet.predict`` span.

HTTP-policy note: worker HTTP *responses* (429 shed, 504 timeout, 400,
500) are answers, not failures — they pass through and never count
toward the breaker or the retry budget. Only transport-level failures
(the worker did not answer) are retried; predict/decode are
idempotent, so a retry never double-charges anything.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import re
import socket
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.telemetry import flight, tracing
from deeplearning4j_tpu.serving import http as shttp

log = logging.getLogger("deeplearning4j_tpu")

# response headers that cross the hop back to the client; everything
# hop-by-hop (Connection, Server, Date, Content-Length is recomputed)
# stays at the router
_PASS_HEADERS = ("retry-after", "traceparent", "content-type",
                 "server-timing")

# the hop phases dl4j_fleet_hop_seconds decomposes into: queue/execute
# are worker-reported (Server-Timing), worker_other is worker handler
# time outside both (parse + serialize inside the worker), transit is
# the remainder of the measured hop (router serialize + network + the
# worker's HTTP accept) attributed by subtraction
HOP_PHASES = ("queue", "execute", "worker_other", "transit")

# transport-level failure classes: the worker did not answer (refused,
# reset mid-read, timed out at connect). urllib's HTTPError is NOT here
# on purpose — that is a worker *answer* and passes through.
_TRANSPORT_ERRORS = (urllib.error.URLError, ConnectionError,
                     http.client.HTTPException, socket.timeout, OSError)


class TransportFailure(RuntimeError):
    """The worker did not produce an HTTP response (dead process,
    refused connection, reset mid-body). The only retryable class."""


class WorkerHandle:
    """Router-side record of one worker process. All mutable state is
    guarded by the router's single lock (the ReplicaSet discipline:
    one mutex keeps the lock-order rule trivially satisfiable)."""

    __slots__ = ("name", "url", "proc", "up", "ready", "consec_failures",
                 "inflight", "polled_load", "models", "last_health",
                 "ejected_at", "last_error", "spawn")

    def __init__(self, name, url, proc=None, spawn=None):
        self.name = name
        self.url = url.rstrip("/")
        self.proc = proc
        self.up = True
        self.ready = None         # unknown until the first healthz poll
        self.consec_failures = 0
        self.inflight = 0
        self.polled_load = 0.0
        self.models = []
        self.last_health = None
        self.ejected_at = None
        self.last_error = None
        # how to start this worker again: {"cmd": [...], "env": {...},
        # "port_file": path} recorded by spawn_local_workers — the
        # autopilot's Respawner relaunches a dead process from it
        self.spawn = spawn

    def describe(self):
        return {
            "url": self.url, "up": self.up, "ready": self.ready,
            "pid": None if self.proc is None else self.proc.pid,
            "consec_failures": self.consec_failures,
            "inflight": self.inflight, "load": self.polled_load,
            "ejected_at": self.ejected_at, "last_error": self.last_error,
        }


def _http(url, body=None, headers=None, timeout=10.0, method=None):
    """(status, headers dict, body bytes) for one worker call. Raises
    :class:`TransportFailure` when no HTTP response came back; a
    non-2xx response returns normally (pass-through semantics)."""
    req = urllib.request.Request(
        url, data=body, headers=dict(headers or {}),
        method=method or ("POST" if body is not None else "GET"))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers.items()), resp.read()
    except urllib.error.HTTPError as e:
        with e:
            return e.code, dict(e.headers.items()), e.read()
    except _TRANSPORT_ERRORS as e:
        raise TransportFailure(f"{type(e).__name__}: {e}") from None


def _parse_gauge_sum(text, name) -> float:
    """Sum of one gauge family's samples from a Prometheus text
    exposition (the router's cheap load probe — no client library)."""
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue   # a longer name sharing the prefix
        try:
            value = float(line.rsplit(None, 1)[1])
        except (ValueError, IndexError):
            continue
        if value >= 0:   # -1 = dead replica sentinel, not load
            total += value
    return total


def _parse_server_timing(value) -> dict:
    """``'queue;dur=0.123, execute;dur=4.5'`` -> phase seconds (dur is
    milliseconds per the Server-Timing spec). Unparseable entries are
    skipped — the header is advisory, never a failure."""
    out = {}
    for part in (value or "").split(","):
        fields = [f.strip() for f in part.strip().split(";")]
        if not fields or not fields[0]:
            continue
        for f in fields[1:]:
            if f.startswith("dur="):
                try:
                    out[fields[0]] = float(f[4:]) / 1e3
                except ValueError:
                    pass
    return out


def _inject_worker_label(line, worker) -> str:
    """One exposition sample line with ``worker="<name>"`` prepended to
    its label set (added as the only label when there is none). A
    pre-existing ``worker`` label (the router's own ``dl4j_fleet_*``
    families) renames to ``exported_worker`` — the Prometheus
    federation collision rule: the source label wins, the target's
    survives under ``exported_``."""
    brace = line.find("{")
    space = line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        end = line.find("}", brace)
        labels = re.sub(r'(^|,)worker="', r'\1exported_worker="',
                        line[brace + 1:end])
        return (line[:brace + 1] + f'worker="{worker}",' + labels
                + line[end:])
    if space == -1:
        return line
    return line[:space] + f'{{worker="{worker}"}}' + line[space:]


def _merge_expositions(sections) -> str:
    """[(worker, exposition_text)] -> ONE exposition with every sample
    under a ``worker`` label, grouped per family (the 0.0.4 format
    requires a family's lines contiguous; HELP/TYPE render once, from
    the first worker exporting the family). Two workers exporting the
    same family/labels stay distinct samples — the injected worker
    label disambiguates the collision."""
    fams: dict = {}      # family -> {"meta": [help, type], "lines": []}
    order: list = []
    for worker, text in sections:
        current = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                name = line.split(None, 3)[2]
                fam = fams.get(name)
                if fam is None:
                    fam = fams[name] = {"meta": [], "lines": []}
                    order.append(name)
                if line.startswith("# TYPE "):
                    current = name
                if len(fam["meta"]) < 2 and line not in fam["meta"]:
                    fam["meta"].append(line)
                continue
            if current is None:     # sample before any TYPE: family by
                sample = line.split("{", 1)[0].split(" ", 1)[0]  # name
                current = sample
                fams.setdefault(current, {"meta": [], "lines": []})
                if current not in order:
                    order.append(current)
            fams[current]["lines"].append(
                _inject_worker_label(line, worker))
    out = []
    for name in order:
        out.extend(fams[name]["meta"])
        out.extend(fams[name]["lines"])
    return "\n".join(out) + "\n"


def spawn_local_workers(n, spec, base_dir=None, timeout=60.0,
                        extra_env=None, admission_budget=None,
                        max_latency=0.0, name_prefix="w",
                        start_index=0):
    """Spawn N worker processes serving ``spec`` (a JSON-able dict,
    see fleet/worker.py), wait until every one reports a bound port
    AND a ready /healthz, and return their :class:`WorkerHandle` list.
    On any startup failure the already-started processes are killed."""
    import subprocess

    base_dir = base_dir or tempfile.mkdtemp(prefix="dl4j_fleet_")
    os.makedirs(base_dir, exist_ok=True)
    spec_path = os.path.join(base_dir, "fleet_spec.json")
    tmp = spec_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(spec, f)
    os.replace(tmp, spec_path)
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    handles, procs = [], []
    try:
        for j in range(int(n)):
            i = int(start_index) + j
            wname = f"{name_prefix}{i}"
            port_file = os.path.join(base_dir, f"{wname}.port")
            try:
                os.remove(port_file)
            except OSError:
                pass
            cmd = [sys.executable, "-m",
                   "deeplearning4j_tpu.fleet.worker",
                   "--spec", spec_path, "--port", "0",
                   "--port-file", port_file,
                   "--max-latency", str(max_latency)]
            if admission_budget is not None:
                cmd += ["--admission-budget", str(admission_budget)]
            procs.append((wname, port_file, cmd,
                          subprocess.Popen(cmd, env=env)))
        deadline = time.monotonic() + timeout
        for wname, port_file, cmd, proc in procs:
            port = None
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"fleet worker {wname} exited "
                        f"rc={proc.returncode} before binding a port")
                try:
                    with open(port_file) as f:
                        port = int(f.read().strip())
                    break
                except (OSError, ValueError):
                    time.sleep(0.05)
            if port is None:
                raise TimeoutError(f"fleet worker {wname} never bound "
                                   f"a port within {timeout}s")
            handles.append(WorkerHandle(
                wname, f"http://127.0.0.1:{port}", proc=proc,
                spawn={"cmd": list(cmd), "env": dict(env),
                       "port_file": port_file}))
        for w in handles:   # block until warmed: no cold compile in
            while True:     # any first request's latency path
                try:
                    status, _, body = _http(w.url + "/healthz",
                                            timeout=2.0)
                except TransportFailure:
                    status, body = 0, b""
                if status == 200:
                    w.ready = True
                    w.last_health = json.loads(body)
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"fleet worker {w.name} never became ready")
                time.sleep(0.05)
    except Exception:
        for _, _, _, proc in procs:
            proc.kill()
        raise
    return handles


class FleetRouter:
    """The fleet front door. ``workers`` is a list of
    :class:`WorkerHandle` (or bare base URLs to adopt). ``start()``
    binds the router's HTTP server and starts the poll thread;
    ``close()`` stops both (and terminates spawned worker processes
    when ``owns_workers``)."""

    # consecutive transport failures before a worker is ejected from
    # routing — the PR-8 replica breaker shape at process granularity
    # (a dead worker refuses instantly; without the breaker its ~0
    # in-flight count would keep attracting least-loaded picks)
    BREAKER = 3

    def __init__(self, workers, poll_interval=0.25, retry_budget=2,
                 request_timeout=60.0, poll_timeout=2.0, capture=None,
                 owns_workers=False):
        self.workers = [w if isinstance(w, WorkerHandle)
                        else WorkerHandle(f"w{i}", w)
                        for i, w in enumerate(workers)]
        if not self.workers:
            raise ValueError("a fleet needs at least one worker")
        self.poll_interval = float(poll_interval)
        self.retry_budget = int(retry_budget)
        self.request_timeout = float(request_timeout)
        self.poll_timeout = float(poll_timeout)
        self.capture = capture
        self.owns_workers = owns_workers
        self.port = None
        self._rollout = None
        self.autopilot = None     # attached by fleet/autopilot.py
        self._lock = threading.Lock()
        self._httpd = None
        self._thread = None
        self._poll_thread = None
        self._stop = threading.Event()
        self._instruments = None

    # -- telemetry -----------------------------------------------------------
    def _inst(self):
        """The bound FleetInstruments bundle, or None while telemetry
        is disabled (re-checked per call; the bundle builds once)."""
        if not telemetry.enabled():
            return None
        if self._instruments is None:
            self._instruments = telemetry.fleet_instruments()
        return self._instruments

    # -- lifecycle -----------------------------------------------------------
    def start(self, port=0):
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer(("127.0.0.1", int(port)),
                                          _RouterHandler)
        self._httpd.daemon_threads = True
        self._httpd.router = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dl4j:fleet:serve")
        self._thread.start()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, daemon=True,
            name="dl4j:fleet:poll")
        self._poll_thread.start()
        inst = self._inst()
        if inst is not None:
            for w in self.workers:
                inst.worker_up(w.name).set(1.0 if w.up else 0.0)
        # the always-on windowed-snapshot ring (ISSUE 16): router-side
        # SLOs burn over dl4j_fleet_* rates/quantiles; process-wide, so
        # close() deliberately leaves it running for other routers
        from deeplearning4j_tpu.telemetry import timeseries
        timeseries.start()
        # the continuous profiler (ISSUE 18): the router samples its
        # own serve/poll/mirror/handler threads so /debug/fleet/profile
        # covers the hop's router side, not just the workers; no-op
        # (zero sampler thread) while telemetry is disabled
        from deeplearning4j_tpu.telemetry import profiler
        profiler.start()
        flight.record("fleet_start", port=self.port,
                      workers=[w.name for w in self.workers])
        log.info("fleet router on http://127.0.0.1:%d (%d workers)",
                 self.port, len(self.workers))
        return self

    def close(self, timeout=5.0):
        self._stop.set()
        # stop an attached autopilot FIRST: a respawner still ticking
        # would resurrect the very worker processes terminated below
        # (the orphan then outlives the fleet)
        ap, self.autopilot = self.autopilot, None
        if ap is not None:
            try:
                ap.close()
            except Exception:
                log.exception("autopilot close failed")
        if self._rollout is not None:
            self._rollout.close()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if self._poll_thread is not None:
            self._poll_thread.join(timeout)
            self._poll_thread = None
        if self.owns_workers:
            for w in self.workers:
                if w.proc is not None and w.proc.poll() is None:
                    w.proc.terminate()
            for w in self.workers:
                if w.proc is not None:
                    try:
                        w.proc.wait(timeout)
                    except Exception:
                        w.proc.kill()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- worker state --------------------------------------------------------
    def _pick(self, tried):
        """The ready worker with the least load, excluding ``tried``;
        increments its in-flight count under the lock (the caller MUST
        pair with :meth:`_done`). ``ready is False`` (worker said it is
        warming/diverged) excludes; ``None`` (not yet polled) does not
        — a just-adopted fleet must route before its first poll."""
        with self._lock:
            live = [w for w in self.workers
                    if w.up and w.name not in tried
                    and w.ready is not False]
            if not live:
                return None
            w = min(live, key=lambda w: (w.inflight, w.polled_load,
                                         w.name))
            w.inflight += 1
            return w

    def _done(self, w):
        with self._lock:
            w.inflight -= 1

    def add_worker(self, w):
        """Adopt one more :class:`WorkerHandle` into routing (the
        autoscaler's scale-up seam). The poll loop picks it up on its
        next round; routing can use it immediately."""
        with self._lock:
            if any(x.name == w.name for x in self.workers):
                raise ValueError(f"worker {w.name!r} already in fleet")
            self.workers.append(w)
        inst = self._inst()
        if inst is not None:
            inst.worker_up(w.name).set(1.0 if w.up else 0.0)
        flight.record("worker_added", worker=w.name, url=w.url)
        log.info("fleet worker %s added (%s)", w.name, w.url)

    def retire_worker(self, name, timeout=5.0):
        """Remove a worker from routing and (when the router owns its
        process) terminate it — the autoscaler's scale-down seam.
        In-flight requests already routed to it finish on their own
        socket; new picks never see it."""
        with self._lock:
            w = next((x for x in self.workers if x.name == name), None)
            if w is None:
                raise ValueError(f"no such worker: {name!r}")
            self.workers.remove(w)
        inst = self._inst()
        if inst is not None:
            inst.worker_up(w.name).set(0.0)
        if self.owns_workers and w.proc is not None \
                and w.proc.poll() is None:
            w.proc.terminate()
            try:
                w.proc.wait(timeout)
            except Exception:
                w.proc.kill()
        flight.record("worker_retired", worker=w.name)
        log.info("fleet worker %s retired", w.name)
        return w

    def _note_transport_failure(self, w, err):
        """Breaker input: under the lock, bump the consecutive count
        and eject at the threshold (or instantly when the spawned
        process is dead — no point waiting out the breaker on a
        corpse)."""
        proc_dead = w.proc is not None and w.proc.poll() is not None
        with self._lock:
            w.consec_failures += 1
            w.last_error = str(err)
            eject = w.up and (proc_dead
                              or w.consec_failures >= self.BREAKER)
            if eject:
                w.up = False
                w.ready = None
                w.ejected_at = time.time()
        if eject:
            flight.record("worker_ejected", worker=w.name,
                          error=str(err),
                          consec_failures=w.consec_failures,
                          proc_dead=proc_dead)
            log.warning("fleet worker %s ejected (%s)", w.name, err)
            inst = self._inst()
            if inst is not None:
                inst.worker_up(w.name).set(0.0)

    def _note_success(self, w):
        with self._lock:
            w.consec_failures = 0

    def _readmit(self, w, payload):
        with self._lock:
            was_down = not w.up
            w.up = True
            w.ready = bool(payload.get("ready"))
            w.consec_failures = 0
            w.ejected_at = None
        if was_down:
            flight.record("worker_readmitted", worker=w.name)
            log.info("fleet worker %s readmitted", w.name)
            inst = self._inst()
            if inst is not None:
                inst.worker_up(w.name).set(1.0)

    # -- the poll thread -----------------------------------------------------
    def _poll_loop(self):
        while not self._stop.wait(self.poll_interval):
            for w in list(self.workers):
                if self._stop.is_set():
                    return
                self._poll_worker(w)

    def _poll_worker(self, w):
        try:
            status, _, body = _http(w.url + "/healthz",
                                    timeout=self.poll_timeout)
            payload = json.loads(body)
        except (TransportFailure, ValueError) as e:
            if w.up:
                self._note_transport_failure(w, e)
            return
        # /healthz answered: 200 = ready, 503 = live but warming or
        # diverged — the worker stays routable-on-recovery either way
        if status == 200:
            self._readmit(w, payload)
        else:
            with self._lock:
                w.ready = False
        with self._lock:
            w.last_health = payload
        if not w.up:
            return
        try:
            _, _, mbody = _http(w.url + "/serving/v1/models",
                                timeout=self.poll_timeout)
            models = json.loads(mbody).get("models", [])
            # ?name= (ISSUE 16 satellite): the poll only reads the two
            # dl4j_serving_ load gauges — no point rendering, shipping,
            # and scanning the full exposition every interval
            _, _, raw = _http(w.url + "/metrics?name=dl4j_serving_",
                              timeout=self.poll_timeout)
            text = raw.decode()
            load = (_parse_gauge_sum(text, "dl4j_serving_queue_depth")
                    + _parse_gauge_sum(text,
                                       "dl4j_serving_replica_load"))
        except (TransportFailure, ValueError, UnicodeDecodeError) as e:
            self._note_transport_failure(w, e)
            return
        with self._lock:
            w.models = models
            w.polled_load = load
        self._note_success(w)

    # -- request path --------------------------------------------------------
    def handle_request(self, name, kind, path, body, in_headers):
        """Route one :predict/:decode. Returns (status, headers, body)
        — the worker's answer passed through. Raises
        :class:`serving.http.HttpError` for router-origin errors (503
        no live worker, 502 retry budget exhausted)."""
        inst = self._inst()
        rollout = self._rollout
        if rollout is not None and kind == "predict" \
                and rollout.pins(name):
            body = rollout.pin_body(body)
        tp = in_headers.get("traceparent")
        root = tracing.start_trace(f"fleet.{kind}", traceparent=tp,
                                   model=name)
        fwd = {"Content-Type": "application/json"}
        if root is not None:
            # forward OUR span as the worker's parent: same trace id as
            # the client's, so the worker's http.predict span nests
            # under fleet.predict and /debug/fleet/traces can stitch
            # the cross-process tree with correct parent edges
            fwd["traceparent"] = root.traceparent()
        elif tp:
            # unsampled at the router: the client's header passes
            # through unmodified (the worker honors its sampled flag)
            fwd["traceparent"] = tp
        with (root or tracing.NULL):
            return self._route(name, kind, path, body, fwd, inst,
                               rollout, root)

    def _route(self, name, kind, path, body, fwd, inst, rollout, root):
        tried = set()
        retries = 0
        while True:
            w = self._pick(tried)
            if w is None:
                if inst is not None:
                    inst.request("none", "no_worker")
                # Retry-After (ISSUE 16 satellite): the soonest a dead
                # worker can be readmitted is the next poll round, so
                # that is when routing capacity can reappear — same
                # contract as the admission controller's 429
                raise shttp.HttpError(
                    503, "no live fleet worker available",
                    headers={"Retry-After":
                             f"{max(self.poll_interval, 0.001):.3f}"})
            t0 = time.perf_counter()
            try:
                try:
                    status, rh, rb = _http(
                        w.url + path, body=body, headers=fwd,
                        timeout=self.request_timeout)
                finally:
                    self._done(w)
            except TransportFailure as e:
                self._note_transport_failure(w, e)
                tried.add(w.name)
                if inst is not None:
                    inst.request(w.name, "transport")
                if retries < self.retry_budget:
                    retries += 1
                    if inst is not None:
                        inst.retries.inc()
                    flight.record("fleet_retry", worker=w.name,
                                  model=name, error=str(e),
                                  attempt=retries)
                    continue
                raise shttp.HttpError(
                    502, f"fleet: no worker reachable for {name!r} "
                         f"after {retries} retries: {e}")
            dt = time.perf_counter() - t0
            self._note_success(w)
            if root:
                root.set_attr(worker=w.name, http_status=status,
                              retries=retries)
            if inst is not None:
                inst.hop(w.name).observe(dt)
                inst.request(w.name, _outcome(status))
            # hop decomposition (ISSUE 16): the worker's Server-Timing
            # reports queue/execute/handler; subtraction attributes the
            # rest of the measured hop — worker handler time outside
            # the phases, then serialize+network+parse transit. The
            # four phases sum to dt by construction.
            st = next((v for k, v in rh.items()
                       if k.lower() == "server-timing"), None)
            ttft = None
            if st:
                phases = _parse_server_timing(st)
                ttft = phases.get("ttft")
                handler_s = min(phases.get("handler", dt), dt)
                queue_s = phases.get("queue", 0.0)
                execute_s = phases.get("execute", 0.0)
                decomp = {
                    "queue": queue_s,
                    "execute": execute_s,
                    "worker_other": max(
                        handler_s - queue_s - execute_s, 0.0),
                    "transit": max(dt - handler_s, 0.0),
                }
                if inst is not None:
                    for phase in HOP_PHASES:
                        inst.hop_phase(phase).observe(decomp[phase])
                if root:
                    root.set_attr(**{f"hop_{p}_s": round(decomp[p], 6)
                                     for p in HOP_PHASES})
            if status == 200 and kind == "predict":
                if self.capture is not None:
                    self.capture.maybe_record(name, body, rb, inst=inst)
                if rollout is not None:
                    rollout.on_primary(name, body, rb, dt,
                                       kind="predict")
            elif status == 200 and kind == "decode" \
                    and rollout is not None:
                # decode canaries are judged on TTFT (the worker's
                # Server-Timing phase); whole-hop dt is the fallback
                rollout.on_primary(name, body, rb, dt, kind="decode",
                                   ttft=ttft)
            out = {k: v for k, v in rh.items()
                   if k.lower() in _PASS_HEADERS}
            return status, out, rb

    # -- rollout -------------------------------------------------------------
    def start_rollout(self, name, spec, version, **kw):
        """Begin a canary rollout of ``spec`` as ``name`` version
        ``version`` (see fleet/rollout.py). One at a time: the
        previous rollout must be terminal."""
        from deeplearning4j_tpu.fleet.rollout import RolloutController

        with self._lock:
            cur = self._rollout
            if cur is not None and not cur.terminal():
                raise RuntimeError(
                    f"a rollout is already active (state {cur.state})")
        ctl = RolloutController(self, name, spec, version, **kw)
        self._rollout = ctl
        try:
            ctl.start()
        except Exception:
            # a rollout that never reached canary must not wedge the
            # one-at-a-time gate
            if not ctl.terminal():
                self._rollout = None
            raise
        return ctl

    @property
    def rollout(self):
        return self._rollout

    # -- GET surfaces --------------------------------------------------------
    def merged_models(self):
        """Union of the live workers' model rows by (name, version) —
        the router's GET /serving/v1/models payload."""
        rows = {}
        with self._lock:
            for w in self.workers:
                if not w.up:
                    continue
                for m in w.models:
                    rows.setdefault((m.get("name"), m.get("version")),
                                    m)
        return [rows[k] for k in sorted(
            rows, key=lambda k: (str(k[0]), -(k[1] or 0)))]

    def healthz(self):
        """(payload, status) for the router's /healthz: ready while at
        least one worker is routable; DEGRADED — still 200 — while any
        worker is ejected (capacity reduced, traffic flows)."""
        with self._lock:
            rows = {w.name: w.describe() for w in self.workers}
            live = [w for w in self.workers if w.up]
            routable = [w for w in live if w.ready is not False]
        ready = bool(routable)
        degraded = len(live) < len(self.workers)
        status = ("degraded" if ready and degraded
                  else "ok" if ready else "warming")
        payload = {
            "status": status, "live": True, "ready": ready,
            "fleet": {"workers": rows, "size": len(self.workers),
                      "routable": len(routable),
                      "degraded": degraded},
        }
        # declared objectives (ISSUE 16): a burning SLO degrades the
        # router — still HTTP 200, the burn informs operators while
        # traffic keeps flowing (degraded-not-503, the PR-5 contract)
        from deeplearning4j_tpu.telemetry import slo as slo_mod

        slo_section = slo_mod.healthz_section()
        if slo_section:
            payload["slo"] = slo_section
            if slo_section.get("degraded") and payload["status"] == "ok":
                payload["status"] = "degraded"
        if self._rollout is not None:
            payload["rollout"] = self._rollout.describe()
        return payload, (200 if ready else 503)

    def describe(self):
        """GET /debug/fleet payload."""
        with self._lock:
            workers = {w.name: w.describe() for w in self.workers}
        out = {"workers": workers,
               "retry_budget": self.retry_budget,
               "breaker": self.BREAKER}
        if self._rollout is not None:
            out["rollout"] = self._rollout.describe()
        if self.capture is not None:
            out["capture"] = self.capture.describe()
        if self.autopilot is not None:
            out["autopilot"] = self.autopilot.describe()
        return out

    # -- federation (ISSUE 16): the fleet as ONE observability surface ------
    def _fan_out(self, path):
        """[(worker, body_bytes)] from GETting ``path`` on every live
        worker; a worker that fails the fetch is skipped (federation is
        best-effort — one dead worker must not blank the fleet view)."""
        with self._lock:
            live = [w for w in self.workers if w.up]
        out = []
        for w in live:
            try:
                status, _, body = _http(w.url + path,
                                        timeout=self.poll_timeout)
            except TransportFailure:
                continue
            if status == 200:
                out.append((w, body))
        return out

    def fleet_metrics(self, name_prefix=None) -> str:
        """GET /debug/fleet/metrics: every live worker's families plus
        the router's own, merged into one exposition under a ``worker``
        label — one scrape federates the fleet."""
        from deeplearning4j_tpu.telemetry import prometheus

        path = "/metrics" + (f"?name={name_prefix}" if name_prefix
                             else "")
        sections = [("router", prometheus.render(
            name_prefix=name_prefix))]
        for w, body in self._fan_out(path):
            try:
                sections.append((w.name, body.decode()))
            except UnicodeDecodeError:
                continue
        return _merge_expositions(sections)

    def fleet_flight(self) -> str:
        """GET /debug/fleet/flight: the router's flight ring and every
        live worker's, each event tagged ``worker`` and the whole merge
        ordered by wall-clock ``ts`` (the cross-process field every
        event carries as of ISSUE 16) — one incident timeline."""
        events = [dict(e, worker="router")
                  for e in flight.get_recorder().events()]
        for w, body in self._fan_out("/debug/flightrecorder"):
            for line in body.decode(errors="replace").splitlines():
                if not line.strip():
                    continue
                try:
                    evt = json.loads(line)
                except ValueError:
                    continue
                evt["worker"] = w.name
                events.append(evt)
        events.sort(key=lambda e: e.get("ts", 0.0))
        if not events:
            return "\n"
        return "\n".join(json.dumps(e, default=flight._json_default)
                         for e in events) + "\n"

    def fleet_traces(self, trace_id=None) -> str:
        """GET /debug/fleet/traces[?trace_id=]: the stitched
        cross-process span tree as JSONL — the router's spans (the
        ``fleet.predict`` roots) plus every live worker's, tagged
        ``worker`` and ordered by wall-clock ``ts``. Because the router
        forwards its OWN traceparent to the worker, the worker spans'
        parent ids point into the router's tree: one connected trace
        per response, no per-worker hand-querying."""
        records = []
        for line in tracing.export_jsonl(trace_id=trace_id).splitlines():
            if not line.strip():
                continue
            rec = json.loads(line)
            rec["worker"] = "router"
            records.append(rec)
        path = "/debug/traces" + (f"?trace_id={trace_id}" if trace_id
                                  else "")
        for w, body in self._fan_out(path):
            for line in body.decode(errors="replace").splitlines():
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                rec["worker"] = w.name
                records.append(rec)
        records.sort(key=lambda r: r.get("ts", 0.0))
        if not records:
            return "\n"
        return "\n".join(json.dumps(r) for r in records) + "\n"

    def fleet_profile(self, window=None) -> str:
        """GET /debug/fleet/profile[?window=]: the fleet's collapsed
        wall-clock stacks merged under an injected worker root frame —
        the router's own sampler ring (poll/mirror/handler threads
        included) plus every live worker's /debug/profile/cpu. One
        request → one whole-fleet flamegraph."""
        from deeplearning4j_tpu.telemetry import profiler

        merged = {}
        for stack, count in profiler.collapsed(window).items():
            key = f"router;{stack}"
            merged[key] = merged.get(key, 0) + count
        path = "/debug/profile/cpu" + (
            f"?window={float(window)}" if window is not None else "")
        for w, body in self._fan_out(path):
            worker_stacks = profiler.parse_collapsed(
                body.decode(errors="replace"))
            for stack, count in worker_stacks.items():
                key = f"{w.name};{stack}"
                merged[key] = merged.get(key, 0) + count
        return profiler.render_collapsed(merged)


def _outcome(status) -> str:
    if status == 200:
        return "ok"
    if status == 429:
        return "shed"
    if status == 504:
        return "timeout"
    if 400 <= status < 500:
        return "client_error"
    return "upstream_error"


# the router's /debug index (ISSUE 18 satellite) — its own debug
# surface plus the fleet-federated routes; served at GET /debug via
# ui.server.debug_index
ROUTER_DEBUG_ROUTES = (
    ("GET", "/debug", "this index: every debug route + description"),
    ("GET", "/debug/fleet",
     "router state: workers, health, breaker, rollout, capture"),
    ("GET", "/debug/fleet/metrics",
     "every live worker's /metrics + the router's, merged under a "
     "worker label (?name=)"),
    ("GET", "/debug/fleet/flight",
     "fleet-merged flight events as JSONL, ordered by wall clock"),
    ("GET", "/debug/fleet/profile",
     "whole-fleet flamegraph: router + worker collapsed stacks, "
     "worker injected as root frame (?window=)"),
    ("GET", "/debug/fleet/traces",
     "stitched cross-process span trees as JSONL (?trace_id=)"),
    ("GET", "/debug/profile/cpu",
     "the router's own collapsed wall-clock stacks (?window=)"),
    ("GET", "/debug/timeseries",
     "the router's windowed metric ring (?window=, ?name=)"),
)


class _RouterHandler(BaseHTTPRequestHandler):
    server_version = "dl4jtpuFleet/1.0"

    def _respond(self, body, status=200, ctype="application/json",
                 headers=None):
        self.send_response(status)
        headers = dict(headers or {})
        if not any(k.lower() == "content-type" for k in headers):
            headers["Content-Type"] = ctype
        headers["Content-Length"] = str(len(body))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        router = self.server.router
        if self.path.rstrip("/") == shttp.MODELS_PATH:
            self._respond(json.dumps(
                {"models": router.merged_models()}).encode())
        elif self.path == "/healthz":
            payload, status = router.healthz()
            self._respond(json.dumps(payload).encode(), status=status)
        elif self.path == "/metrics" or self.path.startswith("/metrics?"):
            from urllib.parse import parse_qs, urlsplit

            from deeplearning4j_tpu.telemetry import prometheus

            query = parse_qs(urlsplit(self.path).query)
            name_prefix = (query.get("name") or [None])[0]
            self._respond(
                prometheus.render(name_prefix=name_prefix).encode(),
                ctype=prometheus.CONTENT_TYPE)
        elif self.path.startswith("/debug/fleet/metrics"):
            # federation (ISSUE 16): the fleet's expositions merged
            # under a worker label — ONE scrape for N+1 processes
            from urllib.parse import parse_qs, urlsplit

            from deeplearning4j_tpu.telemetry import prometheus

            query = parse_qs(urlsplit(self.path).query)
            name_prefix = (query.get("name") or [None])[0]
            self._respond(router.fleet_metrics(name_prefix).encode(),
                          ctype=prometheus.CONTENT_TYPE)
        elif self.path.startswith("/debug/fleet/flight"):
            self._respond(router.fleet_flight().encode(),
                          ctype="application/x-ndjson")
        elif self.path.startswith("/debug/fleet/profile"):
            # the whole-fleet flamegraph (ISSUE 18): router + every
            # live worker's collapsed stacks, worker name injected as
            # the root frame
            from urllib.parse import parse_qs, urlsplit

            query = parse_qs(urlsplit(self.path).query)
            window = (query.get("window") or [None])[0]
            try:
                window = float(window) if window is not None else None
            except ValueError:
                self._respond(b'{"error": "window must be seconds"}',
                              status=400)
                return
            self._respond(router.fleet_profile(window).encode(),
                          ctype="text/plain; charset=utf-8")
        elif self.path.startswith("/debug/profile/cpu"):
            # the router's OWN sampler ring (same surface as the
            # workers': ui/server.py)
            from urllib.parse import parse_qs, urlsplit

            from deeplearning4j_tpu.telemetry import profiler

            query = parse_qs(urlsplit(self.path).query)
            window = (query.get("window") or [None])[0]
            try:
                window = float(window) if window is not None else None
            except ValueError:
                self._respond(b'{"error": "window must be seconds"}',
                              status=400)
                return
            self._respond(profiler.render(window).encode(),
                          ctype="text/plain; charset=utf-8")
        elif self.path.startswith("/debug/fleet/traces"):
            from urllib.parse import parse_qs, urlsplit

            query = parse_qs(urlsplit(self.path).query)
            tid = (query.get("trace_id") or [None])[0]
            self._respond(router.fleet_traces(tid).encode(),
                          ctype="application/x-ndjson")
        elif self.path.startswith("/debug/timeseries"):
            # the router's own windowed-snapshot ring (same surface as
            # the workers': ui/server.py)
            from urllib.parse import parse_qs, urlsplit

            from deeplearning4j_tpu.telemetry import timeseries

            query = parse_qs(urlsplit(self.path).query)
            window = (query.get("window") or [None])[0]
            name = (query.get("name") or [None])[0]
            try:
                window = float(window) if window is not None else None
            except ValueError:
                self._respond(b'{"error": "window must be seconds"}',
                              status=400)
                return
            self._respond(json.dumps(timeseries.describe(
                window=window, name=name)).encode())
        elif self.path.startswith("/debug/fleet"):
            self._respond(json.dumps(router.describe()).encode())
        elif self.path.rstrip("/") == "/debug" or \
                self.path.startswith("/debug?"):
            # the route index (ISSUE 18 satellite)
            from deeplearning4j_tpu.ui.server import debug_index

            self._respond(json.dumps(
                debug_index(ROUTER_DEBUG_ROUTES)).encode())
        else:
            self._respond(b'{"error": "not found"}', status=404)

    def do_POST(self):
        router = self.server.router
        name = shttp.parse_predict_path(self.path)
        kind = "predict"
        if name is None:
            name = shttp.parse_decode_path(self.path)
            kind = "decode"
        if name is None:
            self._respond(b'{"error": "not found"}', status=404)
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        try:
            status, headers, out = router.handle_request(
                name, kind, self.path, body,
                {"traceparent": self.headers.get("traceparent")})
        except shttp.HttpError as e:
            self._respond(shttp.error_body(e), status=e.status,
                          headers=e.headers)
            return
        except Exception as e:   # router bug: answer, don't hang
            log.exception("fleet router error on %s", self.path)
            self._respond(shttp.error_body(shttp.HttpError(
                500, f"{type(e).__name__}: {e}")), status=500)
            return
        self._respond(out, status=status, headers=headers)

    def log_message(self, *args):  # quiet
        pass


def main(argv=None) -> int:
    """Standalone router: spawn N workers from a spec and serve.

        python -m deeplearning4j_tpu.fleet.router \\
            --spec spec.json --workers 3 --port 9100
    """
    import argparse

    p = argparse.ArgumentParser(description="fleet router")
    p.add_argument("--spec", required=True)
    p.add_argument("--workers", type=int, default=3)
    p.add_argument("--port", type=int, default=9100)
    p.add_argument("--adopt", default=None,
                   help="comma-separated worker base URLs to adopt "
                        "instead of spawning")
    args = p.parse_args(argv)
    if args.adopt:
        handles = [WorkerHandle(f"w{i}", u) for i, u in
                   enumerate(args.adopt.split(","))]
        owns = False
    else:
        with open(args.spec) as f:
            spec = json.load(f)
        handles = spawn_local_workers(args.workers, spec)
        owns = True
    router = FleetRouter(handles, owns_workers=owns).start(args.port)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        router.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
