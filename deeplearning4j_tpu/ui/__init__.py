from deeplearning4j_tpu.ui.stats import (  # noqa: F401
    FileStatsStorage, InMemoryStatsStorage, StatsListener)
