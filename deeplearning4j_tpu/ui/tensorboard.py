"""TensorBoard event-file emission, dependency-free.

Reference capability: the SURVEY.md §5 observability prescription ("emit
scalars to TensorBoard event files") standing in for deeplearning4j-ui's
vertx dashboard. TensorFlow/tensorboard are not installed, so this
writes the TFRecord + Event/Summary protos directly with the in-repo
protobuf encoder: a TFRecord frame is

    uint64 length (LE) | uint32 masked-crc32c(length bytes) |
    payload          | uint32 masked-crc32c(payload)

and the payload is an `Event` proto (tensorflow/core/util/event.proto:
wall_time=1 double, step=2 int64, file_version=3 string, summary=5)
whose `Summary` (summary.proto) holds value=1 entries {tag=1,
simple_value=2 float}. Any stock TensorBoard install can open the
resulting events file."""

from __future__ import annotations

import os
import socket
import struct
import time

from deeplearning4j_tpu.modelimport.protobuf import (
    emit_bytes, emit_varint, _emit_tag, _I64, _I32)
from deeplearning4j_tpu.utils.listeners import TrainingListener

_CRC_TABLE = []


def _crc32c_table():
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    tbl = _crc32c_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def _tfrecord_frame(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (header + struct.pack("<I", _masked_crc(header)) + payload
            + struct.pack("<I", _masked_crc(payload)))


def _emit_double(out, field, value):
    _emit_tag(out, field, _I64)
    out.extend(struct.pack("<d", value))


def _emit_float(out, field, value):
    _emit_tag(out, field, _I32)
    out.extend(struct.pack("<f", value))


def _event(wall_time, step=None, file_version=None, summary=None) -> bytes:
    ev = bytearray()
    _emit_double(ev, 1, wall_time)
    if step is not None:
        emit_varint(ev, 2, step)
    if file_version is not None:
        emit_bytes(ev, 3, file_version.encode())
    if summary is not None:
        emit_bytes(ev, 5, summary)
    return bytes(ev)


def _scalar_summary(scalars: dict) -> bytes:
    s = bytearray()
    for tag, value in scalars.items():
        v = bytearray()
        emit_bytes(v, 1, tag.encode())
        _emit_float(v, 2, float(value))
        emit_bytes(s, 1, v)
    return bytes(s)


class SummaryWriter:
    """Minimal tf.summary-style scalar writer."""

    def __init__(self, logdir, filename_suffix=""):
        os.makedirs(logdir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}{filename_suffix}")
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "ab")
        self._f.write(_tfrecord_frame(
            _event(time.time(), file_version="brain.Event:2")))
        self._f.flush()

    def add_scalar(self, tag, value, step):
        self.add_scalars({tag: value}, step)

    def add_scalars(self, scalars: dict, step):
        self._f.write(_tfrecord_frame(_event(
            time.time(), step=int(step),
            summary=_scalar_summary(scalars))))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.flush()
        self._f.close()


def read_events(path):
    """Parse an events file back into [(step, {tag: value})] — the test
    oracle, and a migration path for tooling."""
    from deeplearning4j_tpu.modelimport.protobuf import iter_fields

    out = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        (length,) = struct.unpack("<Q", data[pos:pos + 8])
        (hcrc,) = struct.unpack("<I", data[pos + 8:pos + 12])
        if hcrc != _masked_crc(data[pos:pos + 8]):
            raise ValueError("corrupt tfrecord header crc")
        payload = data[pos + 12:pos + 12 + length]
        (pcrc,) = struct.unpack(
            "<I", data[pos + 12 + length:pos + 16 + length])
        if pcrc != _masked_crc(payload):
            raise ValueError("corrupt tfrecord payload crc")
        pos += 16 + length
        step, scalars = None, {}
        for field, wt, v in iter_fields(payload):
            if field == 2:
                step = v
            elif field == 5:
                for f2, _w2, v2 in iter_fields(v):
                    if f2 != 1:
                        continue
                    tag, val = None, None
                    for f3, _w3, v3 in iter_fields(v2):
                        if f3 == 1:
                            tag = bytes(v3).decode()
                        elif f3 == 2:
                            val = struct.unpack("<f", v3)[0]
                    if tag is not None:
                        scalars[tag] = val
        if scalars:
            out.append((step, scalars))
    return out


class TensorBoardStatsListener(TrainingListener):
    """Per-iteration score -> TensorBoard scalars (the reference's
    StatsListener wired to an event-file backend)."""

    def __init__(self, logdir, frequency=1):
        self.writer = SummaryWriter(logdir)
        self.frequency = frequency

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency:
            return
        self.writer.add_scalars(
            {"score": float(model.score()), "epoch": float(epoch)},
            iteration)
        self.writer.flush()
