"""Training UI server.

Reference capability: deeplearning4j-ui-parent's vertx dashboard
(`UIServer.getInstance().attach(statsStorage)`, SURVEY.md §2.7) — score
curves for attached training sessions in a browser. Implemented on the
stdlib http.server (no vertx, no js deps): "/" renders an auto-refreshing
SVG score chart, "/data" serves the attached storages' records as JSON,
"/metrics" serves the telemetry registry in Prometheus text exposition
(ISSUE 1: the scrape endpoint)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!doctype html>
<html><head><title>dl4j-tpu training UI</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; }
 .axis { stroke: #999; stroke-width: 1; }
 .curve { fill: none; stroke: #2563eb; stroke-width: 1.5; }
 text { font-size: 11px; fill: #555; }
</style></head>
<body>
<h2>Training score</h2>
<div id="chart"></div>
<script>
async function draw() {
  const res = await fetch('/data');
  const sessions = await res.json();
  const el = document.getElementById('chart');
  el.innerHTML = '';
  for (const [sid, recs] of Object.entries(sessions)) {
    const pts = recs.map(r => [r.iteration, r.score]);
    if (!pts.length) continue;
    const W = 640, H = 240, P = 40;
    const xs = pts.map(p => p[0]), ys = pts.map(p => p[1]);
    const xmin = Math.min(...xs), xmax = Math.max(...xs, xmin + 1);
    const ymin = Math.min(...ys), ymax = Math.max(...ys, ymin + 1e-9);
    const sx = x => P + (x - xmin) / (xmax - xmin) * (W - 2 * P);
    const sy = y => H - P - (y - ymin) / (ymax - ymin) * (H - 2 * P);
    const d = pts.map((p, i) => (i ? 'L' : 'M') + sx(p[0]) + ',' + sy(p[1])).join(' ');
    el.innerHTML += `<h4>${sid}</h4>
      <svg width="${W}" height="${H}">
       <line class="axis" x1="${P}" y1="${H - P}" x2="${W - P}" y2="${H - P}"/>
       <line class="axis" x1="${P}" y1="${P}" x2="${P}" y2="${H - P}"/>
       <text x="${P}" y="${H - P + 14}">${xmin}</text>
       <text x="${W - P - 20}" y="${H - P + 14}">${xmax}</text>
       <text x="2" y="${H - P}">${ymin.toFixed(3)}</text>
       <text x="2" y="${P + 4}">${ymax.toFixed(3)}</text>
       <path class="curve" d="${d}"/>
      </svg>`;
  }
}
draw();
setInterval(draw, 2000);
</script>
</body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpuUI/1.0"

    def do_GET(self):
        if self.path == "/data":
            body = json.dumps(self.server.ui._sessions()).encode()
            ctype = "application/json"
        elif self.path == "/metrics":
            from deeplearning4j_tpu.telemetry import prometheus

            body = prometheus.render().encode()
            ctype = prometheus.CONTENT_TYPE
        elif self.path == "/":
            body = _PAGE.encode()
            ctype = "text/html; charset=utf-8"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet
        pass


class UIServer:
    """Singleton mirroring org.deeplearning4j.ui.api.UIServer."""

    _instance = None

    def __init__(self):
        self._storages = []
        self._httpd = None
        self._thread = None
        self.port = None

    @classmethod
    def getInstance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    def attach(self, statsStorage):
        self._storages.append(statsStorage)
        return self

    def detach(self, statsStorage):
        self._storages.remove(statsStorage)

    def _sessions(self):
        out = {}
        for storage in self._storages:
            for sid in storage.listSessionIDs():
                out.setdefault(sid, []).extend(
                    {"iteration": r.get("iteration"),
                     "score": r.get("score"),
                     "epoch": r.get("epoch")}
                    for r in storage.getRecords(sid))
        return out

    def enableRemoteListener(self):  # API parity no-op (single-process)
        return self

    def start(self, port=9000):
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.ui = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
        return self
