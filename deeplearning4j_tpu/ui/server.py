"""Training UI server.

Reference capability: deeplearning4j-ui-parent's vertx dashboard
(`UIServer.getInstance().attach(statsStorage)`, SURVEY.md §2.7) — score
curves for attached training sessions in a browser. Implemented on the
stdlib http.server (no vertx, no js deps): "/" renders an auto-refreshing
SVG score chart, "/data" serves the attached storages' records as JSON,
"/metrics" serves the telemetry registry in Prometheus text exposition
(ISSUE 1: the scrape endpoint), and — with an InferenceSession attached
via serveModels() — "/serving/v1/models" lists registered models and
"POST /serving/v1/models/<name>:predict" serves JSON inference
(ISSUE 2: the serving endpoint). ISSUE 3 adds "/healthz" (liveness +
readiness: serving warmup done, last-step age, divergence state) and
"/debug/flightrecorder" (the telemetry.flight ring buffer as JSONL).
ISSUE 5: /healthz readiness detail gains the resilience section
(supervisor state + checkpoint staleness — "degraded", still 200) and
/metrics refreshes the checkpoint-age gauge at scrape time. ISSUE 11:
"/debug/compiles" (the compile ledger: every train-step/serving
compile with forensic cause, compile seconds, HLO fingerprint) and
"/debug/hlo/<key>" (the per-executable fusion/remat audit). ISSUE 14:
"/debug/memory" (the HBM ownership ledger: claims table, per-device
claimed-vs-in-use reconciliation with the unattributed residual, and
planner headroom), and /metrics refreshes the claimed-bytes gauges at
scrape time."""

from __future__ import annotations

import errno
import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger(__name__)

_PAGE = """<!doctype html>
<html><head><title>dl4j-tpu training UI</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; }
 .axis { stroke: #999; stroke-width: 1; }
 .curve { fill: none; stroke: #2563eb; stroke-width: 1.5; }
 text { font-size: 11px; fill: #555; }
</style></head>
<body>
<h2>Training score</h2>
<div id="chart"></div>
<script>
async function draw() {
  const res = await fetch('/data');
  const sessions = await res.json();
  const el = document.getElementById('chart');
  el.innerHTML = '';
  for (const [sid, recs] of Object.entries(sessions)) {
    const pts = recs.map(r => [r.iteration, r.score]);
    if (!pts.length) continue;
    const W = 640, H = 240, P = 40;
    const xs = pts.map(p => p[0]), ys = pts.map(p => p[1]);
    const xmin = Math.min(...xs), xmax = Math.max(...xs, xmin + 1);
    const ymin = Math.min(...ys), ymax = Math.max(...ys, ymin + 1e-9);
    const sx = x => P + (x - xmin) / (xmax - xmin) * (W - 2 * P);
    const sy = y => H - P - (y - ymin) / (ymax - ymin) * (H - 2 * P);
    const d = pts.map((p, i) => (i ? 'L' : 'M') + sx(p[0]) + ',' + sy(p[1])).join(' ');
    el.innerHTML += `<h4>${sid}</h4>
      <svg width="${W}" height="${H}">
       <line class="axis" x1="${P}" y1="${H - P}" x2="${W - P}" y2="${H - P}"/>
       <line class="axis" x1="${P}" y1="${P}" x2="${P}" y2="${H - P}"/>
       <text x="${P}" y="${H - P + 14}">${xmin}</text>
       <text x="${W - P - 20}" y="${H - P + 14}">${xmax}</text>
       <text x="2" y="${H - P}">${ymin.toFixed(3)}</text>
       <text x="2" y="${P + 4}">${ymax.toFixed(3)}</text>
       <path class="curve" d="${d}"/>
      </svg>`;
  }
}
draw();
setInterval(draw, 2000);
</script>
</body></html>
"""


# the /debug index (ISSUE 18 satellite): every debug route this server
# dispatches, with a one-line description — operators discover routes
# here instead of reading docs mid-incident. The route-drift rule
# checks the dispatched literals against this table AND the docs, so
# the index cannot rot.
DEBUG_ROUTES = (
    ("GET", "/debug", "this index: every debug route + description"),
    ("GET", "/debug/compiles",
     "compile ledger: every compile with cause/seconds/fingerprint "
     "(?site=) + executable-store stats"),
    ("GET", "/debug/flightrecorder",
     "the bounded flight-event ring as JSONL"),
    ("GET", "/debug/hlo/<key>",
     "per-executable HLO fusion/collective/remat/buffer audit"),
    ("GET", "/debug/memory",
     "HBM ownership ledger: claims, reconciliation, planner headroom"),
    ("GET", "/debug/profile/cpu",
     "continuous profiler: collapsed wall-clock stacks, "
     "flamegraph-ready (?window= seconds)"),
    ("POST", "/debug/profile/capture",
     "single-flight deep capture (?seconds=): high-rate sample + "
     "device trace, 409 while one runs"),
    ("GET", "/debug/profile/captures",
     "committed capture artifacts: list, or /<id>/<file> to download"),
    ("GET", "/debug/timeseries",
     "windowed metric ring: counter rates, gauge series, histogram "
     "p50/p99 (?window=, ?name=)"),
    ("GET", "/debug/traces", "sampled span trees as JSONL (?trace_id=)"),
)


def debug_index(routes=DEBUG_ROUTES) -> dict:
    """The GET /debug payload (shared with the fleet router, which
    passes its own table)."""
    return {"routes": [
        {"method": method, "route": route, "description": description}
        for method, route, description in routes]}


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpuUI/1.0"

    def _respond(self, body, ctype="application/json", status=200,
                 headers=None):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/data":
            body = json.dumps(self.server.ui._sessions()).encode()
            ctype = "application/json"
        elif self.path == "/metrics" or \
                self.path.startswith("/metrics?"):
            from deeplearning4j_tpu.telemetry import prometheus

            try:
                # time-derived gauges (dl4j_ckpt_age_seconds) refresh at
                # scrape time so Prometheus sees a live age, not the age
                # as of the last checkpoint commit
                from deeplearning4j_tpu.resilience import async_ckpt

                async_ckpt.refresh_metrics()
            except Exception:
                pass
            try:
                # the HBM ownership gauges (ISSUE 14) reconcile claims
                # against device.memory_stats() at scrape time — the
                # unattributed residual is a census, never a step cost
                from deeplearning4j_tpu.telemetry import memledger

                memledger.refresh_metrics()
            except Exception:
                pass
            # /metrics?exemplars=1 appends OpenMetrics-STYLE exemplar
            # suffixes to histogram buckets (trace ids, ISSUE 10) — an
            # explicit operator opt-in, NOT Accept negotiation: a
            # default Prometheus scrape advertises openmetrics-text in
            # Accept, and claiming that content type for a body this
            # exposition does not fully implement (no '# EOF', counter
            # families keep their _total names) would fail every
            # default scrape. The plain scrape stays bare 0.0.4.
            from urllib.parse import parse_qs, urlsplit

            query = parse_qs(urlsplit(self.path).query)
            exemplars = (query.get("exemplars") or ["0"])[0] not in (
                "0", "false", "")
            # /metrics?name=<prefix> keeps only matching families —
            # selective scrapers (the fleet router's poll thread) stop
            # rendering and parsing the full exposition every interval
            name_prefix = (query.get("name") or [None])[0]
            body = prometheus.render(exemplars=exemplars,
                                     name_prefix=name_prefix).encode()
            ctype = prometheus.CONTENT_TYPE
        elif self.path == "/healthz":
            # liveness + readiness: divergence state, last-step age,
            # serving warmup (ISSUE 3) — 503 until ready, 503 again on
            # divergence, so orchestrators stop routing traffic
            from deeplearning4j_tpu.telemetry import health

            payload, status = health.healthz(self.server.ui._serving)
            self._respond(json.dumps(payload).encode(), status=status)
            return
        elif self.path == "/debug/flightrecorder":
            from deeplearning4j_tpu.telemetry import flight

            self._respond(flight.get_recorder().dump_jsonl().encode(),
                          ctype="application/x-ndjson")
            return
        elif self.path.startswith("/debug/hlo/"):
            # per-executable HLO audit (ISSUE 11): fusion/collective/
            # remat/buffer stats for one ledgered executable; step-site
            # records compile lazily on first ask (cached after)
            from urllib.parse import unquote

            from deeplearning4j_tpu.telemetry import compile_ledger

            key = unquote(self.path[len("/debug/hlo/"):])
            audit = compile_ledger.get_ledger().audit(key)
            if audit is None:
                self._respond(b'{"error": "unknown ledger key"}',
                              status=404)
                return
            self._respond(json.dumps(audit).encode())
            return
        elif self.path.startswith("/debug/compiles"):
            # the compile ledger (ISSUE 11): every train-step compile
            # and AOT serving warmup, newest first, with forensic cause
            # + compile seconds + HLO fingerprint; ?site= filters.
            # ISSUE 13 adds the executable-store section (hits/rejects/
            # bytes on disk). Read-only and served whether or not
            # telemetry is currently enabled (incident dumps outlive a
            # disable())
            from urllib.parse import parse_qs, urlsplit

            from deeplearning4j_tpu import compilestore
            from deeplearning4j_tpu.telemetry import compile_ledger

            query = parse_qs(urlsplit(self.path).query)
            site = (query.get("site") or [None])[0]
            body = json.dumps({
                "records": compile_ledger.get_ledger().describe(
                    site=site),
                "store": compilestore.describe(),
            }).encode()
            self._respond(body)
            return
        elif self.path.startswith("/debug/memory"):
            # the HBM ownership ledger (ISSUE 14): the full claims
            # table, the per-device claimed-vs-in-use reconciliation
            # (incl. the unattributed residual), and the capacity
            # planner's view (headroom, budget, degradation floor).
            # Read-only and served whether or not telemetry is
            # currently enabled (incident dumps outlive a disable())
            from deeplearning4j_tpu.telemetry import memledger

            self._respond(json.dumps(memledger.describe()).encode())
            return
        elif self.path.startswith("/debug/timeseries"):
            # the windowed-snapshot ring (ISSUE 16): counter rates,
            # gauge series, histogram p50/p99 over ?window= seconds,
            # ?name= prefix-filters the keys. Read-only and served
            # whether or not telemetry is currently enabled (incident
            # reads outlive a disable())
            from urllib.parse import parse_qs, urlsplit

            from deeplearning4j_tpu.telemetry import timeseries

            query = parse_qs(urlsplit(self.path).query)
            window = (query.get("window") or [None])[0]
            name = (query.get("name") or [None])[0]
            try:
                window = float(window) if window is not None else None
            except ValueError:
                self._respond(b'{"error": "window must be seconds"}',
                              status=400)
                return
            body = json.dumps(
                timeseries.describe(window=window, name=name)).encode()
            self._respond(body)
            return
        elif self.path.startswith("/debug/profile/cpu"):
            # the continuous profiler (ISSUE 18): collapsed wall-clock
            # stacks over ?window= trailing seconds (whole ring when
            # absent), subsystem as the root frame — pipe straight
            # into flamegraph.pl. Read-only and served whether or not
            # telemetry is currently enabled (the ring outlives a
            # disable())
            from urllib.parse import parse_qs, urlsplit

            from deeplearning4j_tpu.telemetry import profiler

            query = parse_qs(urlsplit(self.path).query)
            window = (query.get("window") or [None])[0]
            try:
                window = float(window) if window is not None else None
            except ValueError:
                self._respond(b'{"error": "window must be seconds"}',
                              status=400)
                return
            self._respond(profiler.render(window).encode(),
                          ctype="text/plain; charset=utf-8")
            return
        elif self.path.startswith("/debug/profile/captures"):
            # deep-capture artifacts (ISSUE 18): bare path lists the
            # committed captures (meta + files), /<id>/<file> downloads
            # one artifact (cpu.collapsed, meta.json, device trace)
            from urllib.parse import unquote, urlsplit

            from deeplearning4j_tpu.telemetry import profiler

            rest = unquote(urlsplit(self.path).path[
                len("/debug/profile/captures"):]).strip("/")
            if not rest:
                self._respond(json.dumps(
                    {"captures": profiler.list_captures()}).encode())
                return
            parts = rest.split("/", 1)
            cap_id = parts[0]
            filename = parts[1] if len(parts) > 1 else "meta.json"
            try:
                data = profiler.read_capture(cap_id, filename)
            except (FileNotFoundError, IsADirectoryError):
                self._respond(b'{"error": "unknown capture"}',
                              status=404)
                return
            ctype = ("application/json" if filename.endswith(".json")
                     else "application/octet-stream")
            self._respond(data, ctype=ctype)
            return
        elif self.path.rstrip("/") == "/debug" or \
                self.path.startswith("/debug?"):
            # the route index (ISSUE 18 satellite)
            self._respond(json.dumps(debug_index()).encode())
            return
        elif self.path.startswith("/debug/traces"):
            # span-tree export (ISSUE 10): the whole ring as JSONL, or
            # one trace via /debug/traces?trace_id=<32hex>
            from urllib.parse import parse_qs, urlsplit

            from deeplearning4j_tpu.telemetry import tracing

            query = parse_qs(urlsplit(self.path).query)
            tid = (query.get("trace_id") or [None])[0]
            self._respond(tracing.export_jsonl(trace_id=tid).encode(),
                          ctype="application/x-ndjson")
            return
        elif self.path.startswith("/serving/"):
            from deeplearning4j_tpu.serving import http as shttp

            if self.path.rstrip("/") != shttp.MODELS_PATH:
                self._respond(b'{"error": "not found"}', status=404)
                return
            try:
                body = shttp.handle_models(self.server.ui._serving)
            except shttp.HttpError as e:
                self._respond(shttp.error_body(e), status=e.status)
                return
            ctype = "application/json"
        elif self.path == "/":
            body = _PAGE.encode()
            ctype = "text/html; charset=utf-8"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self._respond(body, ctype)

    def do_POST(self):
        from deeplearning4j_tpu.serving import http as shttp
        from deeplearning4j_tpu.telemetry import tracing

        if self.path.startswith("/debug/profile/capture"):
            # on-demand deep capture (ISSUE 18): ?seconds= of high-rate
            # sampling + a jax.profiler.trace device capture, committed
            # content-addressed; single-flight — 409 while one runs
            from urllib.parse import parse_qs, urlsplit

            from deeplearning4j_tpu.telemetry import profiler

            query = parse_qs(urlsplit(self.path).query)
            try:
                seconds = float((query.get("seconds") or ["2"])[0])
            except ValueError:
                self._respond(b'{"error": "seconds must be a number"}',
                              status=400)
                return
            try:
                meta = profiler.capture(seconds=seconds)
            except profiler.CaptureBusyError:
                self._respond(
                    b'{"error": "a deep capture is already running"}',
                    status=409)
                return
            self._respond(json.dumps(meta).encode())
            return

        # fleet-admin control plane (ISSUE 15): rollouts push/retract
        # spec-built model versions through the versioned registry —
        # 404 unless a WorkerAdmin is attached (serveFleetAdmin)
        admin_name = shttp.parse_register_path(self.path)
        admin_handler = shttp.handle_register
        if admin_name is None:
            admin_name = shttp.parse_unregister_path(self.path)
            admin_handler = shttp.handle_unregister
        if admin_name is not None:
            try:
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                out = admin_handler(self.server.ui._fleet_admin,
                                    admin_name, body)
            except shttp.HttpError as e:
                self._respond(shttp.error_body(e), status=e.status,
                              headers=e.headers)
                return
            self._respond(out)
            return
        name = shttp.parse_predict_path(self.path)
        handler = shttp.handle_predict
        kind = "predict"
        if name is None:
            name = shttp.parse_decode_path(self.path)
            handler = shttp.handle_decode
            kind = "decode"
        if name is None:
            self._respond(b'{"error": "not found"}', status=404)
            return
        # W3C trace propagation (ISSUE 10): join an upstream trace (or
        # head-sample a new one) and hand the decision back in the
        # response traceparent; the request's context flows to the
        # batcher/replica/decode threads via the serving request objects
        root = tracing.start_trace(
            f"http.{kind}", traceparent=self.headers.get("traceparent"),
            model=name)
        headers = ({"traceparent": root.traceparent()}
                   if root is not None else {})
        # hop decomposition (ISSUE 16): predict responses report the
        # already-captured per-request phases in a Server-Timing header
        # (dur in ms, per the spec) so the fleet router can attribute
        # the serialize+network+parse remainder by subtraction
        timing: dict = {}
        t0 = time.perf_counter()
        try:
            with (root or tracing.NULL):
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    out = handler(self.server.ui._serving, name,
                                  body, timing=timing)
                except shttp.HttpError as e:
                    # attribute BEFORE the span exits: finish() hands
                    # the attrs to the export ring
                    if root is not None:
                        root.set_attr(http_status=e.status)
                    raise
        except shttp.HttpError as e:
            # shed responses carry Retry-After (admission control)
            self._respond(shttp.error_body(e), status=e.status,
                          headers={**e.headers, **headers})
            return
        if timing:
            handler_ms = (time.perf_counter() - t0) * 1e3
            parts = [f"{phase};dur={seconds * 1e3:.3f}"
                     for phase, seconds in sorted(timing.items())]
            parts.append(f"handler;dur={handler_ms:.3f}")
            headers["Server-Timing"] = ", ".join(parts)
        self._respond(out, headers=headers)

    def log_message(self, *args):  # quiet
        pass


class UIServer:
    """Singleton mirroring org.deeplearning4j.ui.api.UIServer."""

    _instance = None

    def __init__(self):
        self._storages = []
        self._httpd = None
        self._thread = None
        self._serving = None
        self._fleet_admin = None
        self.port = None

    @classmethod
    def getInstance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer()
        return cls._instance

    def attach(self, statsStorage):
        self._storages.append(statsStorage)
        return self

    def detach(self, statsStorage):
        self._storages.remove(statsStorage)

    def _sessions(self):
        out = {}
        for storage in self._storages:
            for sid in storage.listSessionIDs():
                out.setdefault(sid, []).extend(
                    {"iteration": r.get("iteration"),
                     "score": r.get("score"),
                     "epoch": r.get("epoch")}
                    for r in storage.getRecords(sid))
        return out

    def enableRemoteListener(self):  # API parity no-op (single-process)
        return self

    def serveModels(self, session):
        """Attach an InferenceSession: enables POST
        /serving/v1/models/<name>:predict and GET /serving/v1/models."""
        self._serving = session
        return self

    def serveFleetAdmin(self, admin):
        """Attach a fleet WorkerAdmin (ISSUE 15): enables the rollout
        control plane — POST /serving/v1/models/<name>:register (a
        model version from a JSON spec) and ...:unregister (retract a
        version; rollback restores the incumbent)."""
        self._fleet_admin = admin
        return self

    def start(self, port=9000, max_port_retries=16):
        """Bind and serve in a daemon thread. A port already in use is
        not fatal (a serving smoke test and a dangling stats UI must
        coexist): retry the next ports, then fall back to an
        OS-assigned one; the port actually bound is logged and stored
        in `self.port`."""
        if self._httpd is not None:
            return self
        candidates = ([port] if port == 0 else
                      list(range(port, port + max_port_retries)) + [0])
        for p in candidates:
            try:
                # ThreadingHTTPServer, NOT HTTPServer: one handler
                # thread per connection, so concurrent predict requests
                # reach the DynamicBatcher together and can coalesce —
                # a serial accept loop would defeat batching before it
                # starts (ISSUE 8 satellite; daemon_threads is the
                # ThreadingHTTPServer default, stated here as intent —
                # in-flight handlers must not block interpreter exit)
                self._httpd = ThreadingHTTPServer(("127.0.0.1", p), _Handler)
                self._httpd.daemon_threads = True
                break
            except OSError as e:
                if e.errno not in (errno.EADDRINUSE, errno.EACCES):
                    raise
                log.warning("UI server port %d in use, trying next", p)
        else:
            raise OSError(
                f"UI server could not bind any port in {candidates}")
        self._httpd.ui = self
        self.port = self._httpd.server_address[1]
        if port and self.port != port:
            log.warning("UI server requested port %d but bound %d",
                        port, self.port)
        log.info("UI server listening on http://127.0.0.1:%d", self.port)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="dl4j:ui:serve")
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            # serve_forever returns after shutdown(); join so stop()
            # means stopped and worker errors can't outlive the server
            if self._thread is not None:
                self._thread.join(timeout=5.0)
            self._thread = None
        return self
