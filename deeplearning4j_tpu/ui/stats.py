"""Training statistics collection.

Reference capability: deeplearning4j-ui's StatsListener + StatsStorage
(SURVEY.md §2.7/§5 observability): per-iteration score, parameter/update
histograms and ratios, persisted to a storage backend. The vertx browser
dashboard is replaced by JSON-lines storage that any plotting tool reads
(per SURVEY.md §5: 'emit scalars to TensorBoard event files instead of
mapdb/vertx UI first' — JSONL is the dependency-free equivalent)."""

from __future__ import annotations

import json
import time

import numpy as np

from deeplearning4j_tpu.utils.listeners import TrainingListener


class InMemoryStatsStorage:
    def __init__(self):
        self.records: list[dict] = []

    def put(self, record: dict):
        self.records.append(record)

    def listSessionIDs(self):
        return sorted({r["session"] for r in self.records})

    def getRecords(self, session=None):
        if session is None:
            return list(self.records)
        return [r for r in self.records if r["session"] == session]


class FileStatsStorage(InMemoryStatsStorage):
    """JSON-lines file storage (one record per iteration)."""

    def __init__(self, path):
        super().__init__()
        self.path = path

    def put(self, record: dict):
        super().put(record)
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")

    @staticmethod
    def load(path):
        s = FileStatsStorage.__new__(FileStatsStorage)
        s.path = path
        s.records = []
        with open(path) as f:
            for line in f:
                if line.strip():
                    s.records.append(json.loads(line))
        return s


class StatsListener(TrainingListener):
    """Collects score + per-layer param/update statistics every N
    iterations (reference: StatsListener(statsStorage, frequency))."""

    def __init__(self, storage, frequency=1, sessionId=None,
                 collectHistograms=False):
        self.storage = storage
        self.frequency = frequency
        self.session = sessionId or f"session_{int(time.time())}"
        self.collectHistograms = collectHistograms
        self._prev_params = None

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency != 0:
            return
        record = {
            "session": self.session,
            "iteration": iteration,
            "epoch": epoch,
            "timestamp": time.time(),
            "score": model.score(),
            "layers": {},
        }
        params = getattr(model, "_params", None)
        if params is not None:
            items = (params.items() if isinstance(params, dict)
                     else enumerate(params))
            for li, p in items:
                for k, v in p.items():
                    arr = np.asarray(v)
                    st = {
                        "mean": float(arr.mean()),
                        "std": float(arr.std()),
                        "meanAbs": float(np.abs(arr).mean()),
                    }
                    if self.collectHistograms:
                        hist, edges = np.histogram(arr, bins=20)
                        st["histogram"] = hist.tolist()
                        st["edges"] = edges.tolist()
                    record["layers"][f"{li}_{k}"] = st
        self.storage.put(record)
