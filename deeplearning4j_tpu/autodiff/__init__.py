"""SameDiff-capability graph autodiff (reference:
org.nd4j.autodiff.samediff.* — SURVEY.md §2.3 "SameDiff", §3.4).

TPU-first inversion: the reference interprets the graph op-by-op in Java with
per-op JNI dispatch (and per-op doDiff rules for the backward graph). Here the
graph lowers ONCE to a pure jax function; autodiff is jax.grad of the lowered
function (no per-op doDiff needed) and the whole train step (forward+backward+
updater) compiles to a single XLA executable with donated parameters —
SURVEY.md §7's "center of gravity".
"""

from deeplearning4j_tpu.autodiff.samediff import (
    SameDiff,
    SDVariable,
    TrainingConfig,
    VariableType,
)

__all__ = ["SameDiff", "SDVariable", "TrainingConfig", "VariableType"]
