"""SameDiff: define-then-run graph with whole-graph XLA compilation.

Reference capability: org.nd4j.autodiff.samediff.SameDiff / SDVariable /
internal.{InferenceSession, TrainingSession} (SURVEY.md §2.3, §3.4). The
reference interprets the graph op-by-op in the JVM with per-op JNI dispatch
and builds an explicit backward graph from per-op doDiff rules. Here:

  - the op graph lowers once to a pure jax function (topological execution
    over the pruned ancestor set);
  - gradients are jax.grad of the lowered function — correct for every op
    in the registry without any doDiff rules;
  - fit() compiles forward+backward+updater into ONE XLA executable with
    donated parameter/updater-state buffers (device-resident params);
  - executables are cached per (outputs, training) and re-specialized by
    jax on shape changes (the executable-cache role of libnd4j's
    GraphExecutioner, SURVEY.md §2.1 item 7).
"""

from __future__ import annotations

import json
import zipfile
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff.ops import OPS, RANDOM_OPS, TRAINING_AWARE_OPS
from deeplearning4j_tpu.ndarray import INDArray
from deeplearning4j_tpu.optimize.updaters import IUpdater, Sgd, updater_from_config


class VariableType(Enum):
    VARIABLE = "VARIABLE"        # trainable
    CONSTANT = "CONSTANT"
    PLACEHOLDER = "PLACEHOLDER"
    ARRAY = "ARRAY"              # op output


@dataclass
class Op:
    fn_name: str
    inputs: list          # input var names
    outputs: list         # output var names
    attrs: dict


class _TraceUnsupported(Exception):
    """Raised when a control-flow body cannot be traced into a child
    SameDiff graph (e.g. it calls jnp functions directly instead of the
    SDVariable op surface). The builder then falls back to storing the
    raw callable: the graph still runs, but cannot be save()d."""


class _CaptureError(_TraceUnsupported):
    """A body captured an outer variable with no build-time value (a
    placeholder or op output). Unlike other trace failures this cannot
    work at runtime either — the raw-callable fallback would leak an
    SDVariable into jnp tracing — so it is a hard build-time error."""


class SubGraph:
    """A control-flow body as a named child SameDiff graph: the
    serializable representation of whileLoop/ifCond/scan/forLoop bodies
    (reference analog: FlatBuffers function defs in libnd4j's graph
    scheme, SURVEY.md §2.1). arg_names are the child placeholders fed
    positionally; out_names the child variables returned."""

    def __init__(self, graph: "SameDiff", arg_names: list,
                 out_names: list):
        self.graph = graph
        self.arg_names = list(arg_names)
        self.out_names = list(out_names)

    def callable(self, squeeze: bool = False):
        """Compile the child graph into a plain jnp-arrays callable with
        the signature control-flow op kernels expect.

        Random ops are rejected: the body runs with a fixed RNG detached
        from the parent graph's stream, so a random op would draw the
        SAME values on every call and every loop iteration — a silent
        correctness trap (ADVICE r3). (Inference-mode dropout is fine:
        it is deterministic at training=False.)"""
        for o in self.graph._ops:
            if o.fn_name in RANDOM_OPS and o.fn_name not in \
                    TRAINING_AWARE_OPS:
                raise ValueError(
                    f"control-flow body contains random op "
                    f"{o.fn_name!r} ({o.outputs[0]!r}): loop/branch "
                    "bodies run with a fixed RNG key, so every call and "
                    "every iteration would draw identical values. Hoist "
                    "the random draw out of the body and pass it in as a "
                    "loop variable instead.")
        fn = self.graph._make_fn(tuple(self.out_names), training=False)
        params, consts = self.graph._split_values()
        arg_names, out_names = self.arg_names, self.out_names
        import jax as _jax

        rng = _jax.random.key(0)

        def call(*args):
            feeds = dict(zip(arg_names, args))
            outs = fn(feeds, params, consts, rng)
            res = tuple(outs[n] for n in out_names)
            return res[0] if (squeeze and len(res) == 1) else res

        return call

    def to_dict(self, value_sink=None, prefix="") -> dict:
        """Serializable dict. Child-graph VALUES (captured constants can
        be weight-matrix sized) go into `value_sink` — the parent's npz
        dict — under prefixed keys, not into the JSON; the tiny scalar
        fallback inlines them when no sink is provided (in-memory use)."""
        # forward the sink so doubly-nested control-flow bodies also land
        # their captured values in the npz instead of inlining JSON lists
        d = self.graph._graph_dict(value_sink=value_sink,
                                   prefix=prefix or "__sub__/")
        if value_sink is not None:
            d["value_keys"] = {}
            for k, v in self.graph._values.items():
                sk = f"{prefix}{k}"
                value_sink[sk] = np.asarray(v)
                d["value_keys"][k] = sk
        else:
            d["values"] = {
                k: {"dtype": str(np.dtype(v.dtype)),
                    "data": np.asarray(v).tolist()}
                for k, v in self.graph._values.items()
            }
        return {"args": self.arg_names, "outs": self.out_names,
                "graph": d}

    @staticmethod
    def from_dict(d: dict, value_source=None) -> "SubGraph":
        child = SameDiff._from_graph_dict(d["graph"],
                                          value_source=value_source)
        if "value_keys" in d["graph"]:
            for k, sk in d["graph"]["value_keys"].items():
                child._values[k] = jnp.asarray(value_source[sk])
        else:
            for k, spec in d["graph"].get("values", {}).items():
                child._values[k] = jnp.asarray(
                    np.asarray(spec["data"], np.dtype(spec["dtype"])))
        return SubGraph(child, d["args"], d["outs"])


def _trace_subgraph(fn, n_args) -> SubGraph:
    """Trace a Python body callable into a child SameDiff graph by calling
    it with child placeholders. Raises _TraceUnsupported when the body
    escapes the SDVariable op surface."""
    child = SameDiff()
    child._tracing = True
    arg_names = [f"__arg{i}" for i in range(n_args)]
    phs = [child.placeHolder(n) for n in arg_names]
    try:
        outs = fn(*phs)
    except _TraceUnsupported:
        raise
    except Exception as e:
        raise _TraceUnsupported(
            f"body is not traceable over SDVariables ({type(e).__name__}: "
            f"{e}); it will be stored as a raw callable and the graph "
            f"will not be serializable") from e
    finally:
        child._tracing = False
    outs = outs if isinstance(outs, tuple) else (outs,)
    if not all(isinstance(o, SDVariable) and o.sd is child for o in outs):
        raise _TraceUnsupported(
            "body returned non-SDVariable outputs during tracing")
    return SubGraph(child, arg_names, [o.name() for o in outs])


# which op attrs hold sub-graph bodies, and the callable attr + squeeze
# behavior each one feeds (squeeze: whileLoop's cond must return a scalar,
# not a 1-tuple)
_SUBGRAPH_ATTRS = {
    "cond_graph": ("cond_fn", True),
    "body_graph": ("body_fn", False),
    "true_graph": ("true_fn", False),
    "false_graph": ("false_fn", False),
}


def _unwrap_value(v):
    if isinstance(v, INDArray):
        return v.jax()
    return jnp.asarray(v)


class SDVariable:
    def __init__(self, sd: "SameDiff", name: str, vtype: VariableType,
                 shape=None, dtype=jnp.float32):
        self.sd = sd
        self._name = name
        self.variableType = vtype
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype

    def name(self) -> str:
        return self._name

    def getShape(self):
        return self._shape

    # -- graph-building arithmetic -----------------------------------------
    def _bin(self, opname, other, rev=False):
        # when operands come from different graphs (control-flow body
        # tracing mixes child placeholders with captured parent vars),
        # build the op on the graph BEING TRACED regardless of operand
        # order — `outer_const + loop_var` must behave like
        # `loop_var + outer_const`
        sd = self.sd
        if (isinstance(other, SDVariable) and other.sd is not sd
                and getattr(other.sd, "_tracing", False)
                and not getattr(sd, "_tracing", False)):
            sd = other.sd
        a = sd._as_var(self)
        b = sd._as_var(other)
        if rev:
            a, b = b, a
        return sd._op(opname, [a, b])

    def add(self, o):
        return self._bin("add", o)

    def sub(self, o):
        return self._bin("sub", o)

    def mul(self, o):
        return self._bin("mul", o)

    def div(self, o):
        return self._bin("div", o)

    def rsub(self, o):
        return self._bin("sub", o, rev=True)

    def rdiv(self, o):
        return self._bin("div", o, rev=True)

    def pow(self, o):
        return self._bin("pow", o)

    def squaredDifference(self, o):
        return self._bin("squaredDifference", o)

    __add__ = add
    __radd__ = add
    __sub__ = sub
    __rsub__ = rsub
    __mul__ = mul
    __rmul__ = mul
    __truediv__ = div
    __rtruediv__ = rdiv
    __pow__ = pow

    def __neg__(self):
        return self.sd._op("neg", [self])

    def __matmul__(self, o):
        return self.mmul(o)

    # comparisons (reference: SDVariable.gt/lt/gte/lte/eq/neq)
    def gt(self, o):
        return self._bin("gt", o)

    def lt(self, o):
        return self._bin("lt", o)

    def gte(self, o):
        return self._bin("gte", o)

    def lte(self, o):
        return self._bin("lte", o)

    def eq(self, o):
        return self._bin("eq", o)

    def neq(self, o):
        return self._bin("neq", o)

    __gt__ = gt
    __lt__ = lt
    __ge__ = gte
    __le__ = lte

    def all(self, *dims, keepDims=False):
        return self._red("all", dims, keepDims)

    def any(self, *dims, keepDims=False):
        return self._red("any", dims, keepDims)

    def neg(self):
        return self.sd._op("neg", [self])

    def mmul(self, o, transposeA=False, transposeB=False):
        return self.sd._op(
            "matmul", [self, self.sd._as_var(o)],
            {"transposeA": transposeA, "transposeB": transposeB},
        )

    def dot(self, o, *dims):
        return self.sd._op(
            "dot", [self, self.sd._as_var(o)],
            {"dimensions": list(dims) or None},
        )

    # shape ops
    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return self.sd._op("reshape", [self], {"shape": list(shape)})

    def transpose(self):
        return self.sd._op("transpose", [self])

    def permute(self, *dims):
        return self.sd._op("permute", [self], {"dimensions": list(dims)})

    def castTo(self, dtype):
        return self.sd._op("cast", [self], {"dtype": dtype})

    # reductions
    def _red(self, opname, dims, keepDims=False):
        return self.sd._op(
            opname, [self], {"dimensions": list(dims) or None, "keepDims": keepDims}
        )

    def sum(self, *dims, keepDims=False):
        return self._red("sum", dims, keepDims)

    def mean(self, *dims, keepDims=False):
        return self._red("mean", dims, keepDims)

    def max(self, *dims, keepDims=False):
        return self._red("max", dims, keepDims)

    def min(self, *dims, keepDims=False):
        return self._red("min", dims, keepDims)

    def prod(self, *dims, keepDims=False):
        return self._red("prod", dims, keepDims)

    def norm1(self, *dims):
        return self._red("norm1", dims)

    def norm2(self, *dims):
        return self._red("norm2", dims)

    def std(self, biasCorrected=True, *dims):
        return self.sd._op(
            "standardDeviation", [self],
            {"dimensions": list(dims) or None, "biasCorrected": biasCorrected},
        )

    def argmax(self, dim=None):
        return self.sd._op("argmax", [self], {"dimension": dim})

    def argmin(self, dim=None):
        return self.sd._op("argmin", [self], {"dimension": dim})

    # misc
    def get(self, idx):
        raise NotImplementedError("use sd.stridedSlice / sd.gather")

    def rename(self, new_name: str) -> "SDVariable":
        self.sd._rename(self._name, new_name)
        return self

    def markAsLoss(self):
        if self._name not in self.sd._loss_vars:
            self.sd._loss_vars.append(self._name)
        return self

    def isPlaceHolder(self):
        return self.variableType == VariableType.PLACEHOLDER

    # -- execution ----------------------------------------------------------
    def eval(self, feeds: dict | None = None) -> INDArray:
        return self.sd.output(feeds or {}, self._name)[self._name]

    def getArr(self) -> INDArray:
        if self.variableType in (VariableType.VARIABLE, VariableType.CONSTANT):
            return INDArray(self.sd._values[self._name])
        return self.eval()

    def setArr(self, value):
        self.sd._values[self._name] = _unwrap_value(value)
        return self

    def __repr__(self):
        return (f"SDVariable(name={self._name!r}, "
                f"type={self.variableType.value}, shape={self._shape})")


# ---------------------------------------------------------------------------
# op namespaces (reference: SDOps families SDMath/SDNN/SDCNN/SDRNN/SDLoss/
# SDRandom on the SameDiff object, SURVEY.md §2.3)
# ---------------------------------------------------------------------------

class _Namespace:
    _passthrough: tuple = ()

    def __init__(self, sd: "SameDiff"):
        self.sd = sd

    def __getattr__(self, item):
        if item in type(self)._passthrough:
            def f(*inputs, name=None, **attrs):
                vars_ = [self.sd._as_var(v) for v in inputs]
                return self.sd._op(item, vars_, attrs, name=name)

            return f
        raise AttributeError(item)


class SDMath(_Namespace):
    _passthrough = (
        "add", "sub", "mul", "div", "rsub", "rdiv", "pow", "neg", "abs",
        "exp", "log", "log1p", "sqrt", "square", "reciprocal", "sign",
        "floor", "ceil", "round", "sin", "cos", "tan", "asin", "acos",
        "atan", "sinh", "cosh", "tanh", "erf", "isnan", "isinf", "matmul",
        "tensorMmul", "dot", "cumsum", "cumprod", "sum", "mean", "max",
        "min", "prod", "norm1", "norm2", "normMax", "logSumExp", "moments",
        "variance", "standardDeviation", "countNonZero", "eq", "neq", "gt",
        "gte", "lt", "lte", "and_op", "or_op", "not_op", "xor_op",
        "maximum", "minimum", "clipByValue", "clipByNorm", "standardize",
        "squaredDifference", "floordiv", "mod", "diag", "invertPermutation",
        "reverse", "argmax", "argmin", "atan2", "expm1", "asinh", "acosh",
        "atanh", "erfc", "lgamma", "digamma", "igamma", "igammac",
        "betainc", "segmentSum", "segmentMax", "segmentMin", "segmentMean",
        "segmentProd", "unsortedSegmentSum", "unsortedSegmentMax",
        "unsortedSegmentMin", "unsortedSegmentMean", "unsortedSegmentProd",
        "topK", "inTopK", "confusionMatrix", "bincount", "zeroFraction",
        "trace",
    )


class SDNN(_Namespace):
    _passthrough = (
        "sigmoid", "relu", "relu6", "elu", "selu", "gelu", "softplus",
        "softsign", "swish", "mish", "hardSigmoid", "hardTanh", "leakyRelu",
        "prelu", "softmax", "logSoftmax", "layerNorm", "batchNorm",
        "dropout", "dotProductAttention", "multiHeadDotProductAttention",
        "pad", "rationalTanh", "rectifiedTanh",
    )

    def linear(self, x, w, b=None, name=None):
        y = self.sd._op("matmul", [x, w])
        if b is not None:
            y = self.sd._op("add", [y, b], name=name)
        return y

    def reluLayer(self, x, w, b, name=None):
        return self.sd._op("relu", [self.linear(x, w, b)], name=name)


class SDCNN(_Namespace):
    _passthrough = (
        "conv2d", "conv1d", "conv3d", "depthwiseConv2d", "deconv2d",
        "maxPooling2d", "avgPooling2d", "maxPooling3d", "avgPooling3d",
        "globalAvgPooling", "upsampling2d", "im2col",
    )


class SDRNN(_Namespace):
    _passthrough = ("lstmCell", "gruCell", "lstmLayer", "gruLayer",
                    "simpleRnnLayer")


class SDLoss(_Namespace):
    _passthrough = (
        "softmaxCrossEntropy", "sparseSoftmaxCrossEntropy",
        "sigmoidCrossEntropy", "meanSquaredError", "absoluteDifference",
        "huberLoss", "logLoss", "hingeLoss", "cosineDistance",
        "klDivergence", "ctcLoss",
    )

    def __getattr__(self, item):
        f = super().__getattr__(item)

        def g(*inputs, name=None, **attrs):
            v = f(*inputs, name=name, **attrs)
            v.markAsLoss()
            return v

        return g


class SDLinalg(_Namespace):
    """Reference: org.nd4j.autodiff.samediff.ops.SDLinalg (cholesky,
    solve, svd, qr, lu, matrix inverse/determinant, band part)."""

    _passthrough = (
        "cholesky", "solve", "triangularSolve", "matrixInverse",
        "matrixDeterminant", "logdet", "svd", "qr", "lu", "lstsq",
        "matrixBandPart", "triu", "tril", "diagPart", "trace", "matmul",
    )


class SDImage(_Namespace):
    """Reference: org.nd4j.autodiff.samediff.ops.SDImage (resize ops,
    extract patches, space/batch/depth rearrangements; NCHW layout)."""

    _passthrough = (
        "imageResize", "extractImagePatches", "spaceToDepth",
        "depthToSpace", "spaceToBatch", "batchToSpace",
        "nonMaxSuppression",
    )


class SDRandom(_Namespace):
    def normal(self, mean, stddev, *shape, name=None):
        return self.sd._op(
            "randomNormal", [], {"shape": list(shape), "mean": mean,
                                 "stddev": stddev}, name=name)

    def uniform(self, low, high, *shape, name=None):
        return self.sd._op(
            "randomUniform", [], {"shape": list(shape), "min": low,
                                  "max": high}, name=name)

    def bernoulli(self, p, *shape, name=None):
        return self.sd._op(
            "randomBernoulli", [], {"shape": list(shape), "p": p}, name=name)

    def gamma(self, alpha, beta, *shape, name=None):
        return self.sd._op(
            "randomGamma", [], {"shape": list(shape), "alpha": alpha,
                                "beta": beta}, name=name)

    def poisson(self, lam, *shape, name=None):
        return self.sd._op(
            "randomPoisson", [], {"shape": list(shape), "lam": lam},
            name=name)

    def exponential(self, lam, *shape, name=None):
        return self.sd._op(
            "randomExponential", [], {"shape": list(shape), "lam": lam},
            name=name)

    def truncatedNormal(self, mean, stddev, *shape, name=None):
        return self.sd._op(
            "truncatedNormal", [], {"shape": list(shape), "mean": mean,
                                    "stddev": stddev}, name=name)


# ---------------------------------------------------------------------------

@dataclass
class TrainingConfig:
    """Reference: org.nd4j.autodiff.samediff.TrainingConfig (SURVEY.md §2.3)."""

    updater: IUpdater = field(default_factory=lambda: Sgd(1e-2))
    dataSetFeatureMapping: Sequence[str] = ()
    dataSetLabelMapping: Sequence[str] = ()
    lossVariables: Sequence[str] = ()
    l1: float = 0.0
    l2: float = 0.0
    weightDecay: float = 0.0
    minimize: bool = True

    def to_json(self):
        return {
            "updater": self.updater.to_json(),
            "dataSetFeatureMapping": list(self.dataSetFeatureMapping),
            "dataSetLabelMapping": list(self.dataSetLabelMapping),
            "lossVariables": list(self.lossVariables),
            "l1": self.l1, "l2": self.l2, "weightDecay": self.weightDecay,
            "minimize": self.minimize,
        }

    @staticmethod
    def from_json(d):
        d = dict(d)
        d["updater"] = updater_from_config(d["updater"])
        return TrainingConfig(**d)


class History:
    """fit() result (reference: org.nd4j.autodiff.listeners.records.History)."""

    def __init__(self):
        self.lossCurve = []      # per-epoch mean loss
        self.iterLosses = []

    def finalTrainingLoss(self):
        return self.lossCurve[-1] if self.lossCurve else None


class SameDiff:
    MULTI_OUTPUT_OPS = {"moments": 2, "lstmCell": 2, "lstmLayer": 3,
                        "gruLayer": 2, "simpleRnnLayer": 2, "svd": 3,
                        "qr": 2, "lu": 2, "topK": 2}

    def __init__(self):
        self._ops: list[Op] = []
        self._vars: dict[str, SDVariable] = {}
        self._values: dict[str, jax.Array] = {}   # VARIABLE + CONSTANT values
        self._producer: dict[str, int] = {}       # var name -> op index
        self._loss_vars: list[str] = []
        self._name_counter = 0
        self.trainingConfig: TrainingConfig | None = None
        self._train_step_fn = None
        self._updater_state = None
        self._step = 0
        self._fn_cache: dict = {}
        self._seed = 0
        self._profiler_cfg = None  # ProfilerConfig for NAN_PANIC checks
        # namespaces
        self.math = SDMath(self)
        self.nn = SDNN(self)
        self.cnn = SDCNN(self)
        self.rnn = SDRNN(self)
        self.loss = SDLoss(self)
        self.random = SDRandom(self)
        self.linalg = SDLinalg(self)
        self.image = SDImage(self)

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    # -- variable creation --------------------------------------------------
    def _unique(self, base: str) -> str:
        if base not in self._vars:
            return base
        while True:
            self._name_counter += 1
            cand = f"{base}_{self._name_counter}"
            if cand not in self._vars:
                return cand

    def placeHolder(self, name: str, dtype=jnp.float32, *shape) -> SDVariable:
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        v = SDVariable(self, name, VariableType.PLACEHOLDER, shape or None, dtype)
        self._vars[name] = v
        return v

    def var(self, name: str, *args, dtype=jnp.float32) -> SDVariable:
        """var(name, array) | var(name, *shape) (zeros) |
        var(name, init_fn, *shape) where init_fn(key, shape)->array."""
        name = self._unique(name)
        if len(args) == 1 and isinstance(
            args[0], (list, np.ndarray, jnp.ndarray, INDArray)
        ):
            val = _unwrap_value(args[0])
        elif args and callable(args[0]):
            shape = tuple(
                args[1]) if len(args) == 2 and isinstance(
                args[1], (list, tuple)) else tuple(args[1:])
            # stable per-name key: crc32, not hash() (which is salted per
            # interpreter and would make initialization nondeterministic)
            import zlib

            key = jax.random.key(
                zlib.crc32(name.encode()) % (2**31) + self._seed)
            val = jnp.asarray(args[0](key, shape), dtype=dtype)
        else:
            shape = tuple(
                args[0]) if len(args) == 1 and isinstance(
                args[0], (list, tuple)) else tuple(args)
            val = jnp.zeros(shape, dtype)
        v = SDVariable(self, name, VariableType.VARIABLE,
                       tuple(val.shape), val.dtype)
        self._vars[name] = v
        self._values[name] = val
        return v

    def constant(self, name_or_value, value=None) -> SDVariable:
        if value is None:
            name, value = self._unique("const"), name_or_value
        else:
            name = self._unique(name_or_value)
        val = _unwrap_value(value)
        v = SDVariable(self, name, VariableType.CONSTANT,
                       tuple(val.shape), val.dtype)
        self._vars[name] = v
        self._values[name] = val
        return v

    def _as_var(self, x) -> SDVariable:
        if isinstance(x, SDVariable):
            if x.sd is not self:
                return self._capture_foreign(x)
            return x
        return self.constant(x)

    def _capture_foreign(self, var: SDVariable) -> SDVariable:
        """A body closure referenced a variable of ANOTHER graph (the
        parent, during control-flow body tracing): snapshot its current
        value into this graph as a captured constant — the captured-
        constant table that makes control-flow bodies serializable."""
        name = f"__cap_{var.name()}"
        if name in self._vars:
            return self._vars[name]
        src = var.sd
        if var.name() not in src._values:
            raise _CaptureError(
                f"control-flow body captures {var.name()!r}, which has no "
                f"value at build time (placeholders/op outputs cannot be "
                f"captured; pass them as explicit loop variables)")
        if var.variableType == VariableType.VARIABLE:
            # a snapshot would silently FREEZE the trainable param inside
            # the body (updates and gradients would never reach it)
            raise _CaptureError(
                f"control-flow body captures trainable variable "
                f"{var.name()!r}; a build-time snapshot would freeze it — "
                f"pass it as an explicit loop variable instead")
        val = src._values[var.name()]
        v = SDVariable(self, name, VariableType.CONSTANT,
                       tuple(val.shape), val.dtype)
        self._vars[name] = v
        self._values[name] = val
        return v

    def convertToConstant(self, var: SDVariable):
        var.variableType = VariableType.CONSTANT
        return var

    def convertToVariable(self, var: SDVariable):
        var.variableType = VariableType.VARIABLE
        return var

    def _rename(self, old: str, new: str):
        if new in self._vars:
            raise ValueError(f"variable {new!r} already exists")
        v = self._vars.pop(old)
        v._name = new
        self._vars[new] = v
        if old in self._values:
            self._values[new] = self._values.pop(old)
        if old in self._producer:
            self._producer[new] = self._producer.pop(old)
        for op_ in self._ops:
            op_.inputs = [new if n == old else n for n in op_.inputs]
            op_.outputs = [new if n == old else n for n in op_.outputs]
        self._loss_vars = [new if n == old else n for n in self._loss_vars]
        self._invalidate()

    # -- op construction ----------------------------------------------------
    def _op(self, fn_name: str, inputs: list, attrs: dict | None = None,
            name: str | None = None, n_out: int | None = None):
        if fn_name not in OPS:
            raise ValueError(f"unknown op {fn_name!r}")
        attrs = {k: v for k, v in (attrs or {}).items() if v is not None}
        n_out = n_out or self.MULTI_OUTPUT_OPS.get(fn_name, 1)
        base = name or fn_name
        out_names = [
            self._unique(base if i == 0 else f"{base}:{i}")
            for i in range(n_out)
        ]
        op_idx = len(self._ops)
        self._ops.append(Op(fn_name, [v.name() for v in inputs],
                            out_names, attrs))
        outs = []
        for on in out_names:
            v = SDVariable(self, on, VariableType.ARRAY)
            self._vars[on] = v
            self._producer[on] = op_idx
            outs.append(v)
        self._invalidate()
        return outs[0] if n_out == 1 else tuple(outs)

    def _invalidate(self):
        """Drop every compiled executable after a graph mutation."""
        self._fn_cache.clear()
        self._train_step_fn = None

    # convenience graph ops on sd itself
    def one_hot(self, x, depth, name=None):
        return self._op("oneHot", [self._as_var(x)], {"depth": depth}, name)

    def gather(self, x, indices, axis=0, name=None):
        return self._op("gather", [self._as_var(x), self._as_var(indices)],
                        {"axis": axis}, name)

    def concat(self, dim, *vars_, name=None):
        return self._op("concat", [self._as_var(v) for v in vars_],
                        {"dimension": dim}, name)

    def stack(self, axis, *vars_, name=None):
        return self._op("stack", [self._as_var(v) for v in vars_],
                        {"axis": axis}, name)

    def unstack(self, x, axis, num, name=None):
        return self._op("unstack", [self._as_var(x)],
                        {"axis": axis, "num": num}, name, n_out=num)

    def split(self, x, numSplit, dimension, name=None):
        return self._op("split", [self._as_var(x)],
                        {"numSplit": numSplit, "dimension": dimension},
                        name, n_out=numSplit)

    def stridedSlice(self, x, begin, end, strides=None, name=None):
        return self._op("stridedSlice", [self._as_var(x)],
                        {"begin": list(begin), "end": list(end),
                         "strides": list(strides) if strides else None}, name)

    def expandDims(self, x, axis, name=None):
        return self._op("expandDims", [self._as_var(x)], {"axis": axis}, name)

    def squeeze(self, x, axis, name=None):
        return self._op("squeeze", [self._as_var(x)], {"axis": axis}, name)

    def where(self, cond, x, y, name=None):
        return self._op("where_op",
                        [self._as_var(cond), self._as_var(x), self._as_var(y)],
                        {}, name)

    def identity(self, x, name=None):
        return self._op("identity", [self._as_var(x)], {}, name)

    # -- control flow (reference: SDBaseOps.whileLoop/ifCond; TF
    # Enter/Exit/Merge/Switch interpreted as whole loops, SURVEY.md §3.4).
    # Bodies are Python callables; at build time each body is TRACED over
    # child-graph placeholder SDVariables into a named sub-SameDiff graph
    # (closure-captured outer constants become a captured-constant table),
    # so graphs holding control flow serialize like any other op — the
    # analog of the reference's FlatBuffers function defs. Bodies that
    # escape the SDVariable surface (raw jnp calls) fall back to the
    # callable itself: they run and differentiate but cannot be save()d.
    def _body_attrs(self, graph_key: str, fn, n_args: int) -> dict:
        fn_key, squeeze = _SUBGRAPH_ATTRS[graph_key]
        try:
            sub = _trace_subgraph(fn, n_args)
            return {graph_key: sub, fn_key: sub.callable(squeeze=squeeze)}
        except _CaptureError as e:
            raise ValueError(str(e)) from e
        except _TraceUnsupported:
            return {fn_key: fn}

    def whileLoop(self, condBody, loopBody, *loopVars, name=None):
        """loopVars -> final vars after `while condBody(*v): v =
        loopBody(*v)`. Forward-only (XLA while has no reverse-mode)."""
        vs = [self._as_var(v) for v in loopVars]
        attrs = self._body_attrs("cond_graph", condBody, len(vs))
        attrs.update(self._body_attrs("body_graph", loopBody, len(vs)))
        return self._op("whileLoop", vs, attrs,
                        name, n_out=len(vs) if len(vs) > 1 else 1)

    def ifCond(self, predicate, trueBody, falseBody, *operands, name=None,
               n_out=1):
        ops_ = [self._as_var(v) for v in operands]
        attrs = self._body_attrs("true_graph", trueBody, len(ops_))
        attrs.update(self._body_attrs("false_graph", falseBody, len(ops_)))
        return self._op("ifCond", [self._as_var(predicate)] + ops_,
                        attrs, name, n_out=n_out)

    def scan(self, body, init, xs, name=None):
        """lax.scan: body(carry, x) -> (carry, y). Returns
        (final_carry, stacked_ys); reverse-mode differentiable."""
        return self._op("scanOp", [self._as_var(init), self._as_var(xs)],
                        self._body_attrs("body_graph", body, 2),
                        name, n_out=2)

    def forLoop(self, n, body, *loopVars, name=None):
        """n fixed iterations of body(i, *vars) (lax.fori_loop)."""
        vs = [self._as_var(v) for v in loopVars]
        attrs = {"n": int(n)}
        attrs.update(self._body_attrs("body_graph", body, 1 + len(vs)))
        return self._op("forLoop", vs, attrs,
                        name, n_out=len(vs) if len(vs) > 1 else 1)

    def getVariable(self, name: str) -> SDVariable:
        return self._vars[name]

    def hasVariable(self, name: str) -> bool:
        return name in self._vars

    def variables(self):
        return [v for v in self._vars.values()
                if v.variableType == VariableType.VARIABLE]

    def variableNames(self):
        return [v.name() for v in self.variables()]

    def setLossVariables(self, *names):
        self._loss_vars = [n.name() if isinstance(n, SDVariable) else n
                           for n in names]
        self._train_step_fn = None

    def getLossVariables(self):
        return list(self._loss_vars)

    # -- execution core -----------------------------------------------------
    def _needed_ops(self, wanted: Sequence[str]) -> list[int]:
        needed: set[int] = set()
        stack = [n for n in wanted if n in self._producer]
        while stack:
            n = stack.pop()
            idx = self._producer.get(n)
            if idx is None or idx in needed:
                continue
            needed.add(idx)
            for inp in self._ops[idx].inputs:
                if inp in self._producer:
                    stack.append(inp)
        return sorted(needed)

    def _make_fn(self, outputs: tuple, training: bool):
        op_indices = self._needed_ops(outputs)

        def fn(placeholders: dict, params: dict, consts: dict, rng):
            env = dict(consts)
            env.update(params)
            env.update(placeholders)
            for idx in op_indices:
                o = self._ops[idx]
                # *_graph attrs are the serializable sub-graph bodies; the
                # kernels consume only the compiled *_fn callables
                kwargs = {k: v for k, v in o.attrs.items()
                          if not k.endswith("_graph")}
                fn_name = o.fn_name
                if fn_name in RANDOM_OPS:
                    kwargs["key"] = jax.random.fold_in(rng, idx)
                if fn_name in TRAINING_AWARE_OPS:
                    kwargs["training"] = training
                args = [env[i] for i in o.inputs]
                res = OPS[fn_name](*args, **kwargs)
                if len(o.outputs) == 1:
                    env[o.outputs[0]] = res
                else:
                    for on, r in zip(o.outputs, res):
                        env[on] = r
            return {n: env[n] for n in outputs}

        return fn

    def _split_values(self):
        params, consts = {}, {}
        for n, v in self._values.items():
            if self._vars[n].variableType == VariableType.VARIABLE:
                params[n] = v
            else:
                consts[n] = v
        return params, consts

    def _jitted(self, outputs: tuple, training: bool):
        key = (outputs, training)
        if key not in self._fn_cache:
            fn = self._make_fn(outputs, training)
            self._fn_cache[key] = jax.jit(fn)
        return self._fn_cache[key]

    def output(self, feeds: dict, *outputs) -> dict:
        """Execute the graph for the requested outputs (InferenceSession
        capability; one compiled XLA executable per (outputs, shapes))."""
        names = tuple(
            o.name() if isinstance(o, SDVariable) else o for o in outputs
        )
        feeds = {k: _unwrap_value(v) for k, v in feeds.items()}
        params, consts = self._split_values()
        rng = jax.random.key(self._seed)
        res = self._jitted(names, False)(feeds, params, consts, rng)
        return {k: INDArray(v) for k, v in res.items()}

    def batchOutput(self):
        return _BatchOutputBuilder(self)

    def outputSingle(self, feeds: dict, output) -> INDArray:
        name = output.name() if isinstance(output, SDVariable) else output
        return self.output(feeds, name)[name]

    def exec_all(self, feeds: dict) -> dict:
        names = tuple(self._vars)
        return self.output(feeds, *names)

    # -- gradients -----------------------------------------------------------
    def _loss_value(self, outs: dict):
        total = 0.0
        for lv in (self._loss_vars or list(outs)):
            total = total + jnp.sum(outs[lv])
        return total

    def calculateGradients(self, feeds: dict, *wrt) -> dict:
        """Analytic gradients of the summed loss variables w.r.t. the given
        variable names (replaces the reference's backward-graph construction,
        SURVEY.md §3.4)."""
        if not self._loss_vars:
            raise ValueError("no loss variables; call setLossVariables/markAsLoss")
        wrt_names = [w.name() if isinstance(w, SDVariable) else w for w in wrt]
        feeds = {k: _unwrap_value(v) for k, v in feeds.items()}
        params, consts = self._split_values()
        rng = jax.random.key(self._seed)

        diff_feeds = {n: feeds[n] for n in wrt_names if n in feeds}
        diff_params = {n: params[n] for n in wrt_names if n in params}
        missing = [n for n in wrt_names
                   if n not in diff_feeds and n not in diff_params]
        if missing:
            raise ValueError(
                f"cannot differentiate w.r.t. {missing}: each name must be a "
                f"fed placeholder or a VARIABLE (constants/ARRAY outputs are "
                f"not differentiable targets)")

        cache_key = ("grad", tuple(self._loss_vars), tuple(wrt_names),
                     tuple(sorted(feeds)))
        if cache_key not in self._fn_cache:
            fwd = self._make_fn(tuple(self._loss_vars), False)

            def grad_fn(feeds, params, consts, rng, dfeeds, dparams):
                def loss_fn(dfeeds, dparams):
                    f = dict(feeds)
                    f.update(dfeeds)
                    p = dict(params)
                    p.update(dparams)
                    return self._loss_value(fwd(f, p, consts, rng))

                return jax.grad(loss_fn, argnums=(0, 1))(dfeeds, dparams)

            self._fn_cache[cache_key] = jax.jit(grad_fn)

        gf, gp = self._fn_cache[cache_key](
            feeds, params, consts, rng, diff_feeds, diff_params)
        out = {}
        out.update({k: INDArray(v) for k, v in gf.items()})
        out.update({k: INDArray(v) for k, v in gp.items()})
        return out

    # -- training ------------------------------------------------------------
    def setProfilerConfig(self, cfg):
        """ProfilerConfig with checkForNaN/checkForInf enables per-step
        finite checks (reference: OpProfiler NAN_PANIC, SURVEY.md §2.3)."""
        self._profiler_cfg = cfg
        return self

    def setTrainingConfig(self, cfg: TrainingConfig):
        self.trainingConfig = cfg
        if cfg.lossVariables:
            self._loss_vars = list(cfg.lossVariables)
        self._updater_state = None
        self._train_step_fn = None

    def _build_train_step(self):
        cfg = self.trainingConfig
        loss_names = tuple(self._loss_vars)
        fwd = self._make_fn(loss_names, True)
        updater = cfg.updater

        def step_fn(params, opt_state, consts, feeds, rng, step):
            def loss_fn(p):
                outs = fwd(feeds, p, consts, rng)
                loss = self._loss_value(outs)
                if cfg.l2 > 0:
                    loss = loss + cfg.l2 * sum(
                        jnp.sum(w * w) for w in jax.tree_util.tree_leaves(p)
                    )
                if cfg.l1 > 0:
                    loss = loss + cfg.l1 * sum(
                        jnp.sum(jnp.abs(w)) for w in jax.tree_util.tree_leaves(p)
                    )
                return loss if cfg.minimize else -loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            if cfg.weightDecay > 0:
                grads = jax.tree_util.tree_map(
                    lambda g, p: g + cfg.weightDecay * p, grads, params
                )
            updates, opt_state = updater.apply(grads, opt_state, params, step)
            params = jax.tree_util.tree_map(lambda p, u: p - u, params, updates)
            return loss, params, opt_state

        # params+opt state live on device and are donated every step —
        # the PJRT buffer-donation equivalent of the flat-param update in
        # MultiLayerNetwork.fit (SURVEY.md §3.1)
        return jax.jit(step_fn, donate_argnums=(0, 1))

    def fit(self, data=None, epochs: int = 1, listeners=()) -> History:
        """data: iterable of DataSet-like ((features, labels) tuples or
        objects with .getFeatures()/.getLabels()), or a single such batch."""
        if self.trainingConfig is None:
            raise ValueError("call setTrainingConfig first")
        cfg = self.trainingConfig
        if not self._loss_vars:
            raise ValueError("no loss variables set")
        if getattr(self, "_train_step_fn", None) is None:
            self._train_step_fn = self._build_train_step()

        history = History()
        params, consts = self._split_values()
        if self._updater_state is None:
            self._updater_state = cfg.updater.init_state(params)
        opt_state = self._updater_state

        base_key = jax.random.key(self._seed + 7)

        for epoch in range(epochs):
            batches = _as_batches(data)
            if epoch == 0 and not hasattr(data, "reset") and not isinstance(
                batches, (list, tuple)
            ):
                # one-shot iterable (generator): materialize so later epochs
                # see the data instead of silently training on nothing
                batches = list(batches)
                data = batches
            epoch_losses = []
            for ds in batches:
                feats, labels = _split_dataset(ds)
                feeds = {}
                fmap = list(cfg.dataSetFeatureMapping)
                lmap = list(cfg.dataSetLabelMapping)
                for name, arr in zip(fmap, feats):
                    feeds[name] = _unwrap_value(arr)
                for name, arr in zip(lmap, labels):
                    feeds[name] = _unwrap_value(arr)
                rng = jax.random.fold_in(base_key, self._step)
                loss, params, opt_state = self._train_step_fn(
                    params, opt_state, consts, feeds, rng, self._step
                )
                # rebind immediately: the step donated the previous buffers,
                # so self._values must never be left pointing at them (a
                # listener or a mid-fit exception would otherwise observe
                # deleted device arrays)
                for n, v in params.items():
                    self._values[n] = v
                self._updater_state = opt_state
                self._step += 1
                epoch_losses.append(loss)  # device array; no host sync here
                if self._profiler_cfg is not None:
                    from deeplearning4j_tpu.utils.profiler import (
                        nan_panic_check)

                    nan_panic_check(self._profiler_cfg, loss, params,
                                    where="variables",
                                    context=f" at step {self._step}")
                if listeners:
                    lv = float(loss)
                    for listener in listeners:
                        if hasattr(listener, "iterationDone"):
                            listener.iterationDone(self, self._step, epoch, lv)
            if not epoch_losses:
                raise ValueError(
                    f"epoch {epoch}: data yielded no batches (exhausted "
                    f"iterator or empty dataset)")
            epoch_losses = [float(l) for l in jax.device_get(epoch_losses)]
            history.iterLosses.extend(epoch_losses)
            history.lossCurve.append(float(np.mean(epoch_losses)))
        return history

    # -- serde (reference: SameDiff.save/load flatbuffers .fb; here a zip of
    # graph JSON + npz values, same round-trip capability, SURVEY.md §5;
    # control-flow bodies serialize as nested sub-graph dicts) ------------
    def _graph_dict(self, value_sink=None, prefix="__sub__/") -> dict:
        return {
            "variables": [
                {
                    "name": v.name(),
                    "type": v.variableType.value,
                    "shape": list(v._shape) if v._shape else None,
                    "dtype": str(np.dtype(v.dtype)) if v.dtype else "float32",
                }
                for v in self._vars.values()
            ],
            "ops": [
                {"fn": o.fn_name, "inputs": o.inputs, "outputs": o.outputs,
                 "attrs": _json_attrs(o.attrs, value_sink,
                                      prefix=f"{prefix}op{i}/")}
                for i, o in enumerate(self._ops)
            ],
            "lossVariables": self._loss_vars,
        }

    @staticmethod
    def _from_graph_dict(graph: dict, value_source=None) -> "SameDiff":
        sd = SameDiff()
        for vd in graph["variables"]:
            v = SDVariable(
                sd, vd["name"], VariableType(vd["type"]),
                tuple(vd["shape"]) if vd["shape"] else None,
                np.dtype(vd["dtype"]),
            )
            sd._vars[vd["name"]] = v
        for i, od in enumerate(graph["ops"]):
            sd._ops.append(Op(od["fn"], od["inputs"], od["outputs"],
                              _attrs_from_json(od["attrs"], value_source)))
            for on in od["outputs"]:
                sd._producer[on] = i
        sd._loss_vars = graph.get("lossVariables", [])
        return sd

    def save(self, path: str, saveUpdaterState: bool = False):
        # control-flow sub-graph values (captured constants can be weight-
        # sized) ride the binary npz under "__sub__/"-prefixed keys, not
        # the JSON
        vals = {k: np.asarray(v) for k, v in self._values.items()}
        graph = self._graph_dict(value_sink=vals)
        graph.update({
            "trainingConfig": (self.trainingConfig.to_json()
                               if self.trainingConfig else None),
            "step": self._step,
        })
        import io

        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("graph.json", json.dumps(graph, indent=1))
            buf = io.BytesIO()
            np.savez(buf, **vals)
            zf.writestr("values.npz", buf.getvalue())
            if saveUpdaterState and self._updater_state is not None:
                leaves, treedef = jax.tree_util.tree_flatten(self._updater_state)
                sbuf = io.BytesIO()
                np.savez(sbuf, **{str(i): np.asarray(l)
                                  for i, l in enumerate(leaves)})
                zf.writestr("updater_state.npz", sbuf.getvalue())

    @staticmethod
    def load(path: str, loadUpdaterState: bool = False) -> "SameDiff":
        import io

        with zipfile.ZipFile(path) as zf:
            graph = json.loads(zf.read("graph.json"))
            values = np.load(io.BytesIO(zf.read("values.npz")))
            sd = SameDiff._from_graph_dict(graph, value_source=values)
            for k in values.files:
                if not k.startswith("__sub__/"):
                    sd._values[k] = jnp.asarray(values[k])
            sd._step = graph.get("step", 0)
            if graph.get("trainingConfig"):
                sd.trainingConfig = TrainingConfig.from_json(
                    graph["trainingConfig"])
            if loadUpdaterState and "updater_state.npz" in zf.namelist():
                params, _ = sd._split_values()
                proto = sd.trainingConfig.updater.init_state(params)
                leaves, treedef = jax.tree_util.tree_flatten(proto)
                data = np.load(io.BytesIO(zf.read("updater_state.npz")))
                new_leaves = [jnp.asarray(data[str(i)])
                              for i in range(len(leaves))]
                sd._updater_state = jax.tree_util.tree_unflatten(
                    treedef, new_leaves)
        return sd

    def summary(self) -> str:
        lines = [f"SameDiff: {len(self._vars)} variables, {len(self._ops)} ops"]
        for v in self._vars.values():
            if v.variableType != VariableType.ARRAY:
                lines.append(
                    f"  {v.variableType.value:<12} {v.name():<24} {v._shape}"
                )
        for o in self._ops:
            lines.append(
                f"  op {o.fn_name:<20} {','.join(o.inputs)} -> "
                f"{','.join(o.outputs)}"
            )
        return "\n".join(lines)


class _BatchOutputBuilder:
    def __init__(self, sd: SameDiff):
        self.sd = sd
        self._feeds = {}
        self._outputs = []

    def input(self, name, value):
        self._feeds[name.name() if isinstance(name, SDVariable) else name] = value
        return self

    def output(self, *names):
        self._outputs.extend(
            n.name() if isinstance(n, SDVariable) else n for n in names
        )
        return self

    def execute(self) -> dict:
        return self.sd.output(self._feeds, *self._outputs)

    def exec(self) -> dict:
        return self.execute()


def _json_attrs(attrs: dict, value_sink=None, prefix="") -> dict:
    # callables whose sub-graph representation exists serialize as the
    # graph; a callable WITHOUT one is a non-traceable body -> still a
    # hard error (same boundary the reference draws at FlatBuffers
    # function defs)
    graph_backed = {_SUBGRAPH_ATTRS[k][0] for k in attrs
                    if k in _SUBGRAPH_ATTRS}
    out = {}
    for k, v in attrs.items():
        if k in graph_backed:
            continue  # rebuilt from the sub-graph on load
        if isinstance(v, SubGraph):
            out[k] = {"__subgraph__": v.to_dict(value_sink,
                                                prefix=f"{prefix}{k}/")}
            continue
        if callable(v):
            raise ValueError(
                "graph holds a control-flow op whose body could not be "
                "traced into a sub-graph (it escapes the SDVariable op "
                "surface, e.g. by calling jnp functions directly); such "
                "graphs run but cannot be serialized — rewrite the body "
                "over SDVariable ops to make it saveable")
        if isinstance(v, tuple):
            v = list(v)
        elif hasattr(v, "dtype") and hasattr(v, "tolist"):
            v = v.tolist()
        elif isinstance(v, (np.integer, np.floating)):
            v = v.item()
        else:
            try:
                json.dumps(v)
            except TypeError:
                v = str(np.dtype(v))  # dtypes and dtype-like objects
        out[k] = v
    return out


def _attrs_from_json(attrs: dict, value_source=None) -> dict:
    """Inverse of _json_attrs: rebuild SubGraph bodies and their runtime
    callables from nested sub-graph dicts."""
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__subgraph__" in v:
            sub = SubGraph.from_dict(v["__subgraph__"], value_source)
            out[k] = sub
            fn_key, squeeze = _SUBGRAPH_ATTRS[k]
            out[fn_key] = sub.callable(squeeze=squeeze)
        else:
            out[k] = v
    return out


def _host_array(x, dtype=None):
    """Host numpy view WITHOUT bouncing through the device (np.asarray on a
    jnp array is a D2H copy; on numpy it is free)."""
    if hasattr(x, "toNumpy"):
        x = x.toNumpy()
    return np.asarray(x, dtype=dtype)


def _prepare_batches(data, epoch_i, epochs):
    """Batches for one epoch. Materializes a one-shot iterable (generator)
    on the first epoch so later epochs see the data instead of silently
    training on nothing. Returns (batches, data) — rebind data to the
    second element."""
    batches = _as_batches(data)
    if (epoch_i == 0 and epochs > 1 and not hasattr(data, "reset")
            and not isinstance(batches, (list, tuple))):
        batches = list(batches)
        data = batches
    return batches, data


def _ones_mask(labels):
    """Example mask of ones matching the loss's per-example view: [N, T]
    for NCW time-series labels, else [N]."""
    if labels.ndim == 3:
        return np.ones((labels.shape[0], labels.shape[2]), np.float32)
    return np.ones((labels.shape[0],), np.float32)


def _pad_to_bucket(arrs, mask, bucket):
    """Pad batch axis of every array (and the mask) up to `bucket` rows by
    repeating the last row; padding rows get mask 0 so they cannot bias the
    loss. Keeps ONE compiled executable across a ragged final minibatch
    (SURVEY.md §7 hard part 1: recompile storms; the reference never had
    this problem because it never compiled)."""
    n = arrs[0].shape[0]
    if n == bucket:
        return arrs, mask, n
    pad = bucket - n
    out = []
    for a in arrs:
        a = np.asarray(a)
        out.append(np.concatenate([a, np.repeat(a[-1:], pad, axis=0)],
                                  axis=0))
    mask = np.concatenate(
        [np.asarray(mask),
         np.zeros((pad,) + np.asarray(mask).shape[1:], np.float32)], axis=0)
    return out, mask, n


def _as_batches(data):
    if data is None:
        raise ValueError("fit() requires data")
    if isinstance(data, (tuple,)) and len(data) == 2 and not isinstance(
        data[0], (tuple, list)
    ):
        return [data]
    if hasattr(data, "getFeatures") or hasattr(data, "features"):
        return [data]
    if hasattr(data, "reset"):
        data.reset()
    return data


def _split_dataset(ds):
    """Accept (features, labels) tuples, DataSet-like objects, or
    MultiDataSet-like (lists of arrays)."""
    if isinstance(ds, tuple) and len(ds) == 2:
        f, l = ds
    elif hasattr(ds, "getFeatures"):
        f, l = ds.getFeatures(), ds.getLabels()
    else:
        f, l = ds.features, ds.labels
    if not isinstance(f, (list, tuple)):
        f = [f]
    if not isinstance(l, (list, tuple)):
        l = [l]
    return f, l


def _split_dataset_full(ds):
    """Like _split_dataset but also returns (featuresMasks, labelsMasks)
    lists (None entries when absent). Reference: DataSet.getFeaturesMaskArray
    / getLabelsMaskArray — masks mark valid timesteps for variable-length
    sequences and MUST reach the loss (SURVEY.md §2.5 masking row)."""
    f, l = _split_dataset(ds)
    fm = lm = None
    if hasattr(ds, "getFeaturesMaskArray"):
        fm = ds.getFeaturesMaskArray()
        lm = ds.getLabelsMaskArray()
    elif hasattr(ds, "featuresMasks"):
        fm, lm = ds.featuresMasks, ds.labelsMasks
    elif hasattr(ds, "featuresMask"):
        fm, lm = ds.featuresMask, ds.labelsMask
    if not isinstance(fm, (list, tuple)):
        fm = [fm] * len(f) if fm is None else [fm]
    if not isinstance(lm, (list, tuple)):
        lm = [lm] * len(l) if lm is None else [lm]
    return f, l, fm, lm
