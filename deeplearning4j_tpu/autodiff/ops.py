"""Op registry: pure jnp/lax emitter functions for every SameDiff op.

This is the TPU-native collapse of libnd4j's declarable-op layer
(SURVEY.md §2.1 "Declarable (custom) ops", ~500-700 CUDA/C++ kernels in
libnd4j/include/ops/declarable/): each entry is a pure function XLA fuses
and differentiates, replacing {generic impl + cuda helper + cudnn platform
helper + hand-written doDiff} per op.

Conventions:
  - fn(*inputs, **attrs) -> jnp array or tuple of arrays
  - ops in RANDOM_OPS receive a `key=` jax PRNG key kwarg at execution
  - ops in TRAINING_AWARE_OPS receive `training=` bool kwarg
  - conv/pool use NCHW activations and [out, in, kH, kW] weights, matching
    DL4J's layout (libnd4j conv2d); lowered to lax.conv_general_dilated which
    XLA maps onto the MXU.
"""

from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
from jax import lax

# ---------------------------------------------------------------------------
# elementwise / transforms
# ---------------------------------------------------------------------------

def _identity(x):
    return x


def _axis(dims, ndim):
    if dims is None or dims == () or dims == []:
        return None
    if isinstance(dims, int):
        dims = (dims,)
    return tuple(d % ndim for d in dims)


OPS = {}


def op(name=None, random=False, training_aware=False):
    def deco(fn):
        OPS[name or fn.__name__] = fn
        if random:
            RANDOM_OPS.add(name or fn.__name__)
        if training_aware:
            TRAINING_AWARE_OPS.add(name or fn.__name__)
        return fn

    return deco


RANDOM_OPS: set = set()
TRAINING_AWARE_OPS: set = set()

# binary
OPS["add"] = lambda a, b: a + b
OPS["sub"] = lambda a, b: a - b
OPS["mul"] = lambda a, b: a * b
OPS["div"] = lambda a, b: a / b
OPS["rsub"] = lambda a, b: b - a
OPS["rdiv"] = lambda a, b: b / a
OPS["pow"] = lambda a, b: a**b
OPS["floordiv"] = lambda a, b: jnp.floor_divide(a, b)
OPS["mod"] = lambda a, b: jnp.mod(a, b)
OPS["squaredDifference"] = lambda a, b: (a - b) ** 2
OPS["maximum"] = jnp.maximum
OPS["minimum"] = jnp.minimum

# unary
OPS["identity"] = _identity
OPS["neg"] = jnp.negative
OPS["abs"] = jnp.abs
OPS["exp"] = jnp.exp
OPS["log"] = jnp.log
OPS["log1p"] = jnp.log1p
OPS["sqrt"] = jnp.sqrt
OPS["rsqrt"] = lax.rsqrt
OPS["square"] = jnp.square
OPS["reciprocal"] = jnp.reciprocal
OPS["sign"] = jnp.sign
OPS["floor"] = jnp.floor
OPS["ceil"] = jnp.ceil
OPS["round"] = jnp.round
OPS["sin"] = jnp.sin
OPS["cos"] = jnp.cos
OPS["tan"] = jnp.tan
OPS["asin"] = jnp.arcsin
OPS["acos"] = jnp.arccos
OPS["atan"] = jnp.arctan
OPS["sinh"] = jnp.sinh
OPS["cosh"] = jnp.cosh
OPS["tanh"] = jnp.tanh
OPS["erf"] = jax.scipy.special.erf
OPS["isnan"] = jnp.isnan
OPS["isinf"] = jnp.isinf

# activations
OPS["sigmoid"] = jax.nn.sigmoid
OPS["relu"] = jax.nn.relu
OPS["relu6"] = jax.nn.relu6
OPS["elu"] = jax.nn.elu
OPS["selu"] = jax.nn.selu
OPS["gelu"] = jax.nn.gelu
OPS["softplus"] = jax.nn.softplus
OPS["softsign"] = jax.nn.soft_sign
OPS["swish"] = jax.nn.silu
OPS["mish"] = lambda x: x * jnp.tanh(jax.nn.softplus(x))
OPS["hardSigmoid"] = jax.nn.hard_sigmoid
OPS["hardTanh"] = lambda x: jnp.clip(x, -1.0, 1.0)
OPS["leakyRelu"] = lambda x, alpha=0.01: jax.nn.leaky_relu(x, alpha)
OPS["prelu"] = lambda x, a: jnp.where(x >= 0, x, a * x)
OPS["rationalTanh"] = lambda x: 1.7159 * jnp.tanh(2.0 * x / 3.0)
OPS["rectifiedTanh"] = lambda x: jnp.maximum(jnp.tanh(x), 0.0)
OPS["thresholdRelu"] = lambda x, cutoff=0.0: jnp.where(x > cutoff, x, 0.0)
OPS["clipByValue"] = lambda x, clipValueMin=-1.0, clipValueMax=1.0: jnp.clip(
    x, clipValueMin, clipValueMax
)


@op("clipByNorm")
def _clip_by_norm(x, clipValue=1.0, dims=None):
    n = jnp.sqrt(jnp.sum(x * x, axis=_axis(dims, x.ndim), keepdims=True))
    return jnp.where(n > clipValue, x * (clipValue / jnp.maximum(n, 1e-12)), x)


@op("softmax")
def _softmax(x, dimension=-1):
    return jax.nn.softmax(x, axis=dimension)


@op("logSoftmax")
def _log_softmax(x, dimension=-1):
    return jax.nn.log_softmax(x, axis=dimension)


@op("softmaxDerivative")
def _softmax_deriv(x, wrt, dimension=-1):
    s = jax.nn.softmax(x, axis=dimension)
    return s * (wrt - jnp.sum(wrt * s, axis=dimension, keepdims=True))


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _red(fn):
    def f(x, dimensions=None, keepDims=False):
        return fn(x, axis=_axis(dimensions, x.ndim), keepdims=keepDims)

    return f


OPS["sum"] = _red(jnp.sum)
OPS["mean"] = _red(jnp.mean)
OPS["max"] = _red(jnp.max)
OPS["min"] = _red(jnp.min)
OPS["prod"] = _red(jnp.prod)
OPS["any"] = _red(jnp.any)
OPS["all"] = _red(jnp.all)
OPS["norm1"] = _red(lambda x, **k: jnp.sum(jnp.abs(x), **k))
OPS["norm2"] = _red(lambda x, **k: jnp.sqrt(jnp.sum(x * x, **k)))
OPS["normMax"] = _red(lambda x, **k: jnp.max(jnp.abs(x), **k))
OPS["logSumExp"] = _red(jax.scipy.special.logsumexp)
OPS["countNonZero"] = _red(lambda x, **k: jnp.sum((x != 0), **k))
OPS["zeroFraction"] = lambda x: jnp.mean((x == 0).astype(jnp.float32))


@op("variance")
def _variance(x, dimensions=None, biasCorrected=True, keepDims=False):
    return jnp.var(
        x, axis=_axis(dimensions, x.ndim), ddof=1 if biasCorrected else 0,
        keepdims=keepDims,
    )


@op("standardDeviation")
def _std(x, dimensions=None, biasCorrected=True, keepDims=False):
    return jnp.std(
        x, axis=_axis(dimensions, x.ndim), ddof=1 if biasCorrected else 0,
        keepdims=keepDims,
    )


@op("argmax")
def _argmax(x, dimension=None, keepDims=False):
    r = jnp.argmax(x, axis=dimension, keepdims=keepDims)
    return r


@op("argmin")
def _argmin(x, dimension=None, keepDims=False):
    return jnp.argmin(x, axis=dimension, keepdims=keepDims)


@op("cumsum")
def _cumsum(x, axis=0, exclusive=False, reverse=False):
    a = x
    if reverse:
        a = jnp.flip(a, axis)
    r = jnp.cumsum(a, axis=axis)
    if exclusive:
        r = r - a
    if reverse:
        r = jnp.flip(r, axis)
    return r


@op("cumprod")
def _cumprod(x, axis=0):
    return jnp.cumprod(x, axis=axis)


@op("moments")
def _moments(x, dimensions=None, keepDims=False):
    ax = _axis(dimensions, x.ndim)
    return jnp.mean(x, ax, keepdims=keepDims), jnp.var(x, ax, keepdims=keepDims)


# ---------------------------------------------------------------------------
# linalg
# ---------------------------------------------------------------------------

@op("matmul")
def _matmul(a, b, transposeA=False, transposeB=False):
    if transposeA:
        a = jnp.swapaxes(a, -1, -2)
    if transposeB:
        b = jnp.swapaxes(b, -1, -2)
    return a @ b


@op("tensorMmul")
def _tensor_mmul(a, b, axesA=None, axesB=None):
    return jnp.tensordot(a, b, axes=(tuple(axesA), tuple(axesB)))


@op("batchMmul")
def _batch_mmul(a, b):
    return a @ b


@op("dot")
def _dot(a, b, dimensions=None):
    if dimensions is None:
        return jnp.sum(a * b)
    return jnp.sum(a * b, axis=_axis(dimensions, a.ndim))


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------

@op("reshape")
def _reshape(x, shape=None):
    return x.reshape(tuple(shape))


@op("permute")
def _permute(x, dimensions=None):
    return jnp.transpose(x, tuple(dimensions))


@op("transpose")
def _transpose(x):
    return x.T


@op("expandDims")
def _expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@op("squeeze")
def _squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


@op("concat")
def _concat(*xs, dimension=0):
    return jnp.concatenate(xs, axis=dimension)


@op("stack")
def _stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


@op("unstack")
def _unstack(x, axis=0, num=None):
    n = num or x.shape[axis]
    return tuple(jnp.squeeze(s, axis) for s in jnp.split(x, n, axis=axis))


@op("split")
def _split(x, numSplit=2, dimension=0):
    return tuple(jnp.split(x, numSplit, axis=dimension))


@op("slice")
def _slice(x, begin=None, size=None):
    begin = tuple(begin)
    size = tuple(
        s if s >= 0 else x.shape[i] - begin[i] for i, s in enumerate(size)
    )
    return lax.dynamic_slice(x, begin, size)


@op("stridedSlice")
def _strided_slice(x, begin=None, end=None, strides=None):
    idx = tuple(
        slice(b, e, s) for b, e, s in zip(begin, end, strides or [1] * len(begin))
    )
    return x[idx]


@op("tile")
def _tile(x, reps=None):
    return jnp.tile(x, tuple(reps))


@op("pad")
def _pad(x, paddings=None, constant=0.0, mode="CONSTANT"):
    pads = tuple(tuple(p) for p in paddings)
    if mode.upper() == "CONSTANT":
        return jnp.pad(x, pads, constant_values=constant)
    return jnp.pad(x, pads, mode=mode.lower())


@op("reverse")
def _reverse(x, dimensions=None):
    return jnp.flip(x, axis=_axis(dimensions, x.ndim))


@op("gather")
def _gather(x, indices, axis=0):
    return jnp.take(x, indices.astype(jnp.int32), axis=axis)


@op("gatherNd")
def _gather_nd(x, indices):
    idx = tuple(jnp.moveaxis(indices.astype(jnp.int32), -1, 0))
    return x[idx]


@op("scatterUpdate")
def _scatter_update(ref, indices, updates):
    return ref.at[indices.astype(jnp.int32)].set(updates)


@op("scatterAdd")
def _scatter_add(ref, indices, updates):
    return ref.at[indices.astype(jnp.int32)].add(updates)


@op("oneHot")
def _one_hot(x, depth=None, on=1.0, off=0.0, axis=-1):
    return jax.nn.one_hot(x.astype(jnp.int32), depth, axis=axis) * (on - off) + off


@op("linspace")
def _linspace(start=0.0, stop=1.0, num=10):
    return jnp.linspace(start, stop, num)


@op("range")
def _range(start=0, limit=None, delta=1):
    return jnp.arange(start, limit, delta)


@op("shape_of")
def _shape_of(x):
    return jnp.asarray(x.shape, dtype=jnp.int32)


@op("cast")
def _cast(x, dtype=None):
    return x.astype(dtype)


@op("assign_op")
def _assign_op(a, b):
    return jnp.broadcast_to(b, a.shape).astype(a.dtype)


@op("invertPermutation")
def _invert_permutation(x):
    return jnp.argsort(x)


@op("sequenceMask")
def _sequence_mask(lengths, maxLen=None):
    return (jnp.arange(maxLen)[None, :] < lengths[:, None]).astype(jnp.float32)


@op("diag")
def _diag(x):
    return jnp.diag(x)


@op("eye_op")
def _eye(n=1, m=None):
    return jnp.eye(n, m)


@op("meshgrid")
def _meshgrid(*xs, indexing="xy"):
    return tuple(jnp.meshgrid(*xs, indexing=indexing))


# comparisons / selection
OPS["eq"] = lambda a, b: a == b
OPS["neq"] = lambda a, b: a != b
OPS["gt"] = lambda a, b: a > b
OPS["gte"] = lambda a, b: a >= b
OPS["lt"] = lambda a, b: a < b
OPS["lte"] = lambda a, b: a <= b
OPS["and_op"] = jnp.logical_and
OPS["or_op"] = jnp.logical_or
OPS["not_op"] = jnp.logical_not
OPS["xor_op"] = jnp.logical_xor


@op("where_op")
def _where(cond, x, y):
    return jnp.where(cond.astype(bool), x, y)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

@op("layerNorm")
def _layer_norm(x, gain, bias=None, channelwise_axis=-1, epsilon=1e-5):
    mean = jnp.mean(x, axis=channelwise_axis, keepdims=True)
    var = jnp.var(x, axis=channelwise_axis, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + epsilon) * gain
    if bias is not None:
        y = y + bias
    return y


@op("batchNorm")
def _batch_norm(x, mean, variance, gamma=None, beta=None, epsilon=1e-5,
                axis=1):
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    rs = lambda a: a.reshape(shape)
    y = (x - rs(mean)) * lax.rsqrt(rs(variance) + epsilon)
    if gamma is not None:
        y = y * rs(gamma)
    if beta is not None:
        y = y + rs(beta)
    return y


@op("standardize")
def _standardize(x, dimensions=(-1,)):
    ax = _axis(dimensions, x.ndim)
    m = jnp.mean(x, axis=ax, keepdims=True)
    s = jnp.std(x, axis=ax, keepdims=True)
    return (x - m) / jnp.maximum(s, 1e-12)


@op("dropout", random=True, training_aware=True)
def _dropout(x, p=0.5, key=None, training=False):
    """p is the RETAIN probability, matching DL4J dropout semantics
    (org.deeplearning4j.nn.conf.dropout.Dropout: activations scaled by 1/p)."""
    if not training or p >= 1.0:
        return x
    mask = jax.random.bernoulli(key, p, x.shape)
    return jnp.where(mask, x / p, 0.0)


# ---------------------------------------------------------------------------
# random
# ---------------------------------------------------------------------------

@op("randomNormal", random=True)
def _random_normal(shape=None, mean=0.0, stddev=1.0, key=None):
    return mean + stddev * jax.random.normal(key, tuple(shape))


@op("randomUniform", random=True)
def _random_uniform(shape=None, min=0.0, max=1.0, key=None):
    return jax.random.uniform(key, tuple(shape), minval=min, maxval=max)


@op("randomBernoulli", random=True)
def _random_bernoulli(shape=None, p=0.5, key=None):
    return jax.random.bernoulli(key, p, tuple(shape)).astype(jnp.float32)


@op("randomGamma", random=True)
def _random_gamma(shape=None, alpha=1.0, beta=1.0, key=None):
    """Gamma(alpha, rate beta) (reference: random ops gamma declarable)."""
    return jax.random.gamma(key, alpha, tuple(shape)) / beta


@op("randomPoisson", random=True)
def _random_poisson(shape=None, lam=1.0, key=None):
    return jax.random.poisson(key, lam, tuple(shape)).astype(jnp.float32)


@op("randomExponential", random=True)
def _random_exponential(shape=None, lam=1.0, key=None):
    return jax.random.exponential(key, tuple(shape)) / lam


@op("truncatedNormal", random=True)
def _truncated_normal(shape=None, mean=0.0, stddev=1.0, key=None):
    """Normal truncated to +/-2 sigma (TF/DL4J truncated_normal
    semantics)."""
    return mean + stddev * jax.random.truncated_normal(
        key, -2.0, 2.0, tuple(shape))


# ---------------------------------------------------------------------------
# conv / pool (NCHW, weights [out, in, kH, kW] like libnd4j conv2d)
# ---------------------------------------------------------------------------

def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


def _conv_pad(padding, kernel, strides, dilation=(1, 1)):
    if isinstance(padding, str):
        return padding.upper()
    p = _pair(padding)
    return [(p[0], p[0]), (p[1], p[1])]


@op("conv2d")
def _conv2d(x, w, b=None, kernel=None, strides=(1, 1), padding=(0, 0),
            dilation=(1, 1), sameMode=False):
    """x: [N,C,H,W]; w: [outC, inC, kH, kW] (DL4J layout)."""
    strides = _pair(strides)
    dilation = _pair(dilation)
    pad = "SAME" if sameMode else _conv_pad(padding, kernel, strides, dilation)
    y = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        rhs_dilation=dilation,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


@op("depthwiseConv2d")
def _depthwise_conv2d(x, w, b=None, strides=(1, 1), padding=(0, 0),
                      dilation=(1, 1), sameMode=False):
    """w: [depthMult, inC, kH, kW] -> grouped conv with C groups."""
    strides = _pair(strides)
    dilation = _pair(dilation)
    c = x.shape[1]
    mult = w.shape[0]
    # reshape to [C*mult, 1, kH, kW] for feature_group_count=C
    w2 = jnp.transpose(w, (1, 0, 2, 3)).reshape(c * mult, 1, *w.shape[2:])
    pad = "SAME" if sameMode else _conv_pad(padding, None, strides, dilation)
    y = lax.conv_general_dilated(
        x, w2, window_strides=strides, padding=pad, rhs_dilation=dilation,
        dimension_numbers=("NCHW", "OIHW", "NCHW"), feature_group_count=c,
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


@op("conv1d")
def _conv1d(x, w, b=None, stride=1, padding=0, sameMode=False):
    """x: [N,C,W]; w: [outC, inC, k]."""
    pad = "SAME" if sameMode else [(padding, padding)]
    y = lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=pad,
        dimension_numbers=("NCH", "OIH", "NCH"),
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1)
    return y


@op("deconv2d")
def _deconv2d(x, w, b=None, strides=(1, 1), padding=(0, 0), sameMode=False):
    """Transposed conv; w: [outC, inC, kH, kW] wrt the FORWARD direction of
    the deconv (i.e. produces outC channels). Implemented as the
    lhs-dilated conv with per-side padding k-1-p and a spatially flipped
    kernel, which yields DL4J's deconv output size s*(i-1) + k - 2p
    (SAME mode: i*s)."""
    strides = _pair(strides)
    p = _pair(padding)
    k = (w.shape[2], w.shape[3])
    if sameMode:
        # total pad k+s-2 per dim -> output i*s
        tot = (k[0] + strides[0] - 2, k[1] + strides[1] - 2)
        pad = [(tot[0] // 2, tot[0] - tot[0] // 2),
               (tot[1] // 2, tot[1] - tot[1] // 2)]
    else:
        pad = [(k[0] - 1 - p[0], k[0] - 1 - p[0]),
               (k[1] - 1 - p[1], k[1] - 1 - p[1])]
    y = lax.conv_general_dilated(
        x, jnp.flip(w, (2, 3)), window_strides=(1, 1), padding=pad,
        lhs_dilation=strides,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y


def _pool(x, kernel, strides, padding, sameMode, init, fn, norm=False):
    kernel = _pair(kernel)
    strides = _pair(strides)
    p = _pair(padding)
    if sameMode:
        pad = "SAME"
    else:
        pad = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    window = (1, 1) + kernel
    strides_full = (1, 1) + strides
    y = lax.reduce_window(x, init, fn, window, strides_full, pad)
    if norm:
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides_full, pad)
        y = y / cnt
    return y


@op("maxPooling2d")
def _max_pool2d(x, kernel=(2, 2), strides=(2, 2), padding=(0, 0),
                sameMode=False):
    return _pool(x, kernel, strides, padding, sameMode, -jnp.inf, lax.max)


@op("avgPooling2d")
def _avg_pool2d(x, kernel=(2, 2), strides=(2, 2), padding=(0, 0),
                sameMode=False, includePadInAvg=False):
    if includePadInAvg:
        k = _pair(kernel)
        s = _pool(x, kernel, strides, padding, sameMode, 0.0, lax.add)
        return s / (k[0] * k[1])
    return _pool(x, kernel, strides, padding, sameMode, 0.0, lax.add, norm=True)


def _triple_(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(a) for a in v)
    return (int(v),) * 3


@op("conv3d")
def _conv3d_op(x, w, b=None, strides=(1, 1, 1), padding=(0, 0, 0),
               dilation=(1, 1, 1), sameMode=False):
    """x: [N,C,D,H,W]; w: [outC, inC, kD, kH, kW] (op-level conv3d —
    reference: libnd4j conv3dnew declarable; the Convolution3D LAYER
    wraps the same lowering)."""
    strides = _triple_(strides)
    dilation = _triple_(dilation)
    if sameMode:
        pad = "SAME"
    else:
        p = _triple_(padding)
        pad = [(p[0], p[0]), (p[1], p[1]), (p[2], p[2])]
    y = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad, rhs_dilation=dilation,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1, 1)
    return y


def _pool3d(x, kernel, strides, padding, sameMode, init, fn, norm=False):
    k = _triple_(kernel)
    s = _triple_(strides)
    p = _triple_(padding)
    pad = "SAME" if sameMode else (
        (0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]), (p[2], p[2]))
    window = (1, 1) + k
    strides_full = (1, 1) + s
    y = lax.reduce_window(x, init, fn, window, strides_full, pad)
    if norm:
        cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                strides_full, pad)
        y = y / cnt
    return y


@op("maxPooling3d")
def _max_pool3d(x, kernel=(2, 2, 2), strides=(2, 2, 2), padding=(0, 0, 0),
                sameMode=False):
    return _pool3d(x, kernel, strides, padding, sameMode, -jnp.inf, lax.max)


@op("avgPooling3d")
def _avg_pool3d(x, kernel=(2, 2, 2), strides=(2, 2, 2), padding=(0, 0, 0),
                sameMode=False):
    return _pool3d(x, kernel, strides, padding, sameMode, 0.0, lax.add,
                   norm=True)


@op("globalAvgPooling")
def _global_avg_pool(x, dimensions=(2, 3)):
    return jnp.mean(x, axis=_axis(dimensions, x.ndim))


@op("upsampling2d")
def _upsampling2d(x, size=(2, 2)):
    s = _pair(size)
    return jnp.repeat(jnp.repeat(x, s[0], axis=2), s[1], axis=3)


@op("im2col")
def _im2col(x, kernel=(2, 2), strides=(1, 1), padding=(0, 0)):
    """Kept for parity with libnd4j helpers/im2col — on TPU conv doesn't go
    through im2col (XLA handles tiling), but the op is part of the surface."""
    k = _pair(kernel)
    s = _pair(strides)
    p = _pair(padding)
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])))
    n, c, h, w = xp.shape
    oh = (h - k[0]) // s[0] + 1
    ow = (w - k[1]) // s[1] + 1
    idx_h = (jnp.arange(oh) * s[0])[:, None] + jnp.arange(k[0])[None, :]
    idx_w = (jnp.arange(ow) * s[1])[:, None] + jnp.arange(k[1])[None, :]
    cols = xp[:, :, idx_h[:, :, None, None], idx_w[None, None, :, :]]
    # [n, c, oh, kh, ow, kw] -> [n, c, kh, kw, oh, ow]
    return jnp.transpose(cols, (0, 1, 3, 5, 2, 4))


# ---------------------------------------------------------------------------
# recurrent (lstmLayer replaces libnd4j helpers/lstm + cudnn LSTM,
# SURVEY.md §2.1; scan keeps the weights resident and lets XLA pipeline steps)
# ---------------------------------------------------------------------------

@op("lstmCell")
def _lstm_cell(x, h_prev, c_prev, w, r, b=None, forgetBias=0.0):
    """One LSTM step. x:[N,I], h_prev/c_prev:[N,H], w:[I,4H], r:[H,4H],
    b:[4H]. Gate order i,f,g(cell),o — matches DL4J lstmLayer gate packing."""
    z = x @ w + h_prev @ r
    if b is not None:
        z = z + b
    hsz = h_prev.shape[-1]
    i, f, g, o = (z[..., k * hsz:(k + 1) * hsz] for k in range(4))
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forgetBias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return h, c


@op("lstmLayer")
def _lstm_layer(x, w, r, b=None, h0=None, c0=None, forgetBias=0.0,
                returnFullSequence=True, unroll=4):
    """x: [N, I, T] (DL4J NCW time-series layout). Returns ([N,H,T], hT, cT).

    TPU lowering (the cuDNN-LSTM trick, SURVEY.md §7 hard part 3): the
    input projection x@W for ALL timesteps is hoisted out of the
    recurrence as ONE [T*N, I] x [I, 4H] MXU matmul; only the [N,H] x
    [H,4H] recurrent matmul stays inside the lax.scan (unrolled to cut
    loop overhead), so the sequential chain carries half the FLOPs and
    the rest runs at large-matmul efficiency."""
    n, _, t = x.shape
    hsz = r.shape[0]
    if h0 is None:
        h0 = jnp.zeros((n, hsz), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((n, hsz), x.dtype)

    xs = jnp.moveaxis(x, 2, 0)  # [T, N, I]
    xw = xs @ w                 # [T, N, 4H] — one batched MXU matmul
    if b is not None:
        xw = xw + b

    # Pallas recurrence kernel on TPU when shapes/dtype allow: h, c and R
    # stay VMEM-resident across all timesteps (1.8x the scan lowering at
    # b1024 under slope timing, r4 A/B: 13.3 vs 24.4 ms/step on the
    # char-RNN config; kernels/lstm.py documents the design and bounds)
    import os as _os

    from deeplearning4j_tpu.kernels.lstm import lstm_seq, lstm_seq_available

    if (jax.default_backend() == "tpu"
            and lstm_seq_available(x.shape[0], hsz, x.dtype)
            and r.dtype == jnp.float32
            and _os.environ.get("DL4J_DISABLE_PALLAS_LSTM") != "1"):
        xw_k = xw.astype(jnp.float32)
        if forgetBias:
            xw_k = xw_k.at[:, :, hsz:2 * hsz].add(forgetBias)
        hs_k, hT, cT = lstm_seq(xw_k, r, h0.astype(jnp.float32),
                                c0.astype(jnp.float32))
        out = jnp.moveaxis(hs_k, 0, 2)
        if not returnFullSequence:
            return hT, hT, cT
        return out, hT, cT

    def step(carry, xw_t):
        h, c = carry
        z = xw_t + h @ r
        i, f, g, o = (z[..., k * hsz:(k + 1) * hsz] for k in range(4))
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f + forgetBias)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return (h2, c2), h2

    (hT, cT), hs = lax.scan(step, (h0, c0), xw,
                            unroll=min(unroll, t))
    out = jnp.moveaxis(hs, 0, 2)  # [N, H, T]
    if not returnFullSequence:
        return hT, hT, cT
    return out, hT, cT


@op("gruCell")
def _gru_cell(x, h_prev, w, r, b=None):
    """x:[N,I], h_prev:[N,H], w:[I,3H], r:[H,3H], b:[6H] (ru then c, input
    and recurrent biases separate, like libnd4j gruCell)."""
    hsz = h_prev.shape[-1]
    wz = x @ w
    rz = h_prev @ r
    if b is not None:
        wz = wz + b[: 3 * hsz]
        rz = rz + b[3 * hsz:]
    ru_w, c_w = wz[..., : 2 * hsz], wz[..., 2 * hsz:]
    ru_r, c_r = rz[..., : 2 * hsz], rz[..., 2 * hsz:]
    ru = jax.nn.sigmoid(ru_w + ru_r)
    rgate, ugate = ru[..., :hsz], ru[..., hsz:]
    cand = jnp.tanh(c_w + rgate * c_r)
    return ugate * h_prev + (1 - ugate) * cand


@op("gruLayer")
def _gru_layer(x, w, r, b=None, h0=None, unroll=4, resetAfter=True,
               activation="tanh"):
    """Input projection hoisted out of the scan (same lowering as
    lstmLayer); the reset-gated candidate keeps only h@r sequential.
    On TPU the Pallas recurrence kernel (kernels/gru.py) takes over when
    shapes allow.

    Gate layout [reset | update | candidate]. resetAfter=True (cuDNN /
    Keras v2 convention): candidate = tanh(c_w + r * (h@Rc + rb_c)),
    bias b is [3H input || 3H recurrent]. resetAfter=False (classic
    Cho et al. / Keras reset_after=False): candidate =
    tanh(c_w + (r*h)@Rc), bias b is 3H input-side only."""
    n, _, t = x.shape
    hsz = r.shape[0]
    if h0 is None:
        h0 = jnp.zeros((n, hsz), x.dtype)
    xs = jnp.moveaxis(x, 2, 0)            # [T, N, I]
    xw = xs @ w                           # [T, N, 3H] — one MXU matmul
    if b is not None:
        xw = xw + b[: 3 * hsz]
    rb = b[3 * hsz:] if b is not None and b.shape[0] > 3 * hsz else None
    act = OPS[activation]

    if not resetAfter:
        def step_before(h, xw_t):
            ru_w, c_w = xw_t[..., : 2 * hsz], xw_t[..., 2 * hsz:]
            ru = jax.nn.sigmoid(ru_w + h @ r[:, : 2 * hsz])
            rgate, ugate = ru[..., :hsz], ru[..., hsz:]
            cand = act(c_w + (rgate * h) @ r[:, 2 * hsz:])
            h2 = ugate * h + (1.0 - ugate) * cand
            return h2, h2

        hT, hs = lax.scan(step_before, h0, xw, unroll=min(unroll, t))
        return jnp.moveaxis(hs, 0, 2), hT

    import os as _os

    from deeplearning4j_tpu.kernels.gru import gru_seq, gru_seq_available

    if (jax.default_backend() == "tpu"
            and activation == "tanh"  # the Pallas kernel fixes tanh
            and gru_seq_available(n, hsz, x.dtype)
            and r.dtype == jnp.float32
            and _os.environ.get("DL4J_DISABLE_PALLAS_GRU") != "1"):
        rb_k = (jnp.zeros((3 * hsz,), jnp.float32) if rb is None
                else rb.astype(jnp.float32))
        hs_k, hT = gru_seq(xw.astype(jnp.float32), r, rb_k,
                           h0.astype(jnp.float32))
        return jnp.moveaxis(hs_k, 0, 2), hT

    def step(h, xw_t):
        rz = h @ r
        if rb is not None:
            rz = rz + rb
        ru_w, c_w = xw_t[..., : 2 * hsz], xw_t[..., 2 * hsz:]
        ru_r, c_r = rz[..., : 2 * hsz], rz[..., 2 * hsz:]
        ru = jax.nn.sigmoid(ru_w + ru_r)
        rgate, ugate = ru[..., :hsz], ru[..., hsz:]
        cand = act(c_w + rgate * c_r)
        h2 = ugate * h + (1.0 - ugate) * cand
        return h2, h2

    hT, hs = lax.scan(step, h0, xw, unroll=min(unroll, t))
    return jnp.moveaxis(hs, 0, 2), hT


@op("simpleRnnLayer")
def _simple_rnn_layer(x, w, r, b=None, h0=None, activation="tanh",
                      unroll=4):
    n, _, t = x.shape
    hsz = r.shape[0]
    if h0 is None:
        h0 = jnp.zeros((n, hsz), x.dtype)
    act = OPS[activation]
    xs = jnp.moveaxis(x, 2, 0)
    xw = xs @ w                           # hoisted input projection
    if b is not None:
        xw = xw + b

    def step(h, xw_t):
        h2 = act(xw_t + h @ r)
        return h2, h2

    hT, hs = lax.scan(step, h0, xw, unroll=min(unroll, t))
    return jnp.moveaxis(hs, 0, 2), hT


# ---------------------------------------------------------------------------
# attention (the reference's multiHeadDotProductAttention declarable op;
# here the soft path — the Pallas flash kernel plugs in via ops/attention)
# ---------------------------------------------------------------------------

@op("dotProductAttention")
def _dot_product_attention(q, k, v, mask=None, scaled=True):
    """q:[..., T_q, D], k:[..., T_k, D], v:[..., T_k, Dv]."""
    scale = 1.0 / _math.sqrt(q.shape[-1]) if scaled else 1.0
    logits = (q * scale) @ jnp.swapaxes(k, -1, -2)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    return w @ v


@op("multiHeadDotProductAttention")
def _mhdpa(q, k, v, wq, wk, wv, wo, mask=None, numHeads=1, scaled=True):
    """Batched multi-head attention: q/k/v [N, T, E]; wq/wk/wv [E, H*Dh],
    wo [H*Dh, E]."""
    n, tq, e = q.shape
    h = numHeads

    def heads(x, wm):
        y = x @ wm
        return y.reshape(n, x.shape[1], h, -1).transpose(0, 2, 1, 3)

    qh, kh, vh = heads(q, wq), heads(k, wk), heads(v, wv)
    if mask is not None and mask.ndim == 2:
        mask = mask[:, None, None, :]
    o = _dot_product_attention(qh, kh, vh, mask, scaled)
    o = o.transpose(0, 2, 1, 3).reshape(n, tq, -1)
    return o @ wo


# ---------------------------------------------------------------------------
# losses (reference: SDLoss / org.nd4j.linalg.lossfunctions)
# ---------------------------------------------------------------------------

def _reduce_loss(per_ex, weights, reduction):
    if weights is not None:
        per_ex = per_ex * weights
    if reduction in ("MEAN_BY_NONZERO_WEIGHT_COUNT", "MEAN_BY_WEIGHT"):
        if weights is not None:
            denom = jnp.maximum(jnp.sum(weights != 0), 1)
            return jnp.sum(per_ex) / denom
        return jnp.mean(per_ex)
    if reduction == "SUM":
        return jnp.sum(per_ex)
    if reduction == "NONE":
        return per_ex
    return jnp.mean(per_ex)


@op("softmaxCrossEntropy")
def _softmax_ce(logits, labels, weights=None, labelSmoothing=0.0,
                reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    nc = logits.shape[-1]
    if labelSmoothing > 0:
        labels = labels * (1 - labelSmoothing) + labelSmoothing / nc
    lp = jax.nn.log_softmax(logits, axis=-1)
    per_ex = -jnp.sum(labels * lp, axis=-1)
    return _reduce_loss(per_ex, weights, reduction)


@op("sparseSoftmaxCrossEntropy")
def _sparse_softmax_ce(logits, labels, reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    lp = jax.nn.log_softmax(logits, axis=-1)
    per_ex = -jnp.take_along_axis(
        lp, labels.astype(jnp.int32)[..., None], axis=-1
    )[..., 0]
    return _reduce_loss(per_ex, None, reduction)


@op("sigmoidCrossEntropy")
def _sigmoid_ce(logits, labels, weights=None,
                reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )
    per_ex = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _reduce_loss(per_ex, weights, reduction)


@op("meanSquaredError")
def _mse(predictions, labels, weights=None,
         reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    per = (predictions - labels) ** 2
    per_ex = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _reduce_loss(per_ex, weights, reduction)


@op("absoluteDifference")
def _mae(predictions, labels, weights=None,
         reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    per = jnp.abs(predictions - labels)
    per_ex = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _reduce_loss(per_ex, weights, reduction)


@op("huberLoss")
def _huber(predictions, labels, weights=None, delta=1.0,
           reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    err = jnp.abs(predictions - labels)
    per = jnp.where(err <= delta, 0.5 * err**2, delta * err - 0.5 * delta**2)
    per_ex = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _reduce_loss(per_ex, weights, reduction)


@op("logLoss")
def _log_loss(predictions, labels, weights=None, epsilon=1e-7,
              reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    p = jnp.clip(predictions, epsilon, 1 - epsilon)
    per = -(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p))
    per_ex = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _reduce_loss(per_ex, weights, reduction)


@op("hingeLoss")
def _hinge(predictions, labels, weights=None,
           reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    # labels in {0,1} -> {-1,1} like SDLoss.hingeLoss
    y = 2.0 * labels - 1.0
    per = jnp.maximum(0.0, 1.0 - y * predictions)
    per_ex = jnp.mean(per, axis=tuple(range(1, per.ndim)))
    return _reduce_loss(per_ex, weights, reduction)


@op("cosineDistance")
def _cosine_distance(predictions, labels, weights=None, dimension=-1,
                     reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    per_ex = 1.0 - jnp.sum(predictions * labels, axis=dimension)
    return _reduce_loss(per_ex, weights, reduction)


@op("klDivergence")
def _kld(predictions, labels, reduction="MEAN_BY_NONZERO_WEIGHT_COUNT"):
    per = labels * (jnp.log(jnp.maximum(labels, 1e-12)) -
                    jnp.log(jnp.maximum(predictions, 1e-12)))
    per_ex = jnp.sum(per, axis=tuple(range(1, per.ndim)))
    return _reduce_loss(per_ex, None, reduction)


# ---------------------------------------------------------------------------
# control flow
# ---------------------------------------------------------------------------
# Reference capability: libnd4j control-flow declarables + SameDiff's
# interpretation of TF Enter/Exit/Merge/Switch loops (SURVEY.md §2.1/§3.4;
# VERDICT.md round-1 missing item 5). TPU-first design: the loop/branch
# bodies are ordinary traced functions lowered to lax.while_loop /
# lax.cond / lax.scan — ONE compiled XLA op each, no per-iteration
# dispatch. Bodies are Python callables over jnp arrays, captured as op
# attrs; graphs holding them execute and (for cond/scan) differentiate,
# but cannot be serialized (same boundary the reference draws: its
# control-flow sub-graphs serialize as FlatBuffers function defs, ours
# would need the callable's source).

@op("whileLoop")
def _while_loop(*state, cond_fn=None, body_fn=None):
    """state -> final state after `while cond_fn(*state): state =
    body_fn(*state)`. Forward-only (XLA while has no reverse-mode)."""
    out = lax.while_loop(lambda s: cond_fn(*s),
                         lambda s: tuple(body_fn(*s)), tuple(state))
    return out if len(out) > 1 else out[0]


@op("ifCond")
def _if_cond(pred, *operands, true_fn=None, false_fn=None):
    out = lax.cond(jnp.asarray(pred).astype(bool).reshape(()),
                   lambda ops: _as_tuple(true_fn(*ops)),
                   lambda ops: _as_tuple(false_fn(*ops)), tuple(operands))
    return out if len(out) > 1 else out[0]


@op("scanOp")
def _scan_op(init, xs, body_fn=None):
    """lax.scan over leading axis of xs; body_fn(carry, x) -> (carry, y).
    Returns (final_carry, stacked_ys); reverse-mode differentiable."""
    return lax.scan(body_fn, init, xs)


@op("forLoop")
def _for_loop(*state, n=None, body_fn=None):
    """n fixed iterations: state = body_fn(i, *state) (fori_loop)."""
    out = lax.fori_loop(0, n, lambda i, s: tuple(body_fn(i, *s)),
                        tuple(state))
    return out if len(out) > 1 else out[0]


def _as_tuple(v):
    return v if isinstance(v, tuple) else (v,)


# ---------------------------------------------------------------------------
# TF-import support ops (registered statically so graphs holding them
# execute after save/load in a fresh process)
# ---------------------------------------------------------------------------

@op("tfEinsum")
def _tf_einsum(*xs, equation=None):
    return jnp.einsum(equation, *xs)


@op("tfZerosLike")
def _tf_zeros_like(x):
    return jnp.zeros_like(x)


@op("tfOnesLike")
def _tf_ones_like(x):
    return jnp.ones_like(x)


@op("tfStridedSlice")
def _tf_strided_slice(x, idx=None):
    import numpy as _np

    return x[tuple(
        (_np.newaxis if i is None else
         (slice(*i) if isinstance(i, (list, tuple)) else i))
        for i in idx)]


# ---------------------------------------------------------------------------
# linear algebra (reference: nd4j SDLinalg / libnd4j blas parity ops —
# cholesky, solve, matrix_inverse, svd, qr, lu, matrix_band_part, ...)
# ---------------------------------------------------------------------------

OPS["cholesky"] = jnp.linalg.cholesky
OPS["matrixInverse"] = jnp.linalg.inv
OPS["matrixDeterminant"] = jnp.linalg.det
OPS["logdet"] = lambda x: jnp.linalg.slogdet(x)[1]
OPS["trace"] = lambda x: jnp.trace(x, axis1=-2, axis2=-1)


@op("solve")
def _solve(a, b, adjoint=False):
    if adjoint:
        a = jnp.swapaxes(a, -1, -2).conj()
    return jnp.linalg.solve(a, b)


@op("triangularSolve")
def _triangular_solve(a, b, lower=True, adjoint=False):
    import jax.scipy.linalg as jsl

    return jsl.solve_triangular(a, b, lower=lower,
                                trans=2 if adjoint else 0)


@op("svd")
def _svd(x, fullUV=False, computeUV=True):
    # computeUV accepted for parity; U/V are always produced so the op's
    # graph arity stays fixed at 3 (XLA drops unused outputs anyway)
    u, s, vh = jnp.linalg.svd(x, full_matrices=fullUV)
    return s, u, jnp.swapaxes(vh, -1, -2)  # DL4J returns (s, u, v)


@op("qr")
def _qr(x, fullMatrices=False):
    return jnp.linalg.qr(x, mode="complete" if fullMatrices else "reduced")


@op("lu")
def _lu(x):
    import jax.scipy.linalg as jsl

    lu, piv = jsl.lu_factor(x)
    return lu, piv


@op("lstsq")
def _lstsq(a, b, fast=True):
    return jnp.linalg.lstsq(a, b)[0]


@op("matrixBandPart")
def _matrix_band_part(x, minLower=-1, maxUpper=-1):
    m, n = x.shape[-2], x.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = jnp.ones((m, n), bool)
    if minLower >= 0:
        keep = keep & (i - j <= minLower)
    if maxUpper >= 0:
        keep = keep & (j - i <= maxUpper)
    return jnp.where(keep, x, jnp.zeros_like(x))


OPS["triu"] = lambda x, diag=0: jnp.triu(x, k=diag)
OPS["tril"] = lambda x, diag=0: jnp.tril(x, k=diag)
OPS["diagPart"] = lambda x: jnp.diagonal(x, axis1=-2, axis2=-1)


# ---------------------------------------------------------------------------
# segment reductions (reference: libnd4j parity_ops segment_* /
# unsorted_segment_*) — num_segments must be static under jit
# ---------------------------------------------------------------------------

def _num_segments(ids, numSegments):
    if numSegments is not None:
        return int(numSegments)
    try:
        return int(jnp.max(ids)) + 1
    except jax.errors.ConcretizationTypeError as e:
        raise ValueError(
            "segment ops need numSegments when the ids are traced "
            "(static output shape under jit); pass numSegments "
            "explicitly") from e


def _segment(reducer):
    def f(data, ids, numSegments=None):
        ids = jnp.asarray(ids, jnp.int32)
        return reducer(data, ids,
                       num_segments=_num_segments(ids, numSegments))
    return f


OPS["segmentSum"] = OPS["unsortedSegmentSum"] = _segment(jax.ops.segment_sum)
OPS["segmentMax"] = OPS["unsortedSegmentMax"] = _segment(jax.ops.segment_max)
OPS["segmentMin"] = OPS["unsortedSegmentMin"] = _segment(jax.ops.segment_min)
OPS["segmentProd"] = OPS["unsortedSegmentProd"] = _segment(
    jax.ops.segment_prod)


@op("segmentMean")
def _segment_mean(data, ids, numSegments=None):
    ids = jnp.asarray(ids, jnp.int32)
    n = _num_segments(ids, numSegments)
    s = jax.ops.segment_sum(data, ids, num_segments=n)
    c = jax.ops.segment_sum(jnp.ones_like(data), ids, num_segments=n)
    return s / jnp.maximum(c, 1)


OPS["unsortedSegmentMean"] = _segment_mean


# ---------------------------------------------------------------------------
# topK / misc (reference: parity ops top_k, in_top_k, confusion_matrix,
# bincount, zero_fraction)
# ---------------------------------------------------------------------------

@op("topK")
def _top_k(x, k=1, sorted=True):  # noqa: A002
    return lax.top_k(x, int(k))


@op("inTopK")
def _in_top_k(predictions, targets, k=1):
    _, idx = lax.top_k(predictions, int(k))
    return jnp.any(idx == targets[..., None], axis=-1)


@op("confusionMatrix")
def _confusion_matrix(labels, pred, numClasses):
    n = int(numClasses)
    idx = jnp.asarray(labels, jnp.int32) * n + jnp.asarray(pred, jnp.int32)
    return jnp.bincount(idx, length=n * n).reshape(n, n)


@op("bincount")
def _bincount(x, weights=None, minLength=0, maxLength=None):
    """DL4J bincount(values, weights, minLength, maxLength). With
    maxLength the output length is static (values >= it are dropped,
    TF maxlength semantics — required under jit); otherwise the length
    is max(values)+1 extended to minLength, which needs concrete
    values."""
    x = jnp.asarray(x, jnp.int32)
    if maxLength is not None:
        n = max(int(minLength), int(maxLength))
        return jnp.bincount(x, weights, length=n)
    try:
        m = int(jnp.max(x)) + 1
    except jax.errors.ConcretizationTypeError as e:
        raise ValueError(
            "bincount without maxLength needs concrete values; inside a "
            "jitted graph pass maxLength for a static output size") from e
    return jnp.bincount(x, weights, length=max(m, int(minLength)))


OPS["zeroFraction"] = lambda x: jnp.mean((x == 0).astype(jnp.float32))


# ---------------------------------------------------------------------------
# image / spatial ops (reference: libnd4j parity image ops — resize,
# extract_image_patches, space_to_batch, batch_to_space, s2d/d2s; the
# reference routes these through custom kernels, here jax.image / lax)
# ---------------------------------------------------------------------------

def _area_weight_matrix(n_in, n_out):
    """(n_out, n_in) row-stochastic overlap weights: output cell i spans
    input range [i*s, (i+1)*s), s = n_in/n_out; each input pixel
    contributes its fractional overlap (TF ResizeArea region averaging,
    valid for any ratio incl. upscale). Host-side numpy — shapes are
    static at trace time."""
    import numpy as np

    s = n_in / n_out
    mat = np.zeros((n_out, n_in), np.float32)
    for i in range(n_out):
        lo, hi = i * s, (i + 1) * s
        for j in range(int(np.floor(lo)), min(int(np.ceil(hi)), n_in)):
            mat[i, j] = min(hi, j + 1) - max(lo, j)
        mat[i] /= s
    return mat


@op("imageResize")
def _image_resize(x, height, width, method="bilinear", antialias=False):
    """x: [N,C,H,W] (DL4J layout); method: bilinear|nearest|cubic|
    lanczos3|lanczos5|area. antialias defaults OFF to match the TF/DL4J
    resize ops this mirrors (jax.image.resize's own default is
    antialias=True). `area` averages exact input regions; integer
    downscale factors take the reshape fast path, general ratios go
    through per-axis overlap-weight matmuls (TF ResizeArea semantics,
    MXU-shaped)."""
    height, width = int(height), int(width)
    n, c, h, w = x.shape
    m = str(method).lower()
    if m == "area":
        if h % height == 0 and w % width == 0:
            fh, fw = h // height, w // width
            return x.reshape(n, c, height, fh, width, fw).mean(
                axis=(3, 5))
        # contract in f32 regardless of input dtype (integer images would
        # truncate the fractional weights to zero; matches the integer
        # fast path, whose .mean() also yields float) at full precision —
        # resize is an exact-semantics op, the MXU bf16 default would
        # shift pixel values visibly
        xf = x.astype(jnp.float32)
        wh = jnp.asarray(_area_weight_matrix(h, height))
        ww = jnp.asarray(_area_weight_matrix(w, width))
        return jnp.einsum("nchw,Hh,Ww->ncHW", xf, wh, ww,
                          precision=lax.Precision.HIGHEST)
    meth = {"bilinear": "bilinear", "nearest": "nearest",
            "cubic": "cubic", "bicubic": "cubic",
            "lanczos3": "lanczos3", "lanczos5": "lanczos5"}[m]
    return jax.image.resize(x, (n, c, height, width), meth,
                            antialias=antialias)


@op("extractImagePatches")
def _extract_image_patches(x, kH, kW, sH=1, sW=1, sameMode=False):
    """TF/DL4J extract_image_patches orders the patch feature dim
    patch-position-major with depth fastest — (kh, kw, c) — while
    lax.conv_general_dilated_patches emits channel-major (c, kh, kw);
    permute to match the reference op's ordering."""
    pad = "SAME" if sameMode else "VALID"
    kH, kW = int(kH), int(kW)
    c = x.shape[1]
    p = lax.conv_general_dilated_patches(
        x, (kH, kW), (int(sH), int(sW)), pad,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, _, oh, ow = p.shape
    p = p.reshape(n, c, kH, kW, oh, ow)
    return jnp.transpose(p, (0, 2, 3, 1, 4, 5)).reshape(
        n, kH * kW * c, oh, ow)


@op("spaceToDepth")
def _space_to_depth(x, blockSize=2):
    n, c, h, w = x.shape
    b = int(blockSize)
    x = x.reshape(n, c, h // b, b, w // b, b)
    return jnp.transpose(x, (0, 3, 5, 1, 2, 4)).reshape(
        n, c * b * b, h // b, w // b)


@op("depthToSpace")
def _depth_to_space(x, blockSize=2):
    n, c, h, w = x.shape
    b = int(blockSize)
    cout = c // (b * b)
    x = x.reshape(n, b, b, cout, h, w)
    return jnp.transpose(x, (0, 3, 4, 1, 5, 2)).reshape(
        n, cout, h * b, w * b)


@op("spaceToBatch")
def _space_to_batch(x, blockSize=2, padding=((0, 0), (0, 0))):
    n, c, h, w = x.shape
    b = int(blockSize)
    x = jnp.pad(x, ((0, 0), (0, 0)) + tuple(tuple(p) for p in padding))
    h2, w2 = x.shape[2], x.shape[3]
    x = x.reshape(n, c, h2 // b, b, w2 // b, b)
    return jnp.transpose(x, (3, 5, 0, 1, 2, 4)).reshape(
        n * b * b, c, h2 // b, w2 // b)


@op("batchToSpace")
def _batch_to_space(x, blockSize=2, crop=((0, 0), (0, 0))):
    nb, c, h, w = x.shape
    b = int(blockSize)
    n = nb // (b * b)
    x = x.reshape(b, b, n, c, h, w)
    x = jnp.transpose(x, (2, 3, 4, 0, 5, 1)).reshape(n, c, h * b, w * b)
    (ct, cb), (cl, cr) = crop
    return x[:, :, ct: x.shape[2] - cb, cl: x.shape[3] - cr]


# ---------------------------------------------------------------------------
# special functions (reference: libnd4j transforms — lgamma, digamma,
# igamma, betainc, erfc, zeta)
# ---------------------------------------------------------------------------

OPS["erfc"] = jax.scipy.special.erfc
OPS["lgamma"] = jax.scipy.special.gammaln
OPS["digamma"] = jax.scipy.special.digamma
OPS["igamma"] = jax.scipy.special.gammainc
OPS["igammac"] = jax.scipy.special.gammaincc
OPS["betainc"] = jax.scipy.special.betainc
OPS["atan2"] = jnp.arctan2
OPS["expm1"] = jnp.expm1
OPS["asinh"] = jnp.arcsinh
OPS["acosh"] = jnp.arccosh
OPS["atanh"] = jnp.arctanh


# ---------------------------------------------------------------------------
# CTC loss (reference: libnd4j ctc_loss declarable / SameDiff ctcLoss).
# TPU-first design: the forward (alpha) recursion in log space as ONE
# lax.scan over time — no per-timestep host dispatch, fully batched,
# differentiable by jax.grad (the reference ships a hand-written
# ctcLossGrad; reverse-mode through the scan supplies it here).
# ---------------------------------------------------------------------------

@op("ctcLoss")
def _ctc_loss(targetLabels, logitInput, targetLabelLengths=None,
              logitInputLengths=None, blankIndex=0):
    """targetLabels: [B, U] int labels (padded); logitInput: [B, T, C]
    UNNORMALIZED logits; lengths: [B] ints. Returns per-example negative
    log likelihood [B]."""
    labels = jnp.asarray(targetLabels, jnp.int32)
    logits = logitInput
    b, u = labels.shape
    t_max, c = logits.shape[1], logits.shape[2]
    if targetLabelLengths is None:
        targetLabelLengths = jnp.full((b,), u, jnp.int32)
    if logitInputLengths is None:
        logitInputLengths = jnp.full((b,), t_max, jnp.int32)
    lab_len = jnp.asarray(targetLabelLengths, jnp.int32)
    log_len = jnp.asarray(logitInputLengths, jnp.int32)
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    s = 2 * u + 1
    neg_inf = jnp.float32(-1e30)
    # extended sequence [blank, l1, blank, ..., lU, blank]
    ext = jnp.full((b, s), blankIndex, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    is_lab = jnp.arange(s) % 2 == 1
    ext_m2 = jnp.concatenate(
        [jnp.full((b, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    allow_skip = is_lab[None, :] & (ext != ext_m2)

    def lp_ext(t_lp):
        return jnp.take_along_axis(t_lp, ext, axis=1)  # [B, S]

    alpha0 = jnp.full((b, s), neg_inf)
    first = lp_ext(lp[:, 0])
    alpha0 = alpha0.at[:, 0].set(first[:, 0])
    if s > 1:
        alpha0 = alpha0.at[:, 1].set(first[:, 1])

    def step(alpha, inputs):
        t_lp, t_idx = inputs
        a1 = jnp.concatenate(
            [jnp.full((b, 1), neg_inf), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate(
            [jnp.full((b, 2), neg_inf), alpha[:, :-2]], axis=1)
        a2 = jnp.where(allow_skip, a2, neg_inf)
        stacked = jnp.stack([alpha, a1, a2])
        new = jax.scipy.special.logsumexp(stacked, axis=0) + lp_ext(t_lp)
        # freeze past each example's input length
        live = (t_idx < log_len)[:, None]
        return jnp.where(live, new, alpha), None

    alpha, _ = lax.scan(
        step, alpha0,
        (jnp.moveaxis(lp[:, 1:], 1, 0), jnp.arange(1, t_max)))

    end = 2 * lab_len  # index of final blank state
    a_end = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
    a_last = jnp.take_along_axis(
        alpha, jnp.maximum(end - 1, 0)[:, None], axis=1)[:, 0]
    a_last = jnp.where(lab_len > 0, a_last, neg_inf)
    return -jax.scipy.special.logsumexp(
        jnp.stack([a_end, a_last]), axis=0)


# ---------------------------------------------------------------------------
# non-max suppression as a REGISTERED op (reference: libnd4j
# non_max_suppression declarable; the host-side YoloUtils path remains
# for detection post-processing, this one is jittable in-graph)
# ---------------------------------------------------------------------------

@op("nonMaxSuppression")
def _non_max_suppression(boxes, scores, maxOutputSize=10,
                         iouThreshold=0.5, scoreThreshold=None):
    """boxes [N,4] (y1,x1,y2,x2), scores [N] -> selected indices
    [maxOutputSize] int32, padded with -1 (static shape for jit)."""
    n = boxes.shape[0]
    k = int(maxOutputSize)
    y1, x1, y2, x2 = (boxes[:, i] for i in range(4))
    area = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    inter = (jnp.maximum(iy2 - iy1, 0) * jnp.maximum(ix2 - ix1, 0))
    union = area[:, None] + area[None, :] - inter
    iou = jnp.where(union > 0, inter / union, 0.0)

    live = jnp.ones((n,), bool)
    if scoreThreshold is not None:
        live = live & (scores >= scoreThreshold)

    def body(i, carry):
        live, out = carry
        masked = jnp.where(live, scores, -jnp.inf)
        idx = jnp.argmax(masked)
        ok = masked[idx] > -jnp.inf
        out = out.at[i].set(jnp.where(ok, idx.astype(jnp.int32), -1))
        # drop the pick and everything overlapping it — STRICTLY above
        # the threshold (TF/libnd4j semantics: iou > threshold
        # suppresses; boundary-equal survives)
        suppress = iou[idx] > iouThreshold
        live = live & ~suppress & ok
        live = live.at[idx].set(False)
        return live, out

    _, out = lax.fori_loop(0, k, body,
                           (live, jnp.full((k,), -1, jnp.int32)))
    return out


# ---------------------------------------------------------------------------
# round-3 declarable widening: shape/index utilities (reference: libnd4j
# transforms — roll, eye, repeat, flip, sort/argsort, scatter, fill)
# ---------------------------------------------------------------------------

@op("roll")
def _roll(x, shift=1, dimensions=None):
    return jnp.roll(x, shift, axis=_axis(dimensions, x.ndim))


@op("eye")
def _eye(rows=None, cols=None, dtype="float32"):
    return jnp.eye(int(rows), None if cols is None else int(cols),
                   dtype=jnp.dtype(dtype))


@op("repeat")
def _repeat(x, repeats=1, dimension=0):
    return jnp.repeat(x, int(repeats), axis=int(dimension))


OPS["flip"] = OPS["reverse"]   # TF/DL4J name alias for the same op


@op("sort")
def _sort(x, dimension=-1, descending=False):
    y = jnp.sort(x, axis=dimension)
    return jnp.flip(y, axis=dimension) if descending else y


@op("argsort")
def _argsort(x, dimension=-1, descending=False):
    i = jnp.argsort(x, axis=dimension)
    return jnp.flip(i, axis=dimension) if descending else i


@op("fill")
def _fill(shape=None, value=0.0, dtype="float32"):
    return jnp.full(tuple(int(s) for s in shape), value,
                    jnp.dtype(dtype))


@op("tensorScatterUpdate")
def _tensor_scatter_update(x, indices, updates):
    """TF tensor_scatter_nd_update semantics: indices [N, K] index the
    first K dims of x; updates [N, ...]."""
    idx = tuple(jnp.moveaxis(jnp.asarray(indices), -1, 0))
    return jnp.asarray(x).at[idx].set(updates)


@op("uniqueWithCounts")
def _unique_with_counts(x, size=None):
    """Static-shape unique (XLA needs fixed shapes): returns
    (values [size], counts [size]) padded with the first value /
    zero counts. `size` defaults to x.size."""
    flat = x.reshape(-1)
    n = flat.shape[0] if size is None else int(size)
    # jnp.unique(size=n) zero-pads counts and fills values itself
    return jnp.unique(flat, return_counts=True, size=n,
                      fill_value=flat[0])


# ---------------------------------------------------------------------------
# r4 registry widening (VERDICT r3 item 8): image adjustments/colorspace,
# scatter variants, separable conv / LRN / dilation, sequence utilities,
# loss variants, noise layers. Reference: libnd4j declarable families
# ops/declarable/generic/{parity_ops,transforms,nn,loss} (SURVEY.md §2.1).
# ---------------------------------------------------------------------------

@op("cross")
def _cross(a, b):
    return jnp.cross(a, b, axis=-1)


OPS["rint"] = jnp.rint
OPS["erfinv"] = lambda x: jax.scipy.special.erfinv(x)


@op("reverseSequence")
def _reverse_sequence(x, seq_lengths, seqAxis=1, batchAxis=0):
    """Reverse the first seq_lengths[b] elements along seqAxis per batch
    row (TF reverse_sequence / DL4J reverse_sequence)."""
    t = x.shape[seqAxis]
    idx = jnp.arange(t)
    sl = jnp.asarray(seq_lengths)

    def rev_row(row, n):
        # positions < n map to n-1-pos, others stay
        src = jnp.where(idx < n, n - 1 - idx, idx)
        return jnp.take(row, src, axis=seqAxis - 1 if seqAxis > batchAxis
                        else seqAxis)

    return jax.vmap(rev_row, in_axes=(batchAxis, 0),
                    out_axes=batchAxis)(x, sl)


@op("histogramFixedWidth")
def _histogram_fixed_width(x, range_lo, range_hi, nbins=100):
    lo, hi = float(range_lo), float(range_hi)
    nbins = int(nbins)
    scaled = (x.reshape(-1) - lo) / max(hi - lo, 1e-30) * nbins
    b = jnp.clip(scaled.astype(jnp.int32), 0, nbins - 1)
    return jnp.zeros(nbins, jnp.int32).at[b].add(1)


@op("weightedCrossEntropyWithLogits")
def _weighted_ce(targets, logits, posWeight):
    """TF nn.weighted_cross_entropy_with_logits: pos_weight scales the
    positive term; numerically stable log1p form."""
    log_w = 1.0 + (posWeight - 1.0) * targets
    return ((1.0 - targets) * logits + log_w *
            (jnp.log1p(jnp.exp(-jnp.abs(logits)))
             + jnp.maximum(-logits, 0.0)))


@op("meanPairwiseSquaredError")
def _mpse(labels, predictions, weights=1.0):
    """TF losses.mean_pairwise_squared_error per batch row."""
    d = (predictions - labels).reshape(labels.shape[0], -1)
    n = d.shape[1]
    sum_d = jnp.sum(d, axis=1)
    sum_d2 = jnp.sum(d * d, axis=1)
    per = 2.0 * (n * sum_d2 - sum_d * sum_d) / max(n * (n - 1), 1)
    return jnp.mean(per * weights)


@op("clipByGlobalNorm")
def _clip_by_global_norm(*tensors, clipNorm=1.0):
    gn = jnp.sqrt(sum(jnp.sum(jnp.square(t)) for t in tensors))
    scale = jnp.minimum(1.0, clipNorm / jnp.maximum(gn, 1e-30))
    out = tuple(t * scale for t in tensors)
    return out if len(out) > 1 else out[0]


@op("matrixSetDiag")
def _matrix_set_diag(x, diag):
    x = jnp.asarray(x)
    diag = jnp.asarray(diag)
    n = min(x.shape[-2], x.shape[-1])
    i = jnp.arange(n)
    return x.at[..., i, i].set(diag[..., :n])


def _scatter_variant(mode):
    def f(ref, indices, updates):
        a = jnp.asarray(ref).at[jnp.asarray(indices)]
        return getattr(a, mode)(updates)
    return f


OPS["scatterMax"] = _scatter_variant("max")
OPS["scatterMin"] = _scatter_variant("min")
OPS["scatterMul"] = _scatter_variant("multiply")
OPS["scatterSub"] = lambda ref, idx, upd: \
    jnp.asarray(ref).at[jnp.asarray(idx)].add(-jnp.asarray(upd))


@op("scatterNd")
def _scatter_nd(indices, updates, shape):
    """TF scatter_nd: indices [N,K] into zeros(shape)."""
    idx = tuple(jnp.moveaxis(jnp.asarray(indices), -1, 0))
    return jnp.zeros(tuple(int(s) for s in shape),
                     jnp.asarray(updates).dtype).at[idx].add(updates)


@op("dynamicStitch")
def _dynamic_stitch(indices_list, data_list):
    """TF dynamic_stitch with statically-known index tensors stacked as
    tuples; later entries win on duplicates (TF contract)."""
    import numpy as np

    total = sum(int(np.prod(np.asarray(i).shape))
                for i in indices_list)
    first = jnp.asarray(data_list[0])
    inner = first.shape[len(np.asarray(indices_list[0]).shape):]
    out = jnp.zeros((total,) + inner, first.dtype)
    for ind, dat in zip(indices_list, data_list):
        ind = jnp.asarray(ind).reshape(-1)
        dat = jnp.asarray(dat).reshape((-1,) + inner)
        out = out.at[ind].set(dat)
    return out


@op("mirrorPad")
def _mirror_pad(x, paddings, mode="REFLECT"):
    import numpy as np

    mode = {"REFLECT": "reflect", "SYMMETRIC": "symmetric"}[
        str(mode).upper()]
    pads = [tuple(int(v) for v in p) for p in np.asarray(paddings)]
    return jnp.pad(x, pads, mode=mode)


@op("rot90")
def _rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, int(k), axes=tuple(int(a) for a in axes))


@op("sconv2d")
def _sconv2d(x, depthWeights, pointWeights, strides=(1, 1),
             sameMode=True):
    """Separable conv2d: depthwise [kH,kW,C,M] (TF HWIO-depthwise
    layout) then pointwise [1,1,C*M,F]; NCHW data like conv2d."""
    dwt = jnp.asarray(depthWeights)
    # [kH,kW,C,M] -> depthwiseConv2d's [M, C, kH, kW]
    dw = OPS["depthwiseConv2d"](x, jnp.transpose(dwt, (3, 2, 0, 1)),
                                strides=strides, sameMode=sameMode)
    pw = jnp.asarray(pointWeights)
    f = pw.shape[-1]
    pw_oihw = jnp.transpose(pw.reshape(pw.shape[-2], f)[None, None],
                            (3, 2, 0, 1))
    return OPS["conv2d"](dw, pw_oihw, sameMode=True)


@op("localResponseNormalization")
def _lrn(x, depth=5, bias=1.0, alpha=1.0, beta=0.5):
    """TF nn.local_response_normalization, NCHW input."""
    c = x.shape[1]
    r = int(depth)
    sq = jnp.square(x)
    acc = sum(
        jnp.pad(sq, ((0, 0), (d, 0), (0, 0), (0, 0)))[:, :c]
        if d >= 0 else
        jnp.pad(sq, ((0, 0), (0, -d), (0, 0), (0, 0)))[:, -c:]
        for d in range(-r, r + 1))
    return x / jnp.power(bias + alpha * acc, beta)


@op("dilation2d")
def _dilation2d(x, w, sH=1, sW=1, sameMode=True):
    """Grayscale morphological dilation (TF nn.dilation2d), NCHW x
    [N,C,H,W], w [C,kH,kW]. SAME padding uses -inf (TF semantics):
    padding must never win the max, so the spatial pad is applied
    explicitly before VALID patch extraction."""
    x = jnp.asarray(x)
    w = jnp.asarray(w)
    c, kh, kw = w.shape
    if sameMode:
        # TF SAME pad depends on the strided output size:
        # pad = max((ceil(H/s)-1)*s + k - H, 0) — NOT a flat k-1,
        # which over-pads when stride > 1 and shifts every window
        h, w_in = x.shape[2], x.shape[3]
        oh = -(-h // int(sH))
        ow_ = -(-w_in // int(sW))
        ph = max((oh - 1) * int(sH) + kh - h, 0)
        pw_ = max((ow_ - 1) * int(sW) + kw - w_in, 0)
        # large finite negative, not -inf (one-hot-conv patch
        # extraction computes 0*pad, and -inf would poison it with
        # NaN) and bf16-representable (the TPU conv truncates operands
        # to bf16, where float32-min overflows to -inf)
        x = jnp.pad(x, ((0, 0), (0, 0),
                        (ph // 2, ph - ph // 2),
                        (pw_ // 2, pw_ - pw_ // 2)),
                    constant_values=-1e30)
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (int(sH), int(sW)), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        precision=lax.Precision.HIGHEST)
    n, _, oh, ow = patches.shape
    patches = patches.reshape(n, c, kh * kw, oh, ow)
    return jnp.max(patches + w.reshape(1, c, kh * kw, 1, 1), axis=2)


@op("adjustContrast")
def _adjust_contrast(x, factor):
    """Per-channel contrast about the spatial mean, NCHW (DL4J layout;
    the last two axes are H,W). NHWC images use adjustContrastV2, which
    the TF importer routes to."""
    x = jnp.asarray(x)
    mean = jnp.mean(x, axis=(-2, -1), keepdims=True) \
        if x.ndim == 4 else jnp.mean(x)
    return (x - mean) * factor + mean


def _rgb_to_hsv(x):
    """x [..., 3] in [0,1] -> HSV (TF image.rgb_to_hsv)."""
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.max(x, axis=-1)
    mn = jnp.min(x, axis=-1)
    d = mx - mn
    safe = jnp.where(d > 0, d, 1.0)
    h = jnp.where(
        mx == r, (g - b) / safe % 6.0,
        jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0))
    h = jnp.where(d > 0, h / 6.0, 0.0)
    s = jnp.where(mx > 0, d / jnp.where(mx > 0, mx, 1.0), 0.0)
    return jnp.stack([h, s, mx], axis=-1)


def _hsv_to_rgb(x):
    h, s, v = x[..., 0] * 6.0, x[..., 1], x[..., 2]
    i = jnp.floor(h)
    f = h - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(jnp.int32) % 6
    r = jnp.choose(i, [v, q, p, p, t, v], mode="clip")
    g = jnp.choose(i, [t, v, v, q, p, p], mode="clip")
    b = jnp.choose(i, [p, p, t, v, v, q], mode="clip")
    return jnp.stack([r, g, b], axis=-1)


OPS["rgbToHsv"] = _rgb_to_hsv
OPS["hsvToRgb"] = _hsv_to_rgb


@op("adjustHue")
def _adjust_hue(x, delta):
    hsv = _rgb_to_hsv(x)
    h = (hsv[..., 0] + delta) % 1.0
    return _hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]], -1))


@op("adjustSaturation")
def _adjust_saturation(x, factor):
    hsv = _rgb_to_hsv(x)
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return _hsv_to_rgb(jnp.stack([hsv[..., 0], s, hsv[..., 2]], -1))


@op("randomShuffle", random=True)
def _random_shuffle(x, key=None):
    return jax.random.permutation(key, x, axis=0)


@op("alphaDropout", random=True, training_aware=True)
def _alpha_dropout(x, p=0.05, key=None, training=False):
    """SELU-preserving dropout (Klambauer et al.); identity at
    inference."""
    if not training or key is None or p <= 0:
        return x
    alpha_p = -1.7580993408473766
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    # Klambauer et al. affine correction: a = ((1-p)(1 + p*a'^2))^-1/2
    # restores unit variance (the droped-out mixture has variance
    # (1-p)(1 + p*a'^2) around its mean)
    a = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
    b = -a * p * alpha_p
    return a * jnp.where(keep, x, alpha_p) + b


@op("gaussianDropout", random=True, training_aware=True)
def _gaussian_dropout(x, p=0.1, key=None, training=False):
    if not training or key is None or p <= 0:
        return x
    std = (p / (1.0 - p)) ** 0.5
    return x * (1.0 + std * jax.random.normal(key, x.shape, x.dtype))


@op("gaussianNoise", random=True, training_aware=True)
def _gaussian_noise(x, stddev=0.1, key=None, training=False):
    if not training or key is None:
        return x
    return x + stddev * jax.random.normal(key, x.shape, x.dtype)


@op("sparseSoftmaxCrossEntropyGrad")
def _sparse_softmax_ce_grad(z, y):
    """TF SparseSoftmaxCrossEntropyWithLogits: (loss [B],
    backprop [B, C])."""
    lp = jax.nn.log_softmax(z, axis=-1)
    loss = -jnp.take_along_axis(
        lp, jnp.asarray(y)[..., None].astype(jnp.int32), axis=-1)[..., 0]
    bp = jax.nn.softmax(z, axis=-1) - jax.nn.one_hot(
        y, z.shape[-1], dtype=z.dtype)
    return loss, bp


@op("adjustContrastV2")
def _adjust_contrast_nhwc(x, factor=1.0):
    """TF AdjustContrastv2: NHWC, per-channel spatial mean."""
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * factor + mean
