"""Host-side object-detection post-processing.

Reference capability: org.deeplearning4j.nn.layers.objdetect.{YoloUtils,
DetectedObject} (SURVEY.md §2.5/§2.7 — used by TinyYOLO/YOLO2 zoo
models). Decode runs on device inside the net's compiled forward (the
Yolo2OutputLayer.apply decode); thresholding + per-class non-max
suppression are a small host loop over the few surviving boxes, exactly
where the reference keeps them (they are O(detections²), not O(pixels)).
"""

from __future__ import annotations

import numpy as np


class DetectedObject:
    """One detection (reference: nn.layers.objdetect.DetectedObject);
    coordinates are grid units with (cx, cy) the box center."""

    def __init__(self, example, cx, cy, w, h, predicted_class, confidence,
                 class_predictions):
        self.exampleNumber = int(example)
        self.centerX = float(cx)
        self.centerY = float(cy)
        self.width = float(w)
        self.height = float(h)
        self.predictedClass = int(predicted_class)
        self.confidence = float(confidence)
        self.classPredictions = np.asarray(class_predictions)

    def getTopLeftXY(self):
        return (self.centerX - self.width / 2,
                self.centerY - self.height / 2)

    def getBottomRightXY(self):
        return (self.centerX + self.width / 2,
                self.centerY + self.height / 2)

    def __repr__(self):
        return (f"DetectedObject(example={self.exampleNumber}, "
                f"class={self.predictedClass}, conf={self.confidence:.3f}, "
                f"cx={self.centerX:.2f}, cy={self.centerY:.2f}, "
                f"w={self.width:.2f}, h={self.height:.2f})")


def _iou(a, b):
    ax1, ay1 = a.getTopLeftXY()
    ax2, ay2 = a.getBottomRightXY()
    bx1, by1 = b.getTopLeftXY()
    bx2, by2 = b.getBottomRightXY()
    iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
    ih = max(0.0, min(ay2, by2) - max(ay1, by1))
    inter = iw * ih
    union = ((ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) - inter)
    return inter / union if union > 0 else 0.0


class YoloUtils:
    @staticmethod
    def getPredictedObjects(decoded, threshold=0.5,
                            nms_threshold=0.4) -> list:
        """decoded: the Yolo2OutputLayer forward output
        [N, B, 5+C, H, W] (xy cell-relative, wh grid units, conf,
        class probs). Returns DetectedObjects above `threshold`
        object-confidence, NMS-suppressed per class at `nms_threshold`
        IoU (reference: YoloUtils.getPredictedObjects + nonMaxSuppression).
        """
        d = np.asarray(decoded)
        n, b, per, h, w = d.shape
        out = []
        conf = d[:, :, 4]                       # [N, B, H, W]
        keep = np.argwhere(conf > threshold)
        for ex, a, gy, gx in keep:
            vec = d[ex, a, :, gy, gx]
            cx, cy = vec[0] + gx, vec[1] + gy
            bw, bh = vec[2], vec[3]
            cls = vec[5:]
            out.append(DetectedObject(ex, cx, cy, bw, bh,
                                      int(np.argmax(cls)),
                                      vec[4] * cls.max(), cls))
        return YoloUtils.nonMaxSuppression(out, nms_threshold)

    @staticmethod
    def nonMaxSuppression(objects, iou_threshold=0.4) -> list:
        """Greedy per-example, per-class NMS keeping highest-confidence
        boxes."""
        kept = []
        by_key: dict = {}
        for o in objects:
            by_key.setdefault((o.exampleNumber, o.predictedClass),
                              []).append(o)
        for group in by_key.values():
            group.sort(key=lambda o: -o.confidence)
            chosen: list = []
            for o in group:
                if all(_iou(o, c) <= iou_threshold for c in chosen):
                    chosen.append(o)
            kept.extend(chosen)
        kept.sort(key=lambda o: (o.exampleNumber, -o.confidence))
        return kept
