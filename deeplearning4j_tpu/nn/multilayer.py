"""MultiLayerNetwork: the sequential-network runtime.

Reference capability: org.deeplearning4j.nn.multilayer.MultiLayerNetwork
(SURVEY.md §2.5, call stack §3.1). The reference's fit() walks layers
calling activate/backpropGradient with a JNI dispatch per op and assembles
a flat gradient for the Solver. Here the whole network lowers to ONE pure
function and fit() runs ONE compiled XLA step per minibatch:
forward + backward (jax.grad) + every per-layer updater fused, with
parameter/updater-state buffers donated (device-resident params — the
PJRT equivalent of the reference's flat-param views, SURVEY.md §7 hard
part 2). No Solver, no per-layer workspaces: XLA owns scheduling.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff.samediff import (
    _as_batches, _host_array, _ones_mask, _pad_to_bucket, _prepare_batches,
    _split_dataset_full)
from deeplearning4j_tpu.evaluation import Evaluation, RegressionEvaluation
from deeplearning4j_tpu.ndarray import INDArray
from deeplearning4j_tpu.nn.conf.configuration import (
    MultiLayerConfiguration, _apply_preprocessor)
from deeplearning4j_tpu.nn.conf.layers import OUTPUT_LAYER_TYPES


def _unwrap(x):
    if isinstance(x, INDArray):
        return x.jax()
    return jnp.asarray(x)


class GradientNormalization:
    ClipL2PerLayer = "clip_l2_per_layer"
    ClipL2PerParamType = "clip_l2_per_param"
    ClipElementWiseAbsoluteValue = "clip_elementwise"
    RenormalizeL2PerLayer = "renorm_l2_per_layer"


def _normalize_grads(grads, mode, threshold):
    if mode is None:
        return grads
    leaves = jax.tree_util.tree_leaves(grads)
    if mode == GradientNormalization.ClipElementWiseAbsoluteValue:
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -threshold, threshold), grads)
    norm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves) + 1e-12)
    if mode == GradientNormalization.RenormalizeL2PerLayer:
        return jax.tree_util.tree_map(lambda g: g / norm, grads)
    scale = jnp.minimum(1.0, threshold / norm)
    return jax.tree_util.tree_map(lambda g: g * scale, grads)


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        if not self.layers:
            raise ValueError("configuration has no layers")
        out = self.layers[-1]
        if not isinstance(out, OUTPUT_LAYER_TYPES):
            raise ValueError("last layer must be an OutputLayer/LossLayer")
        self._params: list[dict] = []
        self._states: list[dict] = []
        self._opt_states: list = []
        self._listeners: list = []
        self._train_step = None
        self._bucket = None  # fit batch-size bucket (pad ragged tail to it)
        self._infer_fns: dict = {}
        self._iteration = 0
        self._epoch = 0
        self._score = None
        self._initialized = False

    # -- init ----------------------------------------------------------------
    def init(self):
        dtype = self.conf.dtype
        key = jax.random.key(self.conf.seed)
        self._params, self._states = [], []
        for i, lr in enumerate(self.layers):
            self._params.append(lr.init_params(jax.random.fold_in(key, i),
                                               dtype))
            self._states.append(lr.init_state(dtype))
        self._opt_states = [
            self._layer_updater(i).init_state(p) if p else ()
            for i, p in enumerate(self._params)
        ]
        self._initialized = True
        return self

    def _layer_updater(self, i):
        u = self.layers[i].updater
        return u if u is not None else self.conf.defaults["updater"]

    def _check_init(self):
        if not self._initialized:
            raise RuntimeError("call init() first")

    # -- pure forward --------------------------------------------------------
    def _forward(self, params, states, x, training, rng, upto=None):
        new_states = []
        n = len(self.layers) if upto is None else upto
        for i in range(n):
            lr = self.layers[i]
            x = _apply_preprocessor(self.conf.preprocessors[i], x)
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            x, st = lr.apply(params[i], states[i], x, training, lrng)
            new_states.append(st)
        new_states.extend(states[n:])
        return x, new_states

    def _loss_from(self, params, states, f, l, training, rng, mask=None):
        """Forward to the last hidden activation, then the output layer's
        fused pre-activation loss (stable logits path)."""
        out_idx = len(self.layers) - 1
        h, new_states = self._forward(params, states, f, training, rng,
                                      upto=out_idx)
        h = _apply_preprocessor(self.conf.preprocessors[out_idx], h)
        out_layer = self.layers[out_idx]
        loss = out_layer.compute_loss(params[out_idx], h, l, mask)
        # L1/L2 regularization per layer (reference: BaseLayer.calcRegularizationScore)
        reg = 0.0
        for i, lr in enumerate(self.layers):
            if not params[i]:
                continue
            l2 = lr.l2 or 0.0
            l1 = lr.l1 or 0.0
            if l2:
                reg = reg + l2 * sum(jnp.sum(w * w)
                                     for w in jax.tree_util.tree_leaves(
                                         params[i])) * 0.5
            if l1:
                reg = reg + l1 * sum(jnp.sum(jnp.abs(w))
                                     for w in jax.tree_util.tree_leaves(
                                         params[i]))
        return loss + reg, new_states

    # -- compiled train step -------------------------------------------------
    def _build_train_step(self):
        updaters = [self._layer_updater(i) for i in range(len(self.layers))]

        def step(params, states, opt_states, f, l, lmask, rng, it):
            def loss_fn(p):
                loss, ns = self._loss_from(p, states, f, l, True, rng,
                                           mask=lmask)
                return loss, ns

            (loss, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opts = [], []
            for i, lr in enumerate(self.layers):
                g = grads[i]
                if not g:
                    new_params.append(params[i])
                    new_opts.append(opt_states[i])
                    continue
                g = _normalize_grads(g, lr.gradientNormalization,
                                     lr.gradientNormalizationThreshold or 1.0)
                upd, new_opt = updaters[i].apply(g, opt_states[i], params[i],
                                                 it)
                new_params.append(jax.tree_util.tree_map(
                    lambda p, u: p - u, params[i], upd))
                new_opts.append(new_opt)
            return loss, new_params, new_states, new_opts

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def fit(self, data, epochs: int | None = None):
        """fit(iterator) / fit(iterator, nEpochs) / fit(features, labels) /
        fit(DataSet)."""
        self._check_init()
        if epochs is not None and not isinstance(epochs, int):
            # fit(features, labels)
            data, epochs = (data, epochs), 1
        epochs = epochs or 1
        if self._train_step is None:
            self._train_step = self._build_train_step()

        params, states, opts = self._params, self._states, self._opt_states
        base_key = jax.random.key(self.conf.seed + 1)
        last_loss = None
        for epoch_i in range(epochs):
            batches, data = _prepare_batches(data, epoch_i, epochs)
            for ds in batches:
                feats, labels, _, lmasks = _split_dataset_full(ds)
                f = _host_array(feats[0])
                l = _host_array(labels[0])
                # always train with an explicit mask so the jit signature
                # (and hence the ONE compiled executable) is stable whether
                # or not the batch is ragged/masked
                lmask = (_host_array(lmasks[0], np.float32)
                         if lmasks[0] is not None else _ones_mask(l))
                if self._bucket is None or f.shape[0] > self._bucket:
                    self._bucket = f.shape[0]
                if f.shape[0] < self._bucket:
                    (f, l), lmask, _ = _pad_to_bucket([f, l], lmask,
                                                      self._bucket)
                rng = jax.random.fold_in(base_key, self._iteration)
                loss, params, states, opts = self._train_step(
                    params, states, opts, f, l, lmask, rng, self._iteration)
                # rebind before anything can observe donated buffers
                self._params, self._states, self._opt_states = (
                    params, states, opts)
                self._iteration += 1
                last_loss = loss
                if self._listeners:
                    lv = float(loss)
                    self._score = lv
                    for listener in self._listeners:
                        listener.iterationDone(self, self._iteration,
                                               self._epoch)
            self._epoch += 1
        if last_loss is not None:
            self._score = float(last_loss)
        return self

    # -- inference -----------------------------------------------------------
    def _infer_fn(self, training=False):
        key = ("out", training)
        if key not in self._infer_fns:
            def fn(params, states, x):
                y, _ = self._forward(params, states, x, training, None)
                return y

            self._infer_fns[key] = jax.jit(fn)
        return self._infer_fns[key]

    def output(self, x, train: bool = False) -> INDArray:
        self._check_init()
        y = self._infer_fn(train)(self._params, self._states, _unwrap(x))
        return INDArray(y)

    def feedForward(self, x, train: bool = False) -> list:
        """All layer activations (reference returns input + each layer's
        activation)."""
        self._check_init()
        x = _unwrap(x)
        acts = [INDArray(x)]
        states = self._states
        for i, lr in enumerate(self.layers):
            x = _apply_preprocessor(self.conf.preprocessors[i], x)
            x, _ = lr.apply(self._params[i], states[i], x, train, None)
            acts.append(INDArray(x))
        return acts

    def rnnTimeStep(self, x):
        """Minimal streaming inference (TBPTT capability, SURVEY.md §2.5):
        full-sequence output of the final step."""
        return self.output(x)

    # -- scoring / eval ------------------------------------------------------
    def score(self, dataset=None) -> float:
        self._check_init()
        if dataset is None:
            if self._score is None:
                raise ValueError("no score yet: call fit() or score(dataset)")
            return self._score
        feats, labels, _, lmasks = _split_dataset_full(dataset)
        lmask = None if lmasks[0] is None else _unwrap(lmasks[0])
        loss, _ = self._loss_from(self._params, self._states,
                                  _unwrap(feats[0]), _unwrap(labels[0]),
                                  False, None, mask=lmask)
        return float(loss)

    def evaluate(self, iterator, numClasses=None) -> Evaluation:
        self._check_init()
        ev = Evaluation(numClasses)
        for ds in _as_batches(iterator):
            feats, labels, _, lmasks = _split_dataset_full(ds)
            out = self.output(feats[0])
            ev.eval(labels[0], out, mask=lmasks[0])
        return ev

    def evaluateRegression(self, iterator) -> RegressionEvaluation:
        ev = RegressionEvaluation()
        for ds in _as_batches(iterator):
            feats, labels, _, lmasks = _split_dataset_full(ds)
            out = self.output(feats[0])
            ev.eval(labels[0], out, mask=lmasks[0])
        return ev

    # -- params --------------------------------------------------------------
    def params(self) -> INDArray:
        """Flat parameter vector in layer order (reference:
        MultiLayerNetwork.params() flat view)."""
        self._check_init()
        leaves = []
        for p in self._params:
            for k in sorted(p):
                leaves.append(jnp.ravel(p[k]))
        if not leaves:
            return INDArray(jnp.zeros((0,)))
        return INDArray(jnp.concatenate(leaves))

    def setParams(self, flat):
        self._check_init()
        flat = _unwrap(flat).reshape(-1)
        off = 0
        for p in self._params:
            for k in sorted(p):
                n = int(np.prod(p[k].shape)) if p[k].shape else 1
                p[k] = flat[off: off + n].reshape(p[k].shape).astype(
                    p[k].dtype)
                off += n
        self._train_step = None

    def numParams(self) -> int:
        return sum(int(np.prod(v.shape)) for p in self._params
                   for v in p.values())

    def getParam(self, layer_idx: int, name: str) -> INDArray:
        return INDArray(self._params[layer_idx][name])

    def setParam(self, layer_idx: int, name: str, value):
        self._params[layer_idx][name] = _unwrap(value)

    def paramTable(self) -> dict:
        return {f"{i}_{k}": INDArray(v)
                for i, p in enumerate(self._params) for k, v in p.items()}

    def gradients(self, features, labels) -> list[dict]:
        """Per-layer analytic gradients (for the gradient-check harness,
        SURVEY.md §4)."""
        self._check_init()
        f, l = _unwrap(features), _unwrap(labels)

        def loss_fn(p):
            loss, _ = self._loss_from(p, self._states, f, l, False, None)
            return loss

        return jax.grad(loss_fn)(self._params)

    def computeGradientAndScore(self, features, labels):
        f, l = _unwrap(features), _unwrap(labels)

        def loss_fn(p):
            loss, _ = self._loss_from(p, self._states, f, l, False, None)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(self._params)
        self._score = float(loss)
        return grads, self._score

    # -- listeners / misc ----------------------------------------------------
    def setListeners(self, *listeners):
        self._listeners = list(listeners)
        return self

    def addListeners(self, *listeners):
        self._listeners.extend(listeners)
        return self

    def getListeners(self):
        return list(self._listeners)

    def getIterationCount(self):
        return self._iteration

    def getEpochCount(self):
        return self._epoch

    def clone(self) -> "MultiLayerNetwork":
        other = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(self.conf.to_json()))
        if self._initialized:
            other.init()
            # real copies, not aliases: the source's next fit() DONATES its
            # buffers, which would invalidate shared references
            copy = lambda x: jnp.array(x, copy=True)  # noqa: E731
            other._params = jax.tree_util.tree_map(copy, self._params)
            other._states = jax.tree_util.tree_map(copy, self._states)
            other._opt_states = jax.tree_util.tree_map(copy, self._opt_states)
        return other

    def summary(self) -> str:
        lines = [f"{'idx':<4}{'layer':<28}{'nParams':<10}{'shape'}"]
        for i, (lr, p) in enumerate(zip(self.layers, self._params)):
            n = sum(int(np.prod(v.shape)) for v in p.values())
            shapes = {k: tuple(v.shape) for k, v in p.items()}
            lines.append(f"{i:<4}{type(lr).__name__:<28}{n:<10}{shapes}")
        lines.append(f"Total params: {self.numParams()}")
        return "\n".join(lines)
