"""MultiLayerNetwork: the sequential-network runtime.

Reference capability: org.deeplearning4j.nn.multilayer.MultiLayerNetwork
(SURVEY.md §2.5, call stack §3.1). The reference's fit() walks layers
calling activate/backpropGradient with a JNI dispatch per op and assembles
a flat gradient for the Solver. Here the whole network lowers to ONE pure
function and fit() runs ONE compiled XLA step per minibatch:
forward + backward (jax.grad) + every per-layer updater fused, with
parameter/updater-state buffers donated (device-resident params — the
PJRT equivalent of the reference's flat-param views, SURVEY.md §7 hard
part 2). No Solver, no per-layer workspaces: XLA owns scheduling.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff.samediff import (
    _as_batches, _host_array, _ones_mask, _pad_to_bucket, _prepare_batches,
    _split_dataset_full)
from deeplearning4j_tpu.evaluation import Evaluation, RegressionEvaluation
from deeplearning4j_tpu.ndarray import INDArray
from deeplearning4j_tpu.nn.conf.configuration import (
    BackpropType, MultiLayerConfiguration, _apply_preprocessor)
from deeplearning4j_tpu.nn.conf.layers import OUTPUT_LAYER_TYPES


def _unwrap(x):
    if isinstance(x, INDArray):
        return x.jax()
    return jnp.asarray(x)


class GradientNormalization:
    ClipL2PerLayer = "clip_l2_per_layer"
    ClipL2PerParamType = "clip_l2_per_param"
    ClipElementWiseAbsoluteValue = "clip_elementwise"
    RenormalizeL2PerLayer = "renorm_l2_per_layer"


def _normalize_grads(grads, mode, threshold):
    if mode is None:
        return grads
    leaves = jax.tree_util.tree_leaves(grads)
    if mode == GradientNormalization.ClipElementWiseAbsoluteValue:
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -threshold, threshold), grads)
    norm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves) + 1e-12)
    if mode == GradientNormalization.RenormalizeL2PerLayer:
        return jax.tree_util.tree_map(lambda g: g / norm, grads)
    scale = jnp.minimum(1.0, threshold / norm)
    return jax.tree_util.tree_map(lambda g: g * scale, grads)


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        if not self.layers:
            raise ValueError("configuration has no layers")
        out = self.layers[-1]
        if not isinstance(out, OUTPUT_LAYER_TYPES):
            raise ValueError("last layer must be an OutputLayer/LossLayer")
        self._params: list[dict] = []
        self._states: list[dict] = []
        self._opt_states: list = []
        self._prec_state: dict = {}  # loss-scaler state (ISSUE 4); {} = off
        self._listeners: list = []
        self._train_step = None
        self._train_step_plan = None  # health BuildPlan compiled into it
        self._multi_step = None
        self._bucket = None  # fit batch-size bucket (pad ragged tail to it)
        self._infer_fns: dict = {}
        self._profiler_cfg = None
        self._stream_states = None   # rnnTimeStep carried state per layer
        self._stream_batch = None
        self._iteration = 0
        self._epoch = 0
        self._score = None
        self._initialized = False

    # -- init ----------------------------------------------------------------
    def init(self):
        # master weights follow the precision policy's param dtype (fp32
        # under any *_mixed policy — the compute cast happens inside the
        # step); without a policy this is exactly conf.dtype as before
        pol = self._precision_policy()
        dtype = pol.param_jnp
        key = jax.random.key(self.conf.seed)
        self._params, self._states = [], []
        for i, lr in enumerate(self.layers):
            self._params.append(lr.init_params(jax.random.fold_in(key, i),
                                               dtype))
            self._states.append(lr.init_state(dtype))
        self._opt_states = [
            self._layer_updater(i).init_state(p) if p else ()
            for i, p in enumerate(self._params)
        ]
        scaler = self._loss_scaler()
        self._prec_state = scaler.init_state() if scaler else {}
        self._initialized = True
        return self

    def _precision_policy(self):
        return self.conf.precision_policy

    def _loss_scaler(self):
        """The policy's loss scaler (built once per net), or None."""
        from deeplearning4j_tpu.precision import DynamicLossScaler

        if not hasattr(self, "_scaler_cache"):
            self._scaler_cache = DynamicLossScaler.for_policy(
                self._precision_policy())
        return self._scaler_cache

    def _layer_updater(self, i):
        u = self.layers[i].updater
        return u if u is not None else self.conf.defaults["updater"]

    def _check_init(self):
        if not self._initialized:
            raise RuntimeError("call init() first")

    # -- pure forward --------------------------------------------------------
    def _forward(self, params, states, x, training, rng, upto=None,
                 compute_dtype=None):
        # float inputs follow the policy's COMPUTE dtype (== the
        # configured dataType without a policy, so bf16 nets accept
        # f32-fed batches exactly as before); int inputs (embedding ids)
        # pass through, and f64 is left alone — the gradient-check
        # harness runs the whole net in fp64. compute_dtype overrides
        # the policy for callers that pick their own activation dtype.
        dt = compute_dtype if compute_dtype is not None \
            else self._precision_policy().compute_jnp
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dt \
                and x.dtype != jnp.float64:
            x = x.astype(dt)
        new_states = []
        n = len(self.layers) if upto is None else upto
        for i in range(n):
            lr = self.layers[i]
            x = _apply_preprocessor(self.conf.preprocessors[i], x)
            lrng = jax.random.fold_in(rng, i) if rng is not None else None
            x, st = lr.apply(params[i], states[i], x, training, lrng)
            new_states.append(st)
        new_states.extend(states[n:])
        return x, new_states

    def _loss_from(self, params, states, f, l, training, rng, mask=None):
        """Forward to the last hidden activation, then the output layer's
        fused pre-activation loss (stable logits path). Under a mixed
        precision policy the (master-dtype) params are cast to the
        compute dtype HERE — inside whatever is being differentiated —
        so the cast's transpose upcasts gradients back to the master
        dtype and Adam/SGD moments stay fp32."""
        from deeplearning4j_tpu.precision import cast_floating

        pol = self._precision_policy()
        if pol.is_mixed:
            params = cast_floating(params, pol.compute_jnp)
        out_idx = len(self.layers) - 1
        h, new_states = self._forward(params, states, f, training, rng,
                                      upto=out_idx)
        h = _apply_preprocessor(self.conf.preprocessors[out_idx], h)
        out_layer = self.layers[out_idx]
        if training and getattr(out_layer, "LOSS_UPDATES_STATE", False):
            # loss-state channel (e.g. OCNN's r threshold): the output
            # layer's apply() never runs during training, so its state
            # updates ride along with the loss
            loss, new_states[out_idx] = out_layer.compute_loss_with_state(
                params[out_idx], h, l, mask, states[out_idx])
        else:
            loss = out_layer.compute_loss(params[out_idx], h, l, mask)
        # hidden-layer aux-loss channel: any layer may store a scalar under
        # "_aux_loss" in its state (e.g. MoELayer's load-balancing loss);
        # summed into the training objective so gradients flow through the
        # layer's forward computation
        if training:
            for st in new_states:
                if isinstance(st, dict) and "_aux_loss" in st:
                    loss = loss + st["_aux_loss"]
        # L1/L2 regularization per layer (reference: BaseLayer.calcRegularizationScore)
        reg = 0.0
        for i, lr in enumerate(self.layers):
            if not params[i]:
                continue
            l2 = lr.l2 or 0.0
            l1 = lr.l1 or 0.0
            if l2:
                reg = reg + l2 * sum(jnp.sum(w * w)
                                     for w in jax.tree_util.tree_leaves(
                                         params[i])) * 0.5
            if l1:
                reg = reg + l1 * sum(jnp.sum(jnp.abs(w))
                                     for w in jax.tree_util.tree_leaves(
                                         params[i]))
        return loss + reg, new_states

    # -- compiled train step -------------------------------------------------
    def _layer_labels(self):
        """Health-row labels (one per layer + the trailing loss row),
        row-aligned with the health array the step returns
        (telemetry.health, ISSUE 3)."""
        from deeplearning4j_tpu.telemetry import health as _health

        return _health.with_loss_row(
            f"{i}:{type(lr).__name__}"
            for i, lr in enumerate(self.layers))

    def _step_math(self, updaters, params, states, opt_states, prec, f, l,
                   lmask, rng, it, health_plan=None):
        """One optimizer step as a pure traced function (shared by the
        single-step jit and the scan-of-K-steps jit). When the health
        plan collects, per-layer stats ride along as one small [L, 5]
        array (fused reductions — no extra dispatch); with the
        SKIP_BATCH policy a non-finite step keeps the old
        params/states/opts via an in-graph select. When the precision
        policy enables loss scaling, `prec` carries the scaler state:
        the loss is scaled before the backward pass, gradients are
        unscaled (exactly — powers of two), a fused finite check gates
        the whole update through the same keep-old-params jnp.where,
        and the scaler state advances — all on device, zero host syncs
        for an overflow step."""
        from deeplearning4j_tpu.telemetry import health as _health

        plan = health_plan or _health.INACTIVE
        scaler = self._loss_scaler()
        scaling = scaler is not None and bool(prec)

        def loss_fn(p):
            loss, ns = self._loss_from(p, states, f, l, True, rng,
                                       mask=lmask)
            if scaling:
                return scaler.scale_loss(loss, prec), (loss, ns)
            return loss, (loss, ns)

        (_, (loss, new_states)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if scaling:
            grads = scaler.unscale(grads, prec)
            finite = scaler.all_finite(grads)
        new_params, new_opts, stats = [], [], []
        for i, lr in enumerate(self.layers):
            g = grads[i]
            if not g:
                new_params.append(params[i])
                new_opts.append(opt_states[i])
                if plan.collect:
                    stats.append(_health.zero_stats())
                continue
            g = _normalize_grads(g, lr.gradientNormalization,
                                 lr.gradientNormalizationThreshold or 1.0)
            upd, new_opt = updaters[i].apply_mixed(g, opt_states[i],
                                                   params[i], it)
            new_params.append(jax.tree_util.tree_map(
                lambda p, u: p - u, params[i], upd))
            new_opts.append(new_opt)
            if plan.collect:
                stats.append(_health.layer_stats(g, upd, new_params[-1]))
        if plan.collect:
            stats.append(_health.loss_stats(loss))
        health = _health.stack_stats(stats) if plan.collect else None
        if scaling:
            new_params = _health.keep_if(finite, new_params, params)
            new_opts = _health.keep_if(finite, new_opts, opt_states)
            new_states = _health.keep_if(finite, new_states, states)
            new_prec = scaler.next_state(prec, finite)
        else:
            new_prec = prec
        if plan.skip:
            ok = _health.step_ok(health)
            new_params = _health.keep_if(ok, new_params, params)
            new_opts = _health.keep_if(ok, new_opts, opt_states)
            new_states = _health.keep_if(ok, new_states, states)
        return loss, new_params, new_states, new_opts, health, new_prec

    def _build_train_step(self, health_plan=None):
        updaters = [self._layer_updater(i) for i in range(len(self.layers))]

        def step(params, states, opt_states, prec, f, l, lmask, rng, it):
            return self._step_math(updaters, params, states, opt_states,
                                   prec, f, l, lmask, rng, it,
                                   health_plan=health_plan)

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _policy_label(self, plan):
        """The compile-ledger/executable-store policy label: precision
        policy + health build plan, both compiled INTO the step — a
        change in either recompiles, and forensics should name it
        policy_change."""
        return (f"{self._precision_policy().name}"
                f"/h{int(plan.collect)}{int(plan.skip)}")

    def _step_program(self, plan, kind="train"):
        """Executable-store program digest: the configuration JSON is
        the full architecture + updater spec (weights are arguments),
        and the policy label covers what else is compiled in."""
        return (f"{kind}:MultiLayerNetwork:{self.conf.to_json()}"
                f":policy={self._policy_label(plan)}")

    def _refresh_train_step(self):
        """(re)build the compiled step when missing or when the health
        build plan changed (telemetry/health toggled, policy changed) —
        the plan is compiled into the step, so it must invalidate."""
        from deeplearning4j_tpu import compilestore
        from deeplearning4j_tpu.telemetry import health as _health

        plan = _health.build_plan(self._listeners)
        if self._train_step is None or \
                getattr(self, "_train_step_plan", None) != plan:
            step = self._build_train_step(plan)
            if compilestore.enabled():
                # ISSUE 13: a warm restart's first step deserializes
                # this signature's executable from the persistent
                # store (milliseconds) instead of recompiling
                step = compilestore.StoredJit(
                    step, "fit", program=self._step_program(plan),
                    policy=self._policy_label(plan),
                    donation=(0, 1, 2))
            self._train_step = step
            self._train_step_plan = plan
        return plan

    def _build_multi_step(self, repeats=1, health_plan=None):
        from deeplearning4j_tpu.telemetry import health as _health

        plan = health_plan or _health.INACTIVE
        updaters = [self._layer_updater(i) for i in range(len(self.layers))]

        def many(params, states, opts, prec, f_k, l_k, m_k, rng0, it0):
            def body(carry, xs):
                params, states, opts, prec, it = carry
                f, l, m = xs
                rng = jax.random.fold_in(rng0, it)
                loss, params, states, opts, health, prec = self._step_math(
                    updaters, params, states, opts, prec, f, l, m, rng, it,
                    health_plan=plan)
                ys = (loss, health) if plan.collect else loss
                return (params, states, opts, prec, it + 1), ys

            def scan_once(carry, _):
                return jax.lax.scan(body, carry, (f_k, l_k, m_k))

            carry = (params, states, opts, prec, it0)
            if repeats == 1:
                carry, ys = scan_once(carry, None)
            else:
                # R passes over the same K batches in one launch (used by
                # slope-based benchmarking; also a legit small-dataset
                # multi-epoch fit) — only the last pass's losses return
                carry, ys_r = jax.lax.scan(scan_once, carry,
                                           None, length=repeats)
                ys = jax.tree_util.tree_map(lambda a: a[-1], ys_r)
            losses, healths = ys if plan.collect else (ys, None)
            params, states, opts, prec, _ = carry
            return losses, params, states, opts, healths, prec

        return jax.jit(many, donate_argnums=(0, 1, 2))

    def fitMultiBatch(self, features_k, labels_k, repeats: int = 1):
        """K optimizer steps in ONE device launch: features_k/labels_k are
        stacked [K, batch, ...] minibatches consumed by a lax.scan. This
        amortizes per-dispatch host/RPC latency (on the axon TPU tunnel a
        single dispatch round-trip exceeds a whole small-model step) the
        way an on-device input pipeline would; semantics match K
        successive fit() calls on the K slices. Returns the [K] losses
        (of the last pass when repeats > 1)."""
        self._check_init()
        from deeplearning4j_tpu.telemetry import health as _health

        plan = _health.build_plan(self._listeners)
        if not isinstance(self._multi_step, dict):
            self._multi_step = {}
        key = (repeats, plan)
        if key not in self._multi_step:
            many = self._build_multi_step(repeats, plan)
            from deeplearning4j_tpu import compilestore

            if compilestore.enabled():
                many = compilestore.StoredJit(
                    many, "fit:multi",
                    program=self._step_program(plan, kind="multi")
                    + f":repeats={repeats}",
                    policy=self._policy_label(plan),
                    donation=(0, 1, 2))
            self._multi_step[key] = many
        # keep device-resident stacks on device (a _host_array bounce
        # would round-trip the whole [K,B,...] block D2H then H2D)
        f_k = _unwrap(features_k) if isinstance(
            features_k, (jax.Array, INDArray)) else _host_array(features_k)
        l_k = _unwrap(labels_k) if isinstance(
            labels_k, (jax.Array, INDArray)) else _host_array(labels_k)
        m_k = np.ones((l_k.shape[0],) + _ones_mask(l_k[0]).shape,
                      np.float32)
        rng0 = jax.random.key(self.conf.seed + 1)
        it0 = self._iteration
        from deeplearning4j_tpu import precision as _precision

        pm = _precision.monitor_for("fit", self._precision_policy())
        if pm is not None:
            pm.baseline_from(self._prec_state)   # pre-launch count
        import time as _time

        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.telemetry import costmodel

        t_launch = _time.perf_counter() if telemetry.enabled() else None
        try:
            (losses, self._params, self._states, self._opt_states,
             healths, self._prec_state) = self._multi_step[key](
                    self._params, self._states, self._opt_states,
                    self._prec_state, f_k, l_k, m_k, rng0,
                    jnp.asarray(self._iteration, jnp.int32))
        except Exception as e:
            from deeplearning4j_tpu.telemetry import memledger

            memledger.raise_if_oom(e, site="train.fitMultiBatch",
                                   step=self._iteration)
            raise
        self._iteration += int(f_k.shape[0]) * repeats
        self._score = float(losses[-1])
        if t_launch is not None:
            # float(losses[-1]) materialized the launch, so this wall
            # time covers the device work
            n_steps = int(f_k.shape[0]) * repeats
            per_step = (_time.perf_counter() - t_launch) / max(1, n_steps)
            timed = getattr(self, "_multi_timed", None)
            if timed is None:
                timed = self._multi_timed = set()
            # the FIRST launch of a (repeats, plan) key compiled inside
            # the timed region, so its per-step wall is useless for MFU
            # (10-100x understated): only a key already seen is warm
            warm = key in timed
            timed.add(key)
            costmodel.attribute_launch(
                "fit", self._multi_step[key],
                (self._params, self._states, self._opt_states,
                 self._prec_state, f_k, l_k, m_k, rng0,
                 jnp.asarray(it0, jnp.int32)),
                self, per_step, warm)
        if pm is not None:
            # publish from the launch's FINAL scaler state (already
            # materialized — we just read losses): scale gauge + the
            # overflow-count delta accumulated across the K steps
            pm.on_launch(range(it0, self._iteration), self._prec_state)
        if healths is not None:
            hm = _health.monitor_for("fit", self._layer_labels(),
                                     self._listeners)
            if hm is not None:
                hm.precision = pm
                # the [K, L, 5] stack is already materialized (we just
                # read losses), so processing here adds no sync
                base = it0 + (repeats - 1) * int(f_k.shape[0])
                for k in range(int(f_k.shape[0])):
                    hm.on_step(base + k, healths[k])
                hm.flush()
        return losses

    def _prefetch_prepare(self):
        """The host-side half of the input pipeline, run in the
        DevicePrefetcher's producer thread: split + pad-to-bucket +
        mask build + device_put, so the fit loop's per-batch host work
        collapses to a queue pop. Falls back to the raw DataSet (and
        the classic host path) for shapes it does not understand."""
        from deeplearning4j_tpu.datasets.prefetch import DeviceBatch

        def prepare(ds):
            feats, labels, _, lmasks = _split_dataset_full(ds)
            if len(feats) != 1 or len(labels) != 1:
                return ds
            f = _host_array(feats[0])
            l = _host_array(labels[0])
            lmask = (_host_array(lmasks[0], np.float32)
                     if lmasks[0] is not None else _ones_mask(l))
            real = f.shape[0]
            bucket = max(real, self._bucket or 0)
            if real < bucket:
                (f, l), lmask, _ = _pad_to_bucket([f, l], lmask, bucket)
            if f.dtype != np.float32:
                f = f.astype(np.float32)
            return DeviceBatch(jax.device_put(f), jax.device_put(l),
                               jax.device_put(lmask), bucket=bucket,
                               real=real)

        return prepare

    def _wrap_prefetch(self, data):
        """Auto-wrap a plain DataSetIterator in a DevicePrefetcher
        (ISSUE 6: transfer overlaps compute on every consumption path).
        Returns (data, prefetcher-or-None); callers close() it."""
        from deeplearning4j_tpu.datasets import prefetch as _prefetch
        from deeplearning4j_tpu.datasets.iterator import (
            DataSetIterator as _DSI)

        if (isinstance(data, _DSI)
                and not isinstance(data, _prefetch.DevicePrefetcher)
                and data.asyncSupported()
                and _prefetch.default_depth() > 0
                and self.conf.backpropType != BackpropType.TruncatedBPTT):
            wrapped = _prefetch.DevicePrefetcher(
                data, prepare=self._prefetch_prepare(), loop="fit")
            return wrapped, wrapped
        return data, None

    def fit(self, data, epochs: int | None = None):
        """fit(iterator) / fit(iterator, nEpochs) / fit(features, labels) /
        fit(DataSet)."""
        self._check_init()
        if epochs is not None and not isinstance(epochs, int):
            # fit(features, labels)
            data, epochs = (data, epochs), 1
        epochs = epochs or 1

        import time as _time

        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.datasets.prefetch import DeviceBatch
        from deeplearning4j_tpu.telemetry import (
            compile_ledger, costmodel, memledger, tracing)
        from deeplearning4j_tpu.telemetry import health as _health

        plan = self._refresh_train_step()
        policy_label = self._policy_label(plan)
        data, _prefetcher = self._wrap_prefetch(data)
        params, states, opts = self._params, self._states, self._opt_states
        prec = self._prec_state
        base_key = jax.random.key(self.conf.seed + 1)
        last_loss = None
        # one flag check per fit(): with telemetry disabled tele is None
        # and the loop body makes zero registry calls per step
        tele = telemetry.loop_instruments("fit")
        # HBM ownership claim (ISSUE 14): params + updater state +
        # loss-scale state, keyed to THIS net (two nets fitting through
        # the same loop label must not re-state one claim). None when
        # disabled — the loop guards on the handle, so the per-step
        # touch() (ONE gauge-set) compiles out
        mem = None if tele is None else memledger.claim_for_owner(
            self, "train", "fit",
            tree={"p": params, "s": states, "o": opts, "prec": prec},
            model=type(self).__name__)
        # same contract for health: hm is None when health/telemetry is
        # off, and the jitted step then returns no health array at all
        hm = _health.monitor_for("fit", self._layer_labels(),
                                 self._listeners)
        # loss-scaler publication (None unless the policy scales AND
        # telemetry is on; the on-device gate runs regardless). The
        # health monitor defers its SKIP_BATCH accounting to pm for
        # steps the scaler already skipped (no double counting).
        from deeplearning4j_tpu import precision as _precision

        pm = _precision.monitor_for("fit", self._precision_policy())
        if pm is not None:
            pm.baseline_from(prec)
        if hm is not None:
            hm.precision = pm
        # sampled trace root (ISSUE 10): NULL (falsy, no tracer calls)
        # when telemetry/tracing is off or the head sampler said no;
        # nests under an enclosing context (ElasticTrainer root) so
        # checkpoints and ETL spans land in the same tree. Entered
        # manually: the epoch loop below must stay at its indentation,
        # and the finally below closes the span on every exit path.
        import sys as _sys

        tspan = tracing.trace_or_span("train.fit", loop="fit")
        tspan.__enter__()
        steps_seen = 0
        try:
            for epoch_i in range(epochs):
                batches, data = _prepare_batches(data, epoch_i, epochs)
                batch_iter = iter(batches)
                while True:
                    if tele is not None:
                        t_etl = _time.perf_counter()
                    ds = next(batch_iter, None)
                    if ds is None:
                        break
                    if tele is not None:
                        tele.record_etl_wait(_time.perf_counter() - t_etl)
                    if isinstance(ds, DeviceBatch) and (
                            self._bucket is None
                            or ds.bucket >= self._bucket):
                        # prefetched: pad/mask/transfer already happened in
                        # the producer thread, arrays are device-resident
                        f, l, lmask = ds.features, ds.labels, ds.mask
                        self._bucket = ds.bucket
                    elif isinstance(ds, DeviceBatch):
                        # staged against a smaller bucket than the
                        # compiled executable's (producer raced a bucket
                        # growth): rejoin the host pad path, KEEPING the
                        # staged mask so already-padded rows stay
                        # zero-weighted
                        f = np.asarray(ds.features)
                        l = np.asarray(ds.labels)
                        lmask = np.asarray(ds.mask)
                        if f.shape[0] < self._bucket:
                            (f, l), lmask, _ = _pad_to_bucket(
                                [f, l], lmask, self._bucket)
                    else:
                        feats, labels, _, lmasks = _split_dataset_full(ds)
                        f = _host_array(feats[0])
                        l = _host_array(labels[0])
                        # always train with an explicit mask so the jit
                        # signature (and hence the ONE compiled executable)
                        # is stable whether or not the batch is ragged/masked
                        lmask = (_host_array(lmasks[0], np.float32)
                                 if lmasks[0] is not None else _ones_mask(l))
                        if self._bucket is None or f.shape[0] > self._bucket:
                            self._bucket = f.shape[0]
                        if f.shape[0] < self._bucket:
                            (f, l), lmask, _ = _pad_to_bucket([f, l], lmask,
                                                              self._bucket)
                    tbptt = (self.conf.backpropType == BackpropType.TruncatedBPTT
                             and self.conf.tbpttLength and f.ndim == 3
                             and f.shape[2] > self.conf.tbpttLength)
                    if tele is not None:
                        t_step = _time.perf_counter()
                    try:
                        if tbptt:
                            loss, params, states, opts, prec = \
                                self._fit_tbptt(
                                    params, states, opts, prec, f, l,
                                    lmask, base_key, hm=hm, pm=pm)
                        else:
                            it_used = self._iteration
                            rng = jax.random.fold_in(base_key, it_used)
                            (loss, params, states, opts, health,
                             prec) = self._train_step(
                                params, states, opts, prec, f, l, lmask,
                                rng, it_used)
                            self._iteration += 1
                    except Exception as e:
                        # OOM forensics (ISSUE 14): an allocation
                        # failure inside the step becomes a typed
                        # DeviceOomError naming this seam and the top
                        # HBM claims; everything else re-raises as-is
                        memledger.raise_if_oom(e, site="train.fit",
                                               step=self._iteration)
                        raise
                    if tele is not None:
                        dt_step = _time.perf_counter() - t_step
                        tele.record_step(dt_step, f.shape[0],
                                         exemplar=tspan.trace_id)
                        if mem is not None:
                            # steady state: ONE gauge-set per step
                            mem.touch()
                        if tspan and not tbptt:
                            tracing.emit("train.step", tspan.ctx(),
                                         t_step, t_step + dt_step,
                                         step=it_used)
                        steps_seen += 1
                        if not tbptt:
                            # locals were rebound to the step's
                            # outputs, so shapes match what dispatched
                            costmodel.maybe_attribute(
                                tele, "fit", self._train_step,
                                (params, states, opts, prec, f, l,
                                 lmask, rng, it_used),
                                self, steps_seen, dt_step)
                            # recompile forensics (ISSUE 11): steady
                            # state is one thread-local read — only a
                            # backend compile during this step builds
                            # and diffs the signature
                            compile_ledger.note_step(
                                "fit", self._train_step,
                                (params, states, opts, prec, f, l,
                                 lmask, rng, it_used),
                                policy=policy_label,
                                window=(t_step, t_step + dt_step))
                    # rebind before anything can observe donated buffers —
                    # including the health monitor, whose HALT policy raises
                    # out of fit(): the caller must find live params to
                    # checkpoint/inspect, not the buffers this step donated
                    self._params, self._states, self._opt_states = (
                        params, states, opts)
                    self._prec_state = prec
                    if not tbptt:
                        if pm is not None:
                            # pm BEFORE hm: the skip set must be populated
                            # when hm's SKIP_BATCH accounting asks
                            pm.on_step(it_used, prec)
                        if hm is not None:
                            # one step behind: processes the PREVIOUS step's
                            # (already materialized) stats — no added sync
                            hm.on_step(it_used, health)
                    last_loss = loss
                    if self._profiler_cfg is not None:
                        from deeplearning4j_tpu.utils.profiler import (
                            nan_panic_check)

                        nan_panic_check(
                            self._profiler_cfg, loss, params,
                            context=f" at iteration {self._iteration}")
                    if self._listeners:
                        lv = float(loss)
                        self._score = lv
                        for listener in self._listeners:
                            listener.iterationDone(self, self._iteration,
                                                   self._epoch)
                self._epoch += 1
            if pm is not None:
                pm.flush()   # before hm.flush: same-step skip handshake
            if hm is not None:
                hm.flush()   # drain the one-behind slot (HALT may raise here)
            if last_loss is not None:
                self._score = float(last_loss)
            return self
        finally:
            tspan.__exit__(*_sys.exc_info())
            # deterministic producer shutdown: a fit that raises
            # (HALT, preemption) must not leave a prefetch thread
            # racing the next attempt for the same base iterator
            if _prefetcher is not None:
                _prefetcher.close()

    # -- layerwise unsupervised pretraining (reference:
    # MultiLayerNetwork.pretrain/pretrainLayer over AutoEncoder / VAE
    # layers, SURVEY.md §2.5 "Layer impls"; here the unsupervised loss +
    # updater fuse into one jitted step per layer) ---------------------------
    def pretrainLayer(self, layer_idx: int, data, epochs: int = 1):
        """Unsupervised pretraining of ONE layer: inputs forward through
        layers [0, layer_idx) in inference mode, then the layer's
        pretrain_loss is minimized with the layer's own updater."""
        self._check_init()
        lr = self.layers[layer_idx]
        if not getattr(lr, "HAS_PRETRAIN_LOSS", False):
            raise ValueError(
                f"layer {layer_idx} ({type(lr).__name__}) has no "
                f"unsupervised pretrain loss")
        updater = self._layer_updater(layer_idx)

        # the below-stack is FROZEN during this layer's pretraining, so its
        # forward runs once per batch outside the differentiated step
        def fwd(below, states, f):
            h, _ = self._forward(below, states, f, False, None,
                                 upto=layer_idx)
            return _apply_preprocessor(self.conf.preprocessors[layer_idx], h)

        def step(lp, opt, h, rng, it):
            loss, g = jax.value_and_grad(
                lambda p: lr.pretrain_loss(p, h, rng))(lp)
            g = _normalize_grads(g, lr.gradientNormalization,
                                 lr.gradientNormalizationThreshold or 1.0)
            upd, opt = updater.apply(g, opt, lp, it)
            lp = jax.tree_util.tree_map(lambda p, u: p - u, lp, upd)
            return loss, lp, opt

        fkey = ("pretrain_fwd", layer_idx)
        skey = ("pretrain", layer_idx)
        if skey not in self._infer_fns:
            self._infer_fns[fkey] = jax.jit(fwd)
            self._infer_fns[skey] = jax.jit(step, donate_argnums=(0, 1))
        jfwd, jstep = self._infer_fns[fkey], self._infer_fns[skey]
        base_key = jax.random.key(self.conf.seed + 2 + layer_idx)
        loss = None
        for epoch_i in range(epochs):
            batches, data = _prepare_batches(data, epoch_i, epochs)
            for ds in batches:
                feats, _, _, _ = _split_dataset_full(ds)
                f = _host_array(feats[0])
                # layer 0 included: fwd still applies the dtype cast and
                # the layer's input preprocessor
                h = jfwd(self._params[:layer_idx], self._states, f)
                rng = jax.random.fold_in(base_key, self._iteration)
                loss, lp, opt = jstep(
                    self._params[layer_idx], self._opt_states[layer_idx],
                    h, rng, self._iteration)
                # rebind immediately: the step DONATED the old buffers
                self._params[layer_idx] = lp
                self._opt_states[layer_idx] = opt
                self._iteration += 1
        if loss is not None:
            self._score = float(loss)
        return self

    def pretrain(self, data, epochs: int = 1):
        """Pretrain every pretrainable layer in order (reference:
        MultiLayerNetwork.pretrain(DataSetIterator))."""
        # materialize one-shot iterables ONCE so the second pretrainable
        # layer doesn't see an exhausted generator
        if not hasattr(data, "reset") and not isinstance(
                data, (list, tuple)):
            data = list(_as_batches(data))
        for i, lr in enumerate(self.layers):
            if getattr(lr, "HAS_PRETRAIN_LOSS", False):
                self.pretrainLayer(i, data, epochs)
        return self

    # -- TBPTT (reference: MultiLayerNetwork truncated BPTT, SURVEY.md §2.5:
    # tBPTTLength splits each minibatch sequence into segments; hidden state
    # carries ACROSS segments (no gradient flow — states enter the next
    # compiled step as inputs), and resets at minibatch boundaries) --------
    def _recurrent_indices(self, forbid_bidirectional=False):
        from deeplearning4j_tpu.nn.conf.layers import Bidirectional

        out = []
        for i, lr in enumerate(self.layers):
            if isinstance(lr, Bidirectional):
                if forbid_bidirectional:
                    # the backward direction needs the FULL sequence; DL4J
                    # likewise rejects rnnTimeStep/TBPTT on bidirectional
                    raise ValueError(
                        f"layer {i} is Bidirectional: streaming rnnTimeStep"
                        f"/TBPTT cannot carry state through a layer that "
                        f"consumes the whole sequence")
                continue
            if getattr(lr, "IS_RECURRENT", False) or getattr(
                    getattr(lr, "rnn", None), "IS_RECURRENT", False):
                out.append(i)
        return out

    def _seed_rnn_states(self, states, batch_size):
        dtype = self.conf.dtype
        out = list(states)
        for i in self._recurrent_indices():
            lr = self.layers[i]
            target = lr.rnn if hasattr(lr, "rnn") and getattr(
                lr.rnn, "IS_RECURRENT", False) and not getattr(
                lr, "IS_RECURRENT", False) else lr
            out[i] = target.streaming_state(batch_size, dtype)
        return out

    def _strip_rnn_states(self, states):
        out = list(states)
        for i in self._recurrent_indices():
            out[i] = {}
        return out

    def _fit_tbptt(self, params, states, opts, prec, f, l, lmask, base_key,
                   hm=None, pm=None):
        L = self.conf.tbpttLength
        T = f.shape[2]
        self._recurrent_indices(forbid_bidirectional=True)
        states = self._seed_rnn_states(states, f.shape[0])
        loss = None
        for t0 in range(0, T, L):
            fc = f[:, :, t0:t0 + L]
            lc = l[:, :, t0:t0 + L] if l.ndim == 3 else l
            mc = lmask[:, t0:t0 + L] if lmask.ndim == 2 else lmask
            if fc.shape[2] < L:
                # zero-pad the tail segment to the fixed tbptt shape and
                # mask the padded timesteps out of the loss
                pad = L - fc.shape[2]
                fc = np.concatenate(
                    [fc, np.zeros(fc.shape[:2] + (pad,), fc.dtype)], axis=2)
                if lc.ndim == 3:
                    lc = np.concatenate(
                        [lc, np.zeros(lc.shape[:2] + (pad,), lc.dtype)],
                        axis=2)
                if mc.ndim == 2:
                    mc = np.concatenate(
                        [mc, np.zeros((mc.shape[0], pad), mc.dtype)], axis=1)
            it_used = self._iteration
            rng = jax.random.fold_in(base_key, it_used)
            loss, params, states, opts, health, prec = self._train_step(
                params, states, opts, prec, fc, lc, mc, rng, it_used)
            self._iteration += 1
            if hm is not None or pm is not None:
                # rebind first: on_step may raise (HALT) and the caller
                # must not be left holding this step's donated buffers
                self._params, self._states, self._opt_states = (
                    params, self._strip_rnn_states(states), opts)
                self._prec_state = prec
                if pm is not None:
                    pm.on_step(it_used, prec)
                if hm is not None:
                    hm.on_step(it_used, health)
        return loss, params, self._strip_rnn_states(states), opts, prec

    # -- streaming inference (reference: rnnTimeStep / rnnClearPreviousState,
    # SURVEY.md §2.5 TBPTT row) ---------------------------------------------
    def rnnTimeStep(self, x):
        """Streaming inference with carried hidden state: x is [N, C]
        (one timestep) or [N, C, T] (a chunk). Successive calls continue
        the sequence; rnnClearPreviousState() resets."""
        self._check_init()
        x = _unwrap(x)
        single = x.ndim == 2
        if single:
            x = x[:, :, None]
        n = x.shape[0]
        rec = set(self._recurrent_indices(forbid_bidirectional=True))
        if self._stream_states is None or self._stream_batch != n:
            seeded = self._seed_rnn_states(self._states, n)
            self._stream_states = {i: seeded[i] for i in rec}
            self._stream_batch = n
        # only the recurrent carry is cached; BN running stats etc. come
        # fresh from self._states so an interleaved fit() (which rebinds
        # self._states after donating the old buffers) can't leave stale
        # or deleted arrays behind
        states = [self._stream_states[i] if i in rec else s
                  for i, s in enumerate(self._states)]
        key = "stream"
        if key not in self._infer_fns:
            def fn(params, states, x):
                return self._forward(params, states, x, False, None)

            self._infer_fns[key] = jax.jit(fn)
        y, new_states = self._infer_fns[key](self._params, states, x)
        self._stream_states = {i: new_states[i] for i in rec}
        y = INDArray(y[:, :, 0]) if single and y.ndim == 3 else INDArray(y)
        return y

    def rnnClearPreviousState(self):
        self._stream_states = None
        self._stream_batch = None

    def rnnGetPreviousState(self, layer_idx: int) -> dict:
        if self._stream_states is None:
            return {}
        return {k: INDArray(v)
                for k, v in self._stream_states.get(layer_idx, {}).items()}

    def rnnSetPreviousState(self, layer_idx: int, state: dict):
        """Install carried state (e.g. restoring a saved streaming session).
        Works after rnnClearPreviousState: a fresh session is seeded from
        the given state's batch size."""
        vals = {k: _unwrap(v) for k, v in state.items()}
        if self._stream_states is None:
            if not vals:
                raise ValueError("cannot infer batch size from empty state")
            n = next(iter(vals.values())).shape[0]
            rec = set(self._recurrent_indices())
            seeded = self._seed_rnn_states(self._states, n)
            self._stream_states = {i: seeded[i] for i in rec}
            self._stream_batch = n
        self._stream_states[layer_idx] = vals

    # -- inference -----------------------------------------------------------
    def _infer_fn(self, training=False):
        key = ("out", training)
        if key not in self._infer_fns:
            from deeplearning4j_tpu.precision import cast_floating

            pol = self._precision_policy()

            def fn(params, states, x):
                # mixed policy: inference ALSO runs in the compute dtype
                # (the MXU payoff applies to serving too) and returns
                # output_dtype at the boundary; identity without a policy
                if pol.is_mixed:
                    params = cast_floating(params, pol.compute_jnp)
                y, _ = self._forward(params, states, x, training, None)
                return y.astype(pol.output_jnp) \
                    if y.dtype != pol.output_jnp and \
                    jnp.issubdtype(y.dtype, jnp.floating) else y

            self._infer_fns[key] = jax.jit(fn)
        return self._infer_fns[key]

    def output(self, x, train: bool = False) -> INDArray:
        self._check_init()
        y = self._infer_fn(train)(self._params, self._states, _unwrap(x))
        return INDArray(y)

    def feedForward(self, x, train: bool = False) -> list:
        """All layer activations (reference returns input + each layer's
        activation)."""
        self._check_init()
        x = _unwrap(x)
        acts = [INDArray(x)]
        states = self._states
        for i, lr in enumerate(self.layers):
            x = _apply_preprocessor(self.conf.preprocessors[i], x)
            x, _ = lr.apply(self._params[i], states[i], x, train, None)
            acts.append(INDArray(x))
        return acts

    # -- scoring / eval ------------------------------------------------------
    def score(self, dataset=None) -> float:
        self._check_init()
        if dataset is None:
            if self._score is None:
                raise ValueError("no score yet: call fit() or score(dataset)")
            return self._score
        feats, labels, _, lmasks = _split_dataset_full(dataset)
        lmask = None if lmasks[0] is None else _unwrap(lmasks[0])
        loss, _ = self._loss_from(self._params, self._states,
                                  _unwrap(feats[0]), _unwrap(labels[0]),
                                  False, None, mask=lmask)
        return float(loss)

    def _eval_outputs(self, iterator):
        """Yield (labels, predictions, mask) per batch with the ragged
        final batch padded UP to the running batch-size bucket (the
        serving-side `pad_rows`), so an eval pass compiles ONE inference
        executable instead of one per distinct tail size. Padding rows
        are sliced back off before scoring — masks stay untouched and
        results are bit-identical to unpadded inference (row-wise
        networks)."""
        from deeplearning4j_tpu.serving.buckets import pad_rows

        bucket = None
        for ds in _as_batches(iterator):
            feats, labels, _, lmasks = _split_dataset_full(ds)
            f = _host_array(feats[0])
            n = f.shape[0]
            if bucket is None or n > bucket:
                bucket = n
            out = self.output(pad_rows(f, bucket))
            yield labels[0], out.toNumpy()[:n], lmasks[0]

    def evaluate(self, iterator, numClasses=None) -> Evaluation:
        self._check_init()
        ev = Evaluation(numClasses)
        for labels, out, mask in self._eval_outputs(iterator):
            ev.eval(labels, out, mask=mask)
        return ev

    def evaluateRegression(self, iterator) -> RegressionEvaluation:
        self._check_init()
        ev = RegressionEvaluation()
        for labels, out, mask in self._eval_outputs(iterator):
            ev.eval(labels, out, mask=mask)
        return ev

    # -- params --------------------------------------------------------------
    def params(self) -> INDArray:
        """Flat parameter vector in layer order (reference:
        MultiLayerNetwork.params() flat view)."""
        self._check_init()
        leaves = []
        for p in self._params:
            for k in sorted(p):
                leaves.append(jnp.ravel(p[k]))
        if not leaves:
            return INDArray(jnp.zeros((0,)))
        return INDArray(jnp.concatenate(leaves))

    def setParams(self, flat):
        self._check_init()
        flat = _unwrap(flat).reshape(-1)
        off = 0
        for p in self._params:
            for k in sorted(p):
                n = int(np.prod(p[k].shape)) if p[k].shape else 1
                p[k] = flat[off: off + n].reshape(p[k].shape).astype(
                    p[k].dtype)
                off += n
        self._train_step = None
        self._multi_step = None

    def numParams(self) -> int:
        return sum(int(np.prod(v.shape)) for p in self._params
                   for v in p.values())

    def getParam(self, layer_idx: int, name: str) -> INDArray:
        return INDArray(self._params[layer_idx][name])

    def setParam(self, layer_idx: int, name: str, value):
        if isinstance(value, dict):  # nested group (Bidirectional fwd/bwd)
            self._params[layer_idx][name] = {
                k: _unwrap(v) for k, v in value.items()}
        else:
            self._params[layer_idx][name] = _unwrap(value)

    def paramTable(self) -> dict:
        return {f"{i}_{k}": INDArray(v)
                for i, p in enumerate(self._params) for k, v in p.items()}

    def gradients(self, features, labels) -> list[dict]:
        """Per-layer analytic gradients (for the gradient-check harness,
        SURVEY.md §4)."""
        self._check_init()
        f, l = _unwrap(features), _unwrap(labels)

        def loss_fn(p):
            loss, _ = self._loss_from(p, self._states, f, l, False, None)
            return loss

        return jax.grad(loss_fn)(self._params)

    def computeGradientAndScore(self, features, labels):
        f, l = _unwrap(features), _unwrap(labels)

        def loss_fn(p):
            loss, _ = self._loss_from(p, self._states, f, l, False, None)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(self._params)
        self._score = float(loss)
        return grads, self._score

    # -- profiler / debug (reference: OpProfiler NAN_PANIC, SURVEY.md §2.3)
    def setProfilerConfig(self, cfg):
        """ProfilerConfig with checkForNaN/checkForInf enables a per-step
        finite check that raises naming the offending parameter."""
        self._profiler_cfg = cfg
        return self

    # -- listeners / misc ----------------------------------------------------
    def setListeners(self, *listeners):
        self._listeners = list(listeners)
        return self

    def addListeners(self, *listeners):
        self._listeners.extend(listeners)
        return self

    def getListeners(self):
        return list(self._listeners)

    def getIterationCount(self):
        return self._iteration

    def getEpochCount(self):
        return self._epoch

    def clone(self) -> "MultiLayerNetwork":
        other = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(self.conf.to_json()))
        if self._initialized:
            other.init()
            # real copies, not aliases: the source's next fit() DONATES its
            # buffers, which would invalidate shared references
            copy = lambda x: jnp.array(x, copy=True)  # noqa: E731
            other._params = jax.tree_util.tree_map(copy, self._params)
            other._states = jax.tree_util.tree_map(copy, self._states)
            other._opt_states = jax.tree_util.tree_map(copy, self._opt_states)
            other._prec_state = jax.tree_util.tree_map(copy,
                                                       self._prec_state)
        return other

    def summary(self) -> str:
        lines = [f"{'idx':<4}{'layer':<28}{'nParams':<10}{'shape'}"]
        for i, (lr, p) in enumerate(zip(self.layers, self._params)):
            n = sum(int(np.prod(v.shape)) for v in p.values())
            shapes = {k: tuple(v.shape) for k, v in p.items()}
            lines.append(f"{i:<4}{type(lr).__name__:<28}{n:<10}{shapes}")
        lines.append(f"Total params: {self.numParams()}")
        return "\n".join(lines)
