from deeplearning4j_tpu.nn.conf.configuration import (  # noqa: F401
    MultiLayerConfiguration, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf import variational  # noqa: F401  (registers)
from deeplearning4j_tpu.nn.conf import objdetect  # noqa: F401  (registers)
from deeplearning4j_tpu.nn.conf import layers_extra  # noqa: F401 (registers)
from deeplearning4j_tpu.nn.conf import attention  # noqa: F401  (registers)
from deeplearning4j_tpu.nn.conf import capsnet  # noqa: F401  (registers)
