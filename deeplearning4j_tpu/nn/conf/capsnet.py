"""CapsNet layers (dynamic routing between capsules).

Reference capability: org.deeplearning4j.nn.conf.layers.{PrimaryCapsules,
CapsuleLayer, CapsuleStrengthLayer} (added to DL4J in 1.0.0-beta4;
SURVEY.md §2.5 layer impls). The reference runs the routing iterations
as per-op dispatch; here the whole routing loop is a lax.fori_loop
inside the net's single compiled step, and the per-capsule prediction
tensor u_hat is ONE batched einsum on the MXU.

Tensor convention follows the reference's mapping of capsule activations
onto the recurrent input type: [N, capsules, capsuleDimensions] =
InputType.recurrent(capsules, capsuleDimensions).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalType, InputType)
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayer, _pair, _register)
from deeplearning4j_tpu.autodiff.ops import OPS
from deeplearning4j_tpu.nn.weights import init_weight


def _squash(s, axis=-1, eps=1e-7):
    """v = |s|^2/(1+|s|^2) * s/|s| (the capsule non-linearity)."""
    sq = jnp.sum(jnp.square(s), axis=axis, keepdims=True)
    return (sq / (1.0 + sq)) * s / jnp.sqrt(sq + eps)


@_register
class PrimaryCapsules(BaseLayer):
    """Conv feature maps -> primary capsule vectors (reference:
    conf.layers.PrimaryCapsules). A conv with channels*capsuleDimensions
    filters, reshaped to [N, caps, capsDim] and squashed."""

    def __init__(self, nIn=None, capsuleDimensions=8, channels=32,
                 kernelSize=(9, 9), stride=(2, 2), hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.capsuleDimensions = int(capsuleDimensions)
        self.channels = int(channels)
        self.kernelSize = _pair(kernelSize)
        self.stride = _pair(stride)
        self.hasBias = hasBias

    def infer(self, input_type):
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(
                f"PrimaryCapsules needs convolutional input, "
                f"got {input_type}")
        self.nIn = self.nIn or input_type.channels
        kh, kw = self.kernelSize
        sh, sw = self.stride
        oh = (input_type.height - kh) // sh + 1
        ow = (input_type.width - kw) // sw + 1
        caps = self.channels * oh * ow
        return InputType.recurrent(caps, self.capsuleDimensions)

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = self.kernelSize
        n_out = self.channels * self.capsuleDimensions
        fan_in = self.nIn * kh * kw
        k1, _ = jax.random.split(key)
        p = {"W": init_weight(self.weightInit, k1,
                              (n_out, self.nIn, kh, kw), fan_in,
                              n_out * kh * kw, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((n_out,), self.biasInit, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        y = OPS["conv2d"](x, params["W"], params.get("b"),
                          strides=self.stride, padding=(0, 0))
        n = y.shape[0]
        # [N, channels*capsDim, H, W] -> [N, channels*H*W, capsDim]
        y = y.reshape(n, self.channels, self.capsuleDimensions, -1)
        y = jnp.transpose(y, (0, 1, 3, 2)).reshape(
            n, -1, self.capsuleDimensions)
        return _squash(y), state


@_register
class CapsuleLayer(BaseLayer):
    """Fully-connected capsules with dynamic routing (reference:
    conf.layers.CapsuleLayer: capsules, capsuleDimensions, routings)."""

    def __init__(self, nIn=None, inputCapsuleDimensions=None, capsules=10,
                 capsuleDimensions=16, routings=3, **kw):
        super().__init__(**kw)
        self.nIn = nIn                       # input capsule COUNT
        self.inputCapsuleDimensions = inputCapsuleDimensions
        self.capsules = int(capsules)
        self.capsuleDimensions = int(capsuleDimensions)
        self.routings = int(routings)

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        t = getattr(input_type, "timeSeriesLength", None)
        self.inputCapsuleDimensions = self.inputCapsuleDimensions or t
        if self.inputCapsuleDimensions is None:
            raise ValueError(
                "CapsuleLayer needs inputCapsuleDimensions (the input "
                "type's capsule dimension was undeclared)")
        return InputType.recurrent(self.capsules, self.capsuleDimensions)

    def init_params(self, key, dtype=jnp.float32):
        i, d_in = self.nIn, self.inputCapsuleDimensions
        j, d_out = self.capsules, self.capsuleDimensions
        k1, _ = jax.random.split(key)
        return {"W": init_weight(self.weightInit, k1, (i, j, d_out, d_in),
                                 d_in, d_out, dtype)}

    def apply(self, params, state, x, training, rng):
        # x: [N, inCaps, inDim]; u_hat[n,i,j,:] = W[i,j] @ x[n,i]
        u_hat = jnp.einsum("ijdk,nik->nijd", params["W"], x)
        n, i, j, _ = u_hat.shape
        b0 = jnp.zeros((n, i, j), u_hat.dtype)

        # fully differentiable routing (routings is small, so the
        # unrolled-through-grad cost is negligible and analytic gradients
        # match numeric ones exactly)
        def routing_iter(it, b):
            c = jax.nn.softmax(b, axis=2)[..., None]      # over out caps
            s = jnp.sum(c * u_hat, axis=1)                # [N, j, d]
            v = _squash(s)
            return b + jnp.einsum("nijd,njd->nij", u_hat, v)

        b = lax.fori_loop(0, self.routings - 1, routing_iter, b0)
        c = jax.nn.softmax(b, axis=2)[..., None]
        v = _squash(jnp.sum(c * u_hat, axis=1))
        return v, state


@_register
class CapsuleStrengthLayer(BaseLayer):
    """[N, caps, capsDim] -> per-capsule L2 norms [N, caps] (reference:
    conf.layers.CapsuleStrengthLayer — class probabilities are capsule
    lengths)."""

    def infer(self, input_type):
        return InputType.feedForward(input_type.size)

    def apply(self, params, state, x, training, rng):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=-1) + 1e-9), state
