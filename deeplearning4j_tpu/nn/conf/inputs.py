"""Input type declarations for automatic shape inference.

Reference capability: org.deeplearning4j.nn.conf.inputs.InputType
(SURVEY.md §2.5 "Config DSL") — setInputType on the config builder drives
nIn inference and automatic preprocessor insertion between layer kinds
(conv <-> dense <-> recurrent).
"""

from __future__ import annotations

from dataclasses import dataclass


class InputType:
    @staticmethod
    def feedForward(size):
        return FeedForwardType(int(size))

    @staticmethod
    def recurrent(size, timeSeriesLength=None):
        return RecurrentType(int(size), timeSeriesLength)

    @staticmethod
    def convolutional(height, width, channels):
        return ConvolutionalType(int(height), int(width), int(channels))

    @staticmethod
    def convolutionalFlat(height, width, channels):
        return ConvolutionalFlatType(int(height), int(width), int(channels))

    @staticmethod
    def convolutional3D(depth, height, width, channels):
        return Convolutional3DType(int(depth), int(height), int(width),
                                   int(channels))

    @staticmethod
    def from_json(d):
        kinds = {
            "feedforward": lambda: FeedForwardType(d["size"]),
            "recurrent": lambda: RecurrentType(
                d["size"], d.get("timeSeriesLength")),
            "convolutional": lambda: ConvolutionalType(
                d["height"], d["width"], d["channels"]),
            "convolutionalflat": lambda: ConvolutionalFlatType(
                d["height"], d["width"], d["channels"]),
            "convolutional3d": lambda: Convolutional3DType(
                d["depth"], d["height"], d["width"], d["channels"]),
        }
        return kinds[d["kind"]]()


@dataclass
class FeedForwardType:
    size: int
    kind: str = "feedforward"

    def arrayElementsPerExample(self):
        return self.size

    def batch_shape(self, n=1):
        return (n, self.size)

    def to_json(self):
        return {"kind": self.kind, "size": self.size}


@dataclass
class RecurrentType:
    size: int
    timeSeriesLength: int | None = None
    kind: str = "recurrent"

    def arrayElementsPerExample(self):
        return self.size * (self.timeSeriesLength or 1)

    def batch_shape(self, n=1):
        # DL4J time-series layout: [N, C, T]
        return (n, self.size, self.timeSeriesLength or 1)

    def to_json(self):
        return {"kind": self.kind, "size": self.size,
                "timeSeriesLength": self.timeSeriesLength}


@dataclass
class ConvolutionalType:
    height: int
    width: int
    channels: int
    kind: str = "convolutional"

    def arrayElementsPerExample(self):
        return self.height * self.width * self.channels

    def batch_shape(self, n=1):
        return (n, self.channels, self.height, self.width)

    def to_json(self):
        return {"kind": self.kind, "height": self.height,
                "width": self.width, "channels": self.channels}


@dataclass
class Convolutional3DType:
    """Volumetric input, NCDHW layout (reference: InputType.convolutional3D
    with DataFormat.NCDHW)."""

    depth: int
    height: int
    width: int
    channels: int
    kind: str = "convolutional3d"

    def arrayElementsPerExample(self):
        return self.depth * self.height * self.width * self.channels

    def batch_shape(self, n=1):
        return (n, self.channels, self.depth, self.height, self.width)

    def to_json(self):
        return {"kind": self.kind, "depth": self.depth,
                "height": self.height, "width": self.width,
                "channels": self.channels}


@dataclass
class ConvolutionalFlatType(ConvolutionalType):
    """MNIST-style flat input that the first conv layer reshapes to NCHW."""

    kind: str = "convolutionalflat"

    def batch_shape(self, n=1):
        return (n, self.height * self.width * self.channels)
