"""NeuralNetConfiguration builder DSL and MultiLayerConfiguration.

Reference capability: org.deeplearning4j.nn.conf.NeuralNetConfiguration
(+.Builder and .ListBuilder) and MultiLayerConfiguration (SURVEY.md §2.5
"Config DSL"): global defaults (seed/updater/weightInit/activation/l1/l2)
cloned into per-layer configs, automatic nIn inference + preprocessor
insertion driven by setInputType, and canonical-JSON round-trip
(MultiLayerConfiguration.fromJson) so checkpoints are portable.
"""

from __future__ import annotations

import json

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalFlatType, ConvolutionalType, InputType, RecurrentType,
)
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayer, ConvolutionLayer, OUTPUT_LAYER_TYPES, SubsamplingLayer,
)
from deeplearning4j_tpu.optimize.updaters import (
    IUpdater, Sgd, updater_from_config)

# preprocessor kinds recorded per layer index (the reference's
# InputPreProcessor impls: CnnToFeedForwardPreProcessor etc. — here pure
# reshapes that XLA folds away)
_PP_FLATTEN = "cnn_to_ff"
_PP_TO_CNN = "ff_to_cnn"


def _apply_preprocessor(pp, x):
    if pp is None:
        return x
    kind, shape = pp
    if kind == _PP_FLATTEN:
        return x.reshape(x.shape[0], -1)
    if kind == _PP_TO_CNN:
        return x.reshape((x.shape[0],) + tuple(shape))
    raise ValueError(f"unknown preprocessor {kind}")


class BackpropType:
    """Reference: org.deeplearning4j.nn.conf.BackpropType."""

    Standard = "Standard"
    TruncatedBPTT = "TruncatedBPTT"


class MultiLayerConfiguration:
    def __init__(self, layers, defaults=None, inputType=None, seed=12345,
                 dataType="float32", backpropType=BackpropType.Standard,
                 tbpttLength=None, precision=None):
        self.layers: list[BaseLayer] = layers
        self.defaults = defaults or {}
        self.inputType = inputType
        self.seed = seed
        self.dataType = dataType
        self.backpropType = backpropType
        self.tbpttLength = tbpttLength
        # precision policy name / Policy / None (ISSUE 4): resolved
        # lazily by precision_policy so a bare dataType keeps behaving
        # exactly as before
        self.precision = precision
        self.preprocessors: list = [None] * len(layers)
        self.layer_input_types: list = [None] * len(layers)
        self._finalize()

    def _finalize(self):
        """Clone defaults into layers and run shape inference front-to-back
        (the reference does this in MultiLayerConfiguration.Builder.build)."""
        if not self.layers:
            return
        for lr in self.layers:
            lr.apply_defaults(self.defaults)
        it = self.inputType
        if it is None:
            # no declared input type: if the first layer states its nIn,
            # chain inference from there (common DL4J idiom: nIn on layer 0
            # only, later layers inferred). Only dense-ish and recurrent
            # first layers imply an input kind; conv needs explicit H/W.
            from deeplearning4j_tpu.nn.conf.layers import (
                Convolution1DLayer, DenseLayer, EmbeddingSequenceLayer,
                LSTM, SimpleRnn)
            from deeplearning4j_tpu.nn.conf.variational import (
                AutoEncoder, VariationalAutoencoder)

            from deeplearning4j_tpu.nn.conf.layers_extra import (
                FrozenLayer, MaskZeroLayer)

            first = self.layers[0]
            # unwrap wrapper layers: the inner layer declares the kind/nIn
            while isinstance(first, (FrozenLayer, MaskZeroLayer)):
                first = (first.layer if isinstance(first, FrozenLayer)
                         else first.underlying)
            n_in = getattr(first, "nIn", None)
            if n_in is None:
                return
            if isinstance(first, (LSTM, SimpleRnn, Convolution1DLayer,
                                  EmbeddingSequenceLayer)):
                it = InputType.recurrent(n_in)
            elif isinstance(first, (DenseLayer, AutoEncoder,
                                    VariationalAutoencoder)):
                # includes output layers (DenseLayer subclasses)
                it = InputType.feedForward(n_in)
            else:
                return
        from deeplearning4j_tpu.nn.conf.capsnet import PrimaryCapsules

        for i, lr in enumerate(self.layers):
            if isinstance(it, ConvolutionalFlatType) and isinstance(
                    lr, (ConvolutionLayer, PrimaryCapsules,
                         SubsamplingLayer)):
                self.preprocessors[i] = (
                    _PP_TO_CNN, (it.channels, it.height, it.width))
                it = InputType.convolutional(it.height, it.width, it.channels)
            elif isinstance(it, ConvolutionalType) and not isinstance(
                    it, ConvolutionalFlatType) and not isinstance(
                    lr, (ConvolutionLayer, SubsamplingLayer)) \
                    and not _wants_conv(lr):
                self.preprocessors[i] = (_PP_FLATTEN, None)
                it = InputType.feedForward(it.arrayElementsPerExample())
            self.layer_input_types[i] = it
            it = lr.infer(it)

    # -- serde ---------------------------------------------------------------
    def to_json(self):
        return json.dumps({
            "layers": [lr.to_json() for lr in self.layers],
            "defaults": _json_defaults(self.defaults),
            "inputType": self.inputType.to_json() if self.inputType else None,
            "seed": self.seed,
            "dataType": self.dataType,
            "backpropType": self.backpropType,
            "tbpttLength": self.tbpttLength,
            "precision": (self.precision.to_json()
                          if hasattr(self.precision, "to_json")
                          else self.precision),
        }, indent=1)

    toJson = to_json

    @staticmethod
    def from_json(s):
        d = json.loads(s) if isinstance(s, str) else s
        defaults = dict(d.get("defaults") or {})
        if isinstance(defaults.get("updater"), dict):
            defaults["updater"] = updater_from_config(defaults["updater"])
        layers = [BaseLayer.from_json(ld) for ld in d["layers"]]
        it = InputType.from_json(d["inputType"]) if d.get("inputType") else None
        return MultiLayerConfiguration(
            layers, defaults, it, d.get("seed", 12345),
            d.get("dataType", "float32"),
            d.get("backpropType", BackpropType.Standard),
            d.get("tbpttLength"), d.get("precision"))

    fromJson = from_json

    @property
    def dtype(self):
        return jnp.dtype(self.dataType)

    @property
    def precision_policy(self):
        """The effective precision.Policy (uniform in dataType when no
        policy is configured)."""
        from deeplearning4j_tpu.precision import resolve_policy

        return resolve_policy(self.precision, self.dataType)


def _wants_conv(layer):
    """Layers that consume CNN activations directly — no flatten before
    them. GlobalPooling reduces the spatial axes itself (DL4J semantics:
    [N,C,H,W] -> [N,C]); Dropout/Activation are shape-preserving."""
    from deeplearning4j_tpu.nn.conf.layers import (
        ActivationLayer, BatchNormalization, Deconvolution2D, DepthToSpace,
        DropoutLayer, GlobalPoolingLayer, LocalResponseNormalization,
        SpaceToDepth, Upsampling2D, ZeroPaddingLayer)
    from deeplearning4j_tpu.nn.conf.capsnet import PrimaryCapsules
    from deeplearning4j_tpu.nn.conf.layers_extra import (
        Cropping2D, FrozenLayer, LocallyConnected2D, PReLULayer)
    from deeplearning4j_tpu.nn.conf.objdetect import Yolo2OutputLayer

    if isinstance(layer, FrozenLayer):
        return _wants_conv(layer.layer)
    return isinstance(layer, (ActivationLayer, BatchNormalization,
                              Cropping2D, Deconvolution2D, DepthToSpace,
                              DropoutLayer, GlobalPoolingLayer,
                              LocalResponseNormalization,
                              LocallyConnected2D, PReLULayer,
                              PrimaryCapsules, SpaceToDepth,
                              Upsampling2D, ZeroPaddingLayer,
                              Yolo2OutputLayer))


def _json_defaults(defaults):
    out = {}
    for k, v in defaults.items():
        out[k] = v.to_json() if hasattr(v, "to_json") else v
    return out


class ListBuilder:
    def __init__(self, defaults, seed, dataType, precision=None):
        self._defaults = defaults
        self._seed = seed
        self._dataType = dataType
        self._precision = precision
        self._layers: list = []
        self._input_type = None
        self._backprop_type = BackpropType.Standard
        self._tbptt_length = None

    def layer(self, idx_or_layer, layer=None):
        if layer is None:
            self._layers.append(idx_or_layer)
        else:
            idx = int(idx_or_layer)
            while len(self._layers) <= idx:
                self._layers.append(None)
            self._layers[idx] = layer
        return self

    def setInputType(self, input_type):
        self._input_type = input_type
        return self

    def inputType(self, input_type):
        return self.setInputType(input_type)

    def backpropType(self, bt):
        """Reference: ListBuilder.backpropType(BackpropType.TruncatedBPTT)."""
        self._backprop_type = bt
        return self

    def tBPTTLength(self, n):
        self._backprop_type = BackpropType.TruncatedBPTT
        self._tbptt_length = int(n)
        return self

    # the reference splits fwd/bwd lengths; equal lengths is the common case
    def tBPTTForwardLength(self, n):
        return self.tBPTTLength(n)

    def tBPTTBackwardLength(self, n):
        self._tbptt_length = min(self._tbptt_length or int(n), int(n))
        return self

    def build(self) -> MultiLayerConfiguration:
        if any(lr is None for lr in self._layers):
            raise ValueError("layer list has gaps")
        return MultiLayerConfiguration(self._layers, dict(self._defaults),
                                       self._input_type, self._seed,
                                       self._dataType,
                                       self._backprop_type,
                                       self._tbptt_length,
                                       self._precision)


class NeuralNetConfiguration:
    """Entry point: NeuralNetConfiguration.Builder()...list()...build()."""

    class Builder:
        def __init__(self):
            self._defaults = {"updater": Sgd(1e-2)}
            self._seed = 12345
            self._dataType = "float32"
            self._precision = None

        def seed(self, s):
            self._seed = int(s)
            return self

        def updater(self, u: IUpdater):
            self._defaults["updater"] = u
            return self

        def weightInit(self, wi):
            self._defaults["weightInit"] = wi
            return self

        def activation(self, a):
            self._defaults["activation"] = a
            return self

        def l1(self, v):
            self._defaults["l1"] = float(v)
            return self

        def l2(self, v):
            self._defaults["l2"] = float(v)
            return self

        def dropOut(self, p):
            self._defaults["dropOut"] = float(p)
            return self

        def biasInit(self, v):
            self._defaults["biasInit"] = float(v)
            return self

        def dataType(self, dt):
            self._dataType = str(jnp.dtype(dt))
            return self

        def precision(self, policy):
            """Precision policy (ISSUE 4): a name ("bf16_mixed", "bf16",
            "fp16_mixed", "float32") or a precision.Policy. "bf16_mixed"
            = fp32 master weights + bf16 compute + fp32 loss with
            dynamic loss scaling compiled into the train step."""
            from deeplearning4j_tpu.precision import resolve_policy

            # validate eagerly so a typo fails at build, not first fit
            resolve_policy(policy, self._dataType)
            self._precision = policy
            return self

        def gradientNormalization(self, gn, threshold=1.0):
            self._defaults["gradientNormalization"] = gn
            self._defaults["gradientNormalizationThreshold"] = threshold
            return self

        def miniBatch(self, flag=True):
            return self  # minibatch scaling is implicit in mean losses

        def trainingWorkspaceMode(self, *_):
            return self  # workspaces are an XLA concern here (no-op facade)

        def inferenceWorkspaceMode(self, *_):
            return self

        def cudnnAlgoMode(self, *_):
            return self  # no cuDNN on the TPU path

        def list(self):
            return ListBuilder(self._defaults, self._seed, self._dataType,
                               self._precision)

        def graphBuilder(self):
            from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder

            return GraphBuilder(self._defaults, self._seed, self._dataType,
                                self._precision)
