"""ComputationGraphConfiguration: DAG of layers and vertices.

Reference capability: org.deeplearning4j.nn.conf.ComputationGraphConfiguration
(+.GraphBuilder) and graph vertices (MergeVertex, ElementWiseVertex, ...)
(SURVEY.md §2.5, call stack §3.2). The reference precomputes a topological
order and walks GraphVertex.doForward/doBackward objects at runtime; here
the whole DAG lowers to one pure function executed inside a single jitted
step, so vertices are just emitter functions.
"""

from __future__ import annotations

import json

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalType, FeedForwardType, InputType, RecurrentType)
from deeplearning4j_tpu.nn.conf.layers import BaseLayer

VERTEX_REGISTRY: dict = {}


def _register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


class GraphVertex:
    """Parameter-less combination vertex."""

    def infer(self, *input_types):
        return input_types[0]

    def apply(self, *xs):
        raise NotImplementedError

    def to_json(self):
        d = {"@class": type(self).__name__}
        d.update({k: (list(v) if isinstance(v, tuple) else v)
                  for k, v in self.__dict__.items()})
        return d

    @staticmethod
    def from_json(d):
        d = dict(d)
        return VERTEX_REGISTRY[d.pop("@class")](**d)


@_register_vertex
class MergeVertex(GraphVertex):
    """Concat along the feature/channel axis (axis 1 for >=2D, matching the
    reference's MergeVertex default)."""

    def infer(self, *input_types):
        t0 = input_types[0]
        total = sum(getattr(t, "channels", getattr(t, "size", 0))
                    for t in input_types)
        if isinstance(t0, ConvolutionalType):
            return InputType.convolutional(t0.height, t0.width, total)
        if isinstance(t0, RecurrentType):
            return InputType.recurrent(total, t0.timeSeriesLength)
        return InputType.feedForward(total)

    def apply(self, *xs):
        return jnp.concatenate(xs, axis=1)


@_register_vertex
class ElementWiseVertex(GraphVertex):
    Add, Subtract, Product, Average, Max = ("Add", "Subtract", "Product",
                                            "Average", "Max")

    def __init__(self, op="Add"):
        self.op = op

    def apply(self, *xs):
        if self.op == "Add":
            y = xs[0]
            for x in xs[1:]:
                y = y + x
            return y
        if self.op == "Subtract":
            return xs[0] - xs[1]
        if self.op == "Product":
            y = xs[0]
            for x in xs[1:]:
                y = y * x
            return y
        if self.op == "Average":
            return sum(xs) / len(xs)
        if self.op == "Max":
            y = xs[0]
            for x in xs[1:]:
                y = jnp.maximum(y, x)
            return y
        raise ValueError(self.op)


@_register_vertex
class ScaleVertex(GraphVertex):
    def __init__(self, scaleFactor=1.0):
        self.scaleFactor = scaleFactor

    def apply(self, x):
        return x * self.scaleFactor


@_register_vertex
class ShiftVertex(GraphVertex):
    def __init__(self, shiftFactor=0.0):
        self.shiftFactor = shiftFactor

    def apply(self, x):
        return x + self.shiftFactor


@_register_vertex
class StackVertex(GraphVertex):
    """Stack along the batch axis (reference: StackVertex)."""

    def apply(self, *xs):
        return jnp.concatenate(xs, axis=0)


@_register_vertex
class SubsetVertex(GraphVertex):
    def __init__(self, fromIdx=0, toIdx=0):
        self.fromIdx = int(fromIdx)
        self.toIdx = int(toIdx)

    def infer(self, *input_types):
        n = self.toIdx - self.fromIdx + 1
        t0 = input_types[0]
        # subset is on the feature/channel axis; preserve the input kind
        if isinstance(t0, ConvolutionalType):
            return InputType.convolutional(t0.height, t0.width, n)
        if isinstance(t0, RecurrentType):
            return InputType.recurrent(n, t0.timeSeriesLength)
        return InputType.feedForward(n)

    def apply(self, x):
        return x[:, self.fromIdx: self.toIdx + 1]


@_register_vertex
class L2NormalizeVertex(GraphVertex):
    def __init__(self, eps=1e-8):
        self.eps = eps

    def apply(self, x):
        n = jnp.sqrt(jnp.sum(x * x, axis=tuple(range(1, x.ndim)),
                             keepdims=True))
        return x / (n + self.eps)


@_register_vertex
class ReshapeVertex(GraphVertex):
    def __init__(self, newShape=None):
        self.newShape = tuple(newShape)

    def apply(self, x):
        return x.reshape((x.shape[0],) + tuple(self.newShape))


class ComputationGraphConfiguration:
    def __init__(self, inputs, nodes, outputs, defaults=None, seed=12345,
                 dataType="float32", input_types=None,
                 backpropType="Standard", tbpttLength=None, precision=None):
        self.inputs = list(inputs)            # input names
        self.nodes = nodes                    # name -> (layer|vertex, [input names])
        self.outputs = list(outputs)          # output layer names
        self.defaults = defaults or {}
        self.seed = seed
        self.dataType = dataType
        self.input_types = input_types or {}
        self.backpropType = backpropType
        self.tbpttLength = tbpttLength
        self.precision = precision            # policy name / Policy / None
        self.topo_order: list[str] = []
        self._finalize()

    def _finalize(self):
        # defaults
        for name, (node, _) in self.nodes.items():
            if isinstance(node, BaseLayer):
                node.apply_defaults(self.defaults)
        # topological order (Kahn)
        indeg = {n: 0 for n in self.nodes}
        dependents: dict[str, list[str]] = {n: [] for n in self.nodes}
        for name, (_, ins) in self.nodes.items():
            for i in ins:
                if i in self.nodes:
                    indeg[name] += 1
                    dependents[i].append(name)
                elif i not in self.inputs:
                    raise ValueError(f"node {name!r} input {i!r} undefined")
        ready = sorted(n for n, d in indeg.items() if d == 0)
        order = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for m in dependents[n]:
                indeg[m] -= 1
                if indeg[m] == 0:
                    ready.append(m)
        if len(order) != len(self.nodes):
            raise ValueError("cycle in computation graph")
        self.topo_order = order
        # shape inference when input types are declared
        if self.input_types:
            types = dict(self.input_types)
            for name in order:
                node, ins = self.nodes[name]
                in_types = [types[i] for i in ins if i in types]
                if len(in_types) != len(ins):
                    continue
                multi = (isinstance(node, GraphVertex)
                         or getattr(node, "MULTI_INPUT", False))
                types[name] = (node.infer(*in_types) if multi
                               else node.infer(in_types[0]))

    @property
    def dtype(self):
        return jnp.dtype(self.dataType)

    @property
    def precision_policy(self):
        from deeplearning4j_tpu.precision import resolve_policy

        return resolve_policy(self.precision, self.dataType)

    def to_json(self):
        nodes = {}
        for name, (node, ins) in self.nodes.items():
            kind = "layer" if isinstance(node, BaseLayer) else "vertex"
            nodes[name] = {"kind": kind, "conf": node.to_json(),
                           "inputs": list(ins)}
        from deeplearning4j_tpu.nn.conf.configuration import _json_defaults

        return json.dumps({
            "inputs": self.inputs,
            "nodes": nodes,
            "outputs": self.outputs,
            "defaults": _json_defaults(self.defaults),
            "seed": self.seed,
            "dataType": self.dataType,
            "inputTypes": {k: v.to_json()
                           for k, v in self.input_types.items()},
            "backpropType": self.backpropType,
            "tbpttLength": self.tbpttLength,
            "precision": (self.precision.to_json()
                          if hasattr(self.precision, "to_json")
                          else self.precision),
        }, indent=1)

    toJson = to_json

    @staticmethod
    def from_json(s):
        from deeplearning4j_tpu.optimize.updaters import updater_from_config

        d = json.loads(s) if isinstance(s, str) else s
        nodes = {}
        for name, nd in d["nodes"].items():
            conf = (BaseLayer.from_json(nd["conf"]) if nd["kind"] == "layer"
                    else GraphVertex.from_json(nd["conf"]))
            nodes[name] = (conf, nd["inputs"])
        defaults = dict(d.get("defaults") or {})
        if isinstance(defaults.get("updater"), dict):
            defaults["updater"] = updater_from_config(defaults["updater"])
        input_types = {k: InputType.from_json(v)
                       for k, v in (d.get("inputTypes") or {}).items()}
        return ComputationGraphConfiguration(
            d["inputs"], nodes, d["outputs"], defaults, d.get("seed", 12345),
            d.get("dataType", "float32"), input_types,
            d.get("backpropType", "Standard"), d.get("tbpttLength"),
            d.get("precision"))

    fromJson = from_json


class GraphBuilder:
    def __init__(self, defaults, seed, dataType, precision=None):
        self._defaults = defaults
        self._seed = seed
        self._dataType = dataType
        self._precision = precision
        self._inputs: list[str] = []
        self._nodes: dict = {}
        self._outputs: list[str] = []
        self._input_types: dict = {}

    def addInputs(self, *names):
        self._inputs.extend(names)
        return self

    def setInputTypes(self, *types):
        for name, t in zip(self._inputs, types):
            self._input_types[name] = t
        return self

    def addLayer(self, name, layer, *inputs):
        self._nodes[name] = (layer, list(inputs))
        return self

    def addVertex(self, name, vertex, *inputs):
        self._nodes[name] = (vertex, list(inputs))
        return self

    def setOutputs(self, *names):
        self._outputs = list(names)
        return self

    def backpropType(self, bt, tbpttLength=None):
        """Reference: GraphBuilder.backpropType(TruncatedBPTT) +
        tBPTTForwardLength/tBPTTBackwardLength (one symmetric length)."""
        self._backprop_type = bt
        if tbpttLength is not None:
            self._tbptt_length = int(tbpttLength)
        return self

    def tBPTTLength(self, n):
        self._backprop_type = "TruncatedBPTT"
        self._tbptt_length = int(n)
        return self

    def build(self) -> ComputationGraphConfiguration:
        return ComputationGraphConfiguration(
            self._inputs, self._nodes, self._outputs, dict(self._defaults),
            self._seed, self._dataType, self._input_types,
            getattr(self, "_backprop_type", "Standard"),
            getattr(self, "_tbptt_length", None), self._precision)
