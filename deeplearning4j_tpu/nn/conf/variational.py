"""Unsupervised pretrain layers: AutoEncoder and VariationalAutoencoder.

Reference capability: org.deeplearning4j.nn.conf.layers.AutoEncoder and
org.deeplearning4j.nn.conf.layers.variational.VariationalAutoencoder
(+ nn.layers.variational.VariationalAutoencoder runtime and the
ReconstructionDistribution family) — SURVEY.md §2.5 "Layer impls".
In the reference these layers carry a layerwise pretrain path
(MultiLayerNetwork.pretrain / pretrainLayer) driven by per-op dispatch;
here the pretrain loss is a pure function the network jits into ONE
compiled unsupervised step (see MultiLayerNetwork.pretrainLayer).

During supervised forward/backprop both layers act as plain feed-forward
encoders, exactly like the reference (AutoEncoder.activate encodes;
the VAE outputs the MEAN of q(z|x)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import resolve_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BaseLayer, _register
from deeplearning4j_tpu.nn.weights import init_weight


# ---------------------------------------------------------------------------
# reconstruction distributions (reference:
# nn.conf.layers.variational.{GaussianReconstructionDistribution,
# BernoulliReconstructionDistribution})
# ---------------------------------------------------------------------------

class ReconstructionDistribution:
    """p(x|z): maps decoder pre-activations to a log-probability of the
    data. distributionInputSize(nIn) gives how many decoder outputs the
    distribution needs per data dimension."""

    name = "base"

    def distribution_input_size(self, n_in: int) -> int:
        raise NotImplementedError

    def log_prob(self, x, pre):
        """Sum over data dims -> per-example log p(x|z), shape [N]."""
        raise NotImplementedError

    def sample_mean(self, pre):
        """E[x|z] from decoder pre-activations (for generateAtMeanGivenZ)."""
        raise NotImplementedError

    def to_json(self):
        return {"@dist": type(self).__name__, **{
            k: v for k, v in self.__dict__.items() if not k.startswith("_")}}

    @staticmethod
    def from_json(d):
        d = dict(d)
        cls = _DISTRIBUTIONS[d.pop("@dist")]
        return cls(**d)


class GaussianReconstructionDistribution(ReconstructionDistribution):
    """Decoder emits [mean, log(sigma^2)] per data dim; activation is
    applied to the MEAN half only (reference semantics)."""

    name = "gaussian"

    def __init__(self, activation="identity"):
        self.activation = activation

    def distribution_input_size(self, n_in):
        return 2 * n_in

    def _split(self, pre):
        n = pre.shape[-1] // 2
        mean = resolve_activation(self.activation)(pre[..., :n])
        log_var = pre[..., n:]
        return mean, log_var

    def log_prob(self, x, pre):
        mean, log_var = self._split(pre)
        log_var = jnp.clip(log_var, -10.0, 10.0)
        lp = -0.5 * (jnp.log(2.0 * jnp.pi) + log_var
                     + jnp.square(x - mean) / jnp.exp(log_var))
        return jnp.sum(lp, axis=-1)

    def sample_mean(self, pre):
        return self._split(pre)[0]


class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """Decoder emits one logit per data dim; sigmoid gives p(x=1)."""

    name = "bernoulli"

    def __init__(self, activation="sigmoid"):
        self.activation = activation

    def distribution_input_size(self, n_in):
        return n_in

    def log_prob(self, x, pre):
        if self.activation == "sigmoid":
            # stable sigmoid cross-entropy straight on the logits
            lp = -(jnp.maximum(pre, 0.0) - pre * x
                   + jnp.log1p(jnp.exp(-jnp.abs(pre))))
            return jnp.sum(lp, axis=-1)
        p = jnp.clip(resolve_activation(self.activation)(pre), 1e-7,
                     1.0 - 1e-7)
        return jnp.sum(x * jnp.log(p) + (1.0 - x) * jnp.log1p(-p), axis=-1)

    def sample_mean(self, pre):
        return resolve_activation(self.activation)(pre)


_DISTRIBUTIONS = {c.__name__: c for c in (
    GaussianReconstructionDistribution, BernoulliReconstructionDistribution)}


def _resolve_distribution(d):
    if isinstance(d, ReconstructionDistribution):
        return d
    if isinstance(d, dict):
        return ReconstructionDistribution.from_json(d)
    key = str(d).lower()
    if key == "bernoulli":
        return BernoulliReconstructionDistribution()
    if key == "gaussian":
        return GaussianReconstructionDistribution()
    raise ValueError(f"unknown reconstruction distribution {d!r}")


# ---------------------------------------------------------------------------
# AutoEncoder
# ---------------------------------------------------------------------------

@_register
class AutoEncoder(BaseLayer):
    """Denoising autoencoder (reference: conf.layers.AutoEncoder).

    Supervised forward = encode: act(x W + b). Pretrain loss = corrupt the
    input with masking noise (corruptionLevel), encode, decode through the
    TIED transpose weight W^T + visible bias, score the reconstruction
    against the clean input.
    """

    HAS_PRETRAIN_LOSS = True

    def __init__(self, nIn=None, nOut=None, corruptionLevel=0.3,
                 sparsity=0.0, lossFunction="mse", **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.corruptionLevel = corruptionLevel
        self.sparsity = sparsity
        self.lossFunction = lossFunction

    def apply_defaults(self, defaults):
        # honor a global .activation(...) default; "sigmoid" is only the
        # no-default fallback (same propagation rule as BaseOutputLayer)
        if self.activation is None and defaults.get("activation") is None:
            self.activation = "sigmoid"
        super().apply_defaults(defaults)

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.arrayElementsPerExample()
        return InputType.feedForward(self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        k1, _ = jax.random.split(key)
        return {
            "W": init_weight(self.weightInit, k1, (self.nIn, self.nOut),
                             self.nIn, self.nOut, dtype),
            "b": jnp.full((self.nOut,), float(self.biasInit or 0.0), dtype),
            "vb": jnp.zeros((self.nIn,), dtype),
        }

    def apply(self, params, state, x, training, rng):
        return self._dropout(self._encode(params, x), training, rng), state

    def _encode(self, params, x):
        return self._act(x @ params["W"] + params["b"])

    def _decode(self, params, h):
        return self._act(h @ params["W"].T + params["vb"])

    def pretrain_loss(self, params, x, rng):
        """Mean reconstruction loss of the denoising pass, per example."""
        from deeplearning4j_tpu.nn.losses import resolve_loss

        xc = x
        if self.corruptionLevel and rng is not None:
            keep = jax.random.bernoulli(
                rng, 1.0 - self.corruptionLevel, x.shape)
            xc = jnp.where(keep, x, jnp.zeros_like(x))
        recon_pre = self._decode(params, self._encode(params, xc))
        # reconstruction scored pre-activation-free: the decode already
        # applied the activation, so use the identity head
        loss = resolve_loss(self.lossFunction)(x, recon_pre, "identity",
                                               None)
        if self.sparsity:
            # KL sparsity penalty toward the target mean activation
            rho = self.sparsity
            h_mean = jnp.clip(jnp.mean(self._encode(params, x), axis=0),
                              1e-6, 1.0 - 1e-6)
            loss = loss + jnp.sum(rho * jnp.log(rho / h_mean)
                                  + (1 - rho) * jnp.log(
                                      (1 - rho) / (1 - h_mean)))
        return loss


# ---------------------------------------------------------------------------
# VariationalAutoencoder
# ---------------------------------------------------------------------------

@_register
class VariationalAutoencoder(BaseLayer):
    """VAE layer (reference: conf.layers.variational.VariationalAutoencoder
    + nn.layers.variational runtime).

    nOut is the LATENT size. encoderLayerSizes / decoderLayerSizes are the
    hidden MLP widths. Supervised forward outputs the mean of q(z|x).
    Pretrain loss = -ELBO with the reparameterization trick:
    KL(q(z|x) || N(0, I)) - (1/S) sum_s log p(x | z_s).
    """

    HAS_PRETRAIN_LOSS = True

    def __init__(self, nIn=None, nOut=None, encoderLayerSizes=(256,),
                 decoderLayerSizes=(256,), pzxActivationFunction="identity",
                 reconstructionDistribution="bernoulli", numSamples=1, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.encoderLayerSizes = tuple(
            int(s) for s in (encoderLayerSizes if isinstance(
                encoderLayerSizes, (list, tuple)) else (encoderLayerSizes,)))
        self.decoderLayerSizes = tuple(
            int(s) for s in (decoderLayerSizes if isinstance(
                decoderLayerSizes, (list, tuple)) else (decoderLayerSizes,)))
        self.pzxActivationFunction = pzxActivationFunction
        self.reconstructionDistribution = _resolve_distribution(
            reconstructionDistribution)
        self.numSamples = int(numSamples)

    def apply_defaults(self, defaults):
        if self.activation is None and defaults.get("activation") is None:
            self.activation = "leakyrelu"
        super().apply_defaults(defaults)

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.arrayElementsPerExample()
        return InputType.feedForward(self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        p = {}
        keys = iter(jax.random.split(key, 2 * (
            len(self.encoderLayerSizes) + len(self.decoderLayerSizes)) + 4))

        def dense(prefix, shapes):
            last = shapes[0]
            for i, width in enumerate(shapes[1:]):
                p[f"{prefix}W{i}"] = init_weight(
                    self.weightInit, next(keys), (last, width), last, width,
                    dtype)
                p[f"{prefix}b{i}"] = jnp.zeros((width,), dtype)
                last = width
            return last

        e_last = dense("e", (self.nIn,) + self.encoderLayerSizes)
        p["meanW"] = init_weight(self.weightInit, next(keys),
                                 (e_last, self.nOut), e_last, self.nOut,
                                 dtype)
        p["meanB"] = jnp.zeros((self.nOut,), dtype)
        p["logVarW"] = init_weight(self.weightInit, next(keys),
                                   (e_last, self.nOut), e_last, self.nOut,
                                   dtype)
        p["logVarB"] = jnp.zeros((self.nOut,), dtype)
        d_last = dense("d", (self.nOut,) + self.decoderLayerSizes)
        out_size = self.reconstructionDistribution.distribution_input_size(
            self.nIn)
        p["xW"] = init_weight(self.weightInit, next(keys),
                              (d_last, out_size), d_last, out_size, dtype)
        p["xB"] = jnp.zeros((out_size,), dtype)
        return p

    # -- pure pieces ---------------------------------------------------------
    def _mlp(self, params, prefix, n, x):
        act = resolve_activation(self.activation)
        for i in range(n):
            x = act(x @ params[f"{prefix}W{i}"] + params[f"{prefix}b{i}"])
        return x

    def _posterior(self, params, x):
        h = self._mlp(params, "e", len(self.encoderLayerSizes), x)
        mean = resolve_activation(self.pzxActivationFunction)(
            h @ params["meanW"] + params["meanB"])
        log_var = jnp.clip(h @ params["logVarW"] + params["logVarB"],
                           -10.0, 10.0)
        return mean, log_var

    def _decode_pre(self, params, z):
        h = self._mlp(params, "d", len(self.decoderLayerSizes), z)
        return h @ params["xW"] + params["xB"]

    def apply(self, params, state, x, training, rng):
        mean, _ = self._posterior(params, x)
        return self._dropout(mean, training, rng), state

    def _sample_log_probs(self, params, x, rng, n_samples):
        """Reparameterized samples z_s ~ q(z|x) with the three per-sample
        log-densities the ELBO / importance estimates need. Returns
        (kl, [log p(x|z_s)], [log p(z_s) - log q(z_s|x)])."""
        mean, log_var = self._posterior(params, x)
        kl = 0.5 * jnp.sum(
            jnp.exp(log_var) + jnp.square(mean) - 1.0 - log_var, axis=-1)
        rng = rng if rng is not None else jax.random.key(0)
        recon, weight = [], []
        for s in range(n_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape,
                                    mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            recon.append(self.reconstructionDistribution.log_prob(
                x, self._decode_pre(params, z)))
            # log N(z; 0, I) - log N(z; mean, var), both diagonal
            log_p_z = -0.5 * jnp.sum(jnp.square(z) + jnp.log(2 * jnp.pi),
                                     axis=-1)
            log_q_z = -0.5 * jnp.sum(
                jnp.square(eps) + jnp.log(2 * jnp.pi) + log_var, axis=-1)
            weight.append(log_p_z - log_q_z)
        return kl, jnp.stack(recon), jnp.stack(weight)

    def pretrain_loss(self, params, x, rng):
        kl, recon, _ = self._sample_log_probs(params, x, rng,
                                              self.numSamples)
        return jnp.mean(kl - jnp.mean(recon, axis=0))

    # -- reference inference APIs -------------------------------------------
    def reconstruction_log_probability(self, params, x, rng=None,
                                       num_samples=None):
        """Per-example importance-sampled estimate of log p(x) (reference:
        VariationalAutoencoder.reconstructionLogProbability):
        logsumexp_s[log p(x|z_s) + log p(z_s) - log q(z_s|x)] - log S,
        which converges to log p(x) as S grows (IWAE bound)."""
        x = jnp.asarray(x)
        s_total = num_samples or self.numSamples
        _, recon, weight = self._sample_log_probs(params, x, rng, s_total)
        return (jax.scipy.special.logsumexp(recon + weight, axis=0)
                - jnp.log(float(s_total)))

    def generate_at_mean_given_z(self, params, z):
        """E[x|z] (reference: generateAtMeanGivenZ)."""
        return self.reconstructionDistribution.sample_mean(
            self._decode_pre(params, jnp.asarray(z)))

    def activate_latent(self, params, x):
        """Mean and log-variance of q(z|x)."""
        return self._posterior(params, jnp.asarray(x))
