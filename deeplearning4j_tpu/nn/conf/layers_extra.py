"""Structural / specialty layers rounding out the DL4J layer registry.

Reference capability (SURVEY.md §2.5 "Layer impls" — conf.layers.*):
Cropping1D/2D/3D, Upsampling1D/3D, Convolution3D, Subsampling3D,
LocallyConnected1D/2D, PReLULayer, RepeatVector, MaskZeroLayer,
FrozenLayer, ElementWiseMultiplicationLayer, CenterLossOutputLayer.
All are pure-function emitters lowered into the net's single compiled
step like every other layer; 3-D convolution maps straight onto
lax.conv_general_dilated with NCDHW dimension numbers (one XLA op where
the reference has a vol2col + gemm helper chain).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import (
    Convolutional3DType, InputType)
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayer, BaseOutputLayer, ConvolutionMode, LossLayer, PoolingType,
    _pair, _register)
from deeplearning4j_tpu.nn.weights import init_weight


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


# ---------------------------------------------------------------------------
# cropping
# ---------------------------------------------------------------------------

@_register
class Cropping1D(BaseLayer):
    """[N, C, T] -> crop (head, tail) timesteps (reference:
    conf.layers.convolutional.Cropping1D)."""

    def __init__(self, cropping=(0, 0), **kw):
        super().__init__(**kw)
        c = cropping if isinstance(cropping, (list, tuple)) else (cropping,
                                                                  cropping)
        self.cropping = tuple(int(v) for v in c)

    def infer(self, input_type):
        t = getattr(input_type, "timeSeriesLength", None)
        if t is not None:
            t = t - self.cropping[0] - self.cropping[1]
            if t <= 0:
                raise ValueError(
                    f"Cropping1D{self.cropping} consumes the whole "
                    f"{input_type.timeSeriesLength}-step sequence")
        return InputType.recurrent(input_type.size, t)

    def apply(self, params, state, x, training, rng):
        a, bz = self.cropping
        return x[:, :, a: x.shape[2] - bz], state


@_register
class Cropping2D(BaseLayer):
    """[N, C, H, W] -> crop (top, bottom, left, right) (reference:
    conf.layers.convolutional.Cropping2D)."""

    def __init__(self, cropping=(0, 0, 0, 0), **kw):
        super().__init__(**kw)
        c = cropping
        if isinstance(c, int):
            c = (c, c, c, c)
        elif len(c) == 2:
            c = (c[0], c[0], c[1], c[1])
        self.cropping = tuple(int(v) for v in c)

    def infer(self, input_type):
        t, b, l, r = self.cropping
        oh = input_type.height - t - b
        ow = input_type.width - l - r
        if oh <= 0 or ow <= 0:
            raise ValueError(
                f"Cropping2D{self.cropping} leaves a {oh}x{ow} output for "
                f"{input_type.height}x{input_type.width} input")
        return InputType.convolutional(oh, ow, input_type.channels)

    def apply(self, params, state, x, training, rng):
        t, b, l, r = self.cropping
        return x[:, :, t: x.shape[2] - b, l: x.shape[3] - r], state


@_register
class Cropping3D(BaseLayer):
    """[N, C, D, H, W] crop; cropping = (d1, d2, h1, h2, w1, w2)."""

    def __init__(self, cropping=(0, 0, 0, 0, 0, 0), **kw):
        super().__init__(**kw)
        c = cropping
        if isinstance(c, int):
            c = (c,) * 6
        elif len(c) == 3:
            c = (c[0], c[0], c[1], c[1], c[2], c[2])
        self.cropping = tuple(int(v) for v in c)

    def infer(self, input_type):
        d1, d2, h1, h2, w1, w2 = self.cropping
        od = input_type.depth - d1 - d2
        oh = input_type.height - h1 - h2
        ow = input_type.width - w1 - w2
        if od <= 0 or oh <= 0 or ow <= 0:
            raise ValueError(
                f"Cropping3D{self.cropping} leaves a {od}x{oh}x{ow} output")
        return InputType.convolutional3D(od, oh, ow, input_type.channels)

    def apply(self, params, state, x, training, rng):
        d1, d2, h1, h2, w1, w2 = self.cropping
        return x[:, :, d1: x.shape[2] - d2, h1: x.shape[3] - h2,
                 w1: x.shape[4] - w2], state


# ---------------------------------------------------------------------------
# upsampling
# ---------------------------------------------------------------------------

@_register
class Upsampling1D(BaseLayer):
    """[N, C, T] -> repeat each timestep `size` times."""

    def __init__(self, size=2, **kw):
        super().__init__(**kw)
        self.size = int(size)

    def infer(self, input_type):
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(input_type.size,
                                   t * self.size if t else None)

    def apply(self, params, state, x, training, rng):
        return jnp.repeat(x, self.size, axis=2), state


@_register
class Upsampling3D(BaseLayer):
    """[N, C, D, H, W] nearest-neighbor upsampling."""

    def __init__(self, size=(2, 2, 2), **kw):
        super().__init__(**kw)
        self.size = _triple(size)

    def infer(self, input_type):
        sd, sh, sw = self.size
        return InputType.convolutional3D(input_type.depth * sd,
                                         input_type.height * sh,
                                         input_type.width * sw,
                                         input_type.channels)

    def apply(self, params, state, x, training, rng):
        sd, sh, sw = self.size
        x = jnp.repeat(x, sd, axis=2)
        x = jnp.repeat(x, sh, axis=3)
        return jnp.repeat(x, sw, axis=4), state


# ---------------------------------------------------------------------------
# 3-D convolution / pooling (NCDHW)
# ---------------------------------------------------------------------------

@_register
class Convolution3D(BaseLayer):
    """Reference: conf.layers.Convolution3D (NCDHW). One
    lax.conv_general_dilated call replaces the reference's vol2col + gemm
    helper chain."""

    def __init__(self, nIn=None, nOut=None, kernelSize=(3, 3, 3),
                 stride=(1, 1, 1), padding=(0, 0, 0), dilation=(1, 1, 1),
                 convolutionMode=None, hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.kernelSize = _triple(kernelSize)
        self.stride = _triple(stride)
        self.padding = _triple(padding)
        self.dilation = _triple(dilation)
        self.convolutionMode = convolutionMode or ConvolutionMode.TRUNCATE
        self.hasBias = hasBias

    def _same(self):
        return self.convolutionMode == ConvolutionMode.SAME

    def infer(self, input_type):
        if not isinstance(input_type, Convolutional3DType):
            raise ValueError(
                f"Convolution3D needs convolutional3D input, "
                f"got {input_type}")
        self.nIn = self.nIn or input_type.channels
        dims = (input_type.depth, input_type.height, input_type.width)
        out = []
        for i in range(3):
            k = (self.kernelSize[i] - 1) * self.dilation[i] + 1
            if self._same():
                out.append(-(-dims[i] // self.stride[i]))
            else:
                out.append((dims[i] + 2 * self.padding[i] - k)
                           // self.stride[i] + 1)
        return InputType.convolutional3D(out[0], out[1], out[2], self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        kd, kh, kw = self.kernelSize
        fan_in = self.nIn * kd * kh * kw
        fan_out = self.nOut * kd * kh * kw
        k1, _ = jax.random.split(key)
        p = {"W": init_weight(self.weightInit, k1,
                              (self.nOut, self.nIn, kd, kh, kw),
                              fan_in, fan_out, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        x = self._dropout(x, training, rng)
        if self._same():
            pad = "SAME"
        else:
            pad = [(p, p) for p in self.padding]
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=self.stride, padding=pad,
            rhs_dilation=self.dilation,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        if "b" in params:
            y = y + params["b"].reshape(1, -1, 1, 1, 1)
        return self._act(y), state


@_register
class Subsampling3DLayer(BaseLayer):
    """Reference: conf.layers.Subsampling3DLayer (max/avg, NCDHW)."""

    def __init__(self, poolingType=PoolingType.MAX, kernelSize=(2, 2, 2),
                 stride=(2, 2, 2), padding=(0, 0, 0), convolutionMode=None,
                 **kw):
        super().__init__(**kw)
        self.poolingType = poolingType
        self.kernelSize = _triple(kernelSize)
        self.stride = _triple(stride)
        self.padding = _triple(padding)
        self.convolutionMode = convolutionMode or ConvolutionMode.TRUNCATE

    def infer(self, input_type):
        dims = (input_type.depth, input_type.height, input_type.width)
        out = []
        for i in range(3):
            if self.convolutionMode == ConvolutionMode.SAME:
                out.append(-(-dims[i] // self.stride[i]))
            else:
                out.append((dims[i] + 2 * self.padding[i]
                            - self.kernelSize[i]) // self.stride[i] + 1)
        return InputType.convolutional3D(out[0], out[1], out[2],
                                         input_type.channels)

    def apply(self, params, state, x, training, rng):
        window = (1, 1) + self.kernelSize
        strides = (1, 1) + self.stride
        if self.convolutionMode == ConvolutionMode.SAME:
            pad = "SAME"
        else:
            pad = ((0, 0), (0, 0)) + tuple(
                (p, p) for p in self.padding)
        if self.poolingType == PoolingType.MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                  pad)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            c = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                  strides, pad)
            y = s / c
        return y, state


# ---------------------------------------------------------------------------
# locally connected (unshared conv weights)
# ---------------------------------------------------------------------------

@_register
class LocallyConnected2D(BaseLayer):
    """Convolution with UNSHARED per-position weights (reference:
    conf.layers.LocallyConnected2D). Patches come from one
    conv_general_dilated_patches call; the per-position contraction is a
    single batched einsum on the MXU instead of the reference's unrolled
    per-window gemms."""

    def __init__(self, nIn=None, nOut=None, kernelSize=(2, 2),
                 stride=(1, 1), padding=(0, 0), hasBias=True,
                 inputSize=None, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.kernelSize = _pair(kernelSize)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.hasBias = hasBias
        self.inputSize = tuple(inputSize) if inputSize else None  # (H, W)

    def _out_hw(self):
        h, w = self.inputSize
        kh, kw = self.kernelSize
        sh, sw = self.stride
        ph, pw = self.padding
        return ((h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1)

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.channels
        self.inputSize = (input_type.height, input_type.width)
        oh, ow = self._out_hw()
        return InputType.convolutional(oh, ow, self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        if self.inputSize is None:
            raise ValueError("LocallyConnected2D needs inputSize (H, W) "
                             "or setInputType on the config")
        kh, kw = self.kernelSize
        oh, ow = self._out_hw()
        k = self.nIn * kh * kw
        k1, _ = jax.random.split(key)
        p = {"W": init_weight(self.weightInit, k1,
                              (oh * ow, k, self.nOut), k, self.nOut,
                              dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        ph, pw = self.padding
        patches = lax.conv_general_dilated_patches(
            x, self.kernelSize, self.stride,
            [(ph, ph), (pw, pw)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        n, k, oh, ow = patches.shape
        patches = patches.reshape(n, k, oh * ow)
        y = jnp.einsum("nkp,pko->nop", patches, params["W"])
        y = y.reshape(n, -1, oh, ow)
        if "b" in params:
            y = y + params["b"].reshape(1, -1, 1, 1)
        return self._act(y), state


@_register
class LocallyConnected1D(BaseLayer):
    """[N, C, T] unshared 1-D convolution."""

    def __init__(self, nIn=None, nOut=None, kernelSize=2, stride=1,
                 padding=0, hasBias=True, inputSize=None, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.kernelSize = int(kernelSize)
        self.stride = int(stride)
        self.padding = int(padding)
        self.hasBias = hasBias
        self.inputSize = int(inputSize) if inputSize else None  # T

    def _out_t(self):
        return ((self.inputSize + 2 * self.padding - self.kernelSize)
                // self.stride + 1)

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        t = getattr(input_type, "timeSeriesLength", None)
        if t:
            self.inputSize = t
        return InputType.recurrent(self.nOut,
                                   self._out_t() if self.inputSize else None)

    def init_params(self, key, dtype=jnp.float32):
        if self.inputSize is None:
            raise ValueError("LocallyConnected1D needs inputSize (T) or a "
                             "recurrent input type with a declared length")
        k = self.nIn * self.kernelSize
        k1, _ = jax.random.split(key)
        p = {"W": init_weight(self.weightInit, k1,
                              (self._out_t(), k, self.nOut), k, self.nOut,
                              dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        p = self.padding
        patches = lax.conv_general_dilated_patches(
            x, (self.kernelSize,), (self.stride,), [(p, p)],
            dimension_numbers=("NCH", "OIH", "NCH"))
        n, k, ot = patches.shape
        y = jnp.einsum("nkp,pko->nop", patches, params["W"])
        y = y.reshape(n, -1, ot)
        if "b" in params:
            y = y + params["b"].reshape(1, -1, 1)
        return self._act(y), state


# ---------------------------------------------------------------------------
# small parametric / shaping layers
# ---------------------------------------------------------------------------

@_register
class PReLULayer(BaseLayer):
    """Parametric ReLU with a learned per-channel slope (reference:
    conf.layers.PReLULayer)."""

    def __init__(self, nIn=None, alphaInit=0.0, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.alphaInit = float(alphaInit)

    def infer(self, input_type):
        self.nIn = self.nIn or getattr(
            input_type, "channels", getattr(input_type, "size", None))
        return input_type

    def init_params(self, key, dtype=jnp.float32):
        return {"alpha": jnp.full((self.nIn,), self.alphaInit, dtype)}

    def apply(self, params, state, x, training, rng):
        shape = [1] * x.ndim
        shape[1 if x.ndim > 2 else -1] = -1
        a = params["alpha"].reshape(shape)
        return jnp.where(x >= 0, x, a * x), state


@_register
class RepeatVector(BaseLayer):
    """[N, C] -> [N, C, n] (reference: conf.layers.misc.RepeatVector)."""

    def __init__(self, repetitionFactor=2, **kw):
        super().__init__(**kw)
        self.repetitionFactor = int(repetitionFactor)

    def infer(self, input_type):
        return InputType.recurrent(input_type.size, self.repetitionFactor)

    def apply(self, params, state, x, training, rng):
        return jnp.repeat(x[:, :, None], self.repetitionFactor, axis=2), \
            state


@_register
class ElementWiseMultiplicationLayer(BaseLayer):
    """out = act(x * w + b) with learned per-feature w, b (reference:
    conf.layers.misc.ElementWiseMultiplicationLayer)."""

    def __init__(self, nIn=None, nOut=None, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        self.nOut = self.nIn
        return InputType.feedForward(self.nIn)

    def init_params(self, key, dtype=jnp.float32):
        return {"w": jnp.ones((self.nIn,), dtype),
                "b": jnp.full((self.nIn,), float(self.biasInit or 0.0),
                              dtype)}

    def apply(self, params, state, x, training, rng):
        return self._act(x * params["w"] + params["b"]), state


@_register
class MaskZeroLayer(BaseLayer):
    """Wrapper deriving a timestep mask from the INPUT (timesteps where
    every feature equals maskingValue), zeroing the wrapped layer's input
    AND output at masked steps so a recurrent underlying layer's carried
    state never sees the masking sentinel (reference:
    conf.layers.util.MaskZeroLayer — the keras-import masking idiom)."""

    def __init__(self, underlying=None, maskingValue=0.0, **kw):
        super().__init__(**kw)
        self.underlying = underlying
        self.maskingValue = float(maskingValue)

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        if self.underlying is not None:
            self.underlying.apply_defaults(defaults)

    def infer(self, input_type):
        return self.underlying.infer(input_type)

    def init_params(self, key, dtype=jnp.float32):
        return self.underlying.init_params(key, dtype)

    def init_state(self, dtype=jnp.float32):
        return self.underlying.init_state(dtype)

    def apply(self, params, state, x, training, rng):
        # Zero the INPUT at masked timesteps (not just the output): a
        # recurrent underlying layer must not carry hidden state polluted
        # by interior masked steps — reference zeroes the input and the
        # RNN honors the mask.
        keep = jnp.any(x != self.maskingValue, axis=1, keepdims=True)
        keep = keep.astype(x.dtype)
        y, state = self.underlying.apply(params, state, x * keep,
                                         training, rng)
        return y * keep.astype(y.dtype), state


@_register
class FrozenLayer(BaseLayer):
    """Wrapper excluding the inner layer from training (reference:
    conf.layers.misc.FrozenLayer; freezing = the NoOp updater, same
    mechanism as TransferLearning.setFeatureExtractor)."""

    def __init__(self, layer=None, **kw):
        super().__init__(**kw)
        self.layer = layer
        from deeplearning4j_tpu.optimize.updaters import NoOp

        self.updater = NoOp()

    def apply_defaults(self, defaults):
        d = dict(defaults)
        d.pop("updater", None)   # keep NoOp regardless of the global
        super().apply_defaults(d)
        if self.layer is not None:
            self.layer.apply_defaults(d)

    def infer(self, input_type):
        return self.layer.infer(input_type)

    def init_params(self, key, dtype=jnp.float32):
        return self.layer.init_params(key, dtype)

    def init_state(self, dtype=jnp.float32):
        return self.layer.init_state(dtype)

    def apply(self, params, state, x, training, rng):
        # frozen = inference behavior even during fit: no dropout, no
        # batch-norm running-stat updates (state is returned unchanged)
        y, _ = self.layer.apply(params, state, x, False, None)
        return y, state


@_register
class CenterLossOutputLayer(BaseOutputLayer):
    """Classification output with an added center-loss pull toward learned
    per-class feature centers (reference:
    conf.layers.CenterLossOutputLayer, used by FaceNet-style zoo models).

    Centers here are PARAMETERS optimized jointly by the layer's updater
    (gradient lambda*(c_y - h)) rather than the reference's separate
    alpha-EMA update — same fixed point, one compiled step, and the loss
    stays exactly differentiable (numeric gradient checks pass). `alpha`
    (the reference's EMA rate) is therefore accepted-and-IGNORED config
    parity: the centers' effective learning rate is the optimizer's. A
    one-time warning makes the divergence visible.
    """

    _warned_alpha = False

    def __init__(self, alpha=0.05, lambdaCoeff=2e-4, **kw):
        super().__init__(**kw)
        self.alpha = float(alpha)
        self.lambdaCoeff = float(lambdaCoeff)
        if alpha != 0.05 and not CenterLossOutputLayer._warned_alpha:
            import warnings

            warnings.warn(
                "CenterLossOutputLayer.alpha is accepted for DL4J config "
                "parity but ignored: centers train with the layer's "
                "updater, not an alpha-EMA", stacklevel=2)
            CenterLossOutputLayer._warned_alpha = True

    def init_params(self, key, dtype=jnp.float32):
        p = super().init_params(key, dtype)
        p["centers"] = jnp.zeros((self.nOut, self.nIn), dtype)
        return p

    def compute_loss(self, params, x, labels, mask=None):
        base = super().compute_loss(params, x, labels, mask)
        # labels one-hot [N, numClasses] -> each example's class center
        c = labels @ params["centers"]                 # [N, nIn]
        pull = jnp.sum(jnp.square(x - c), axis=-1)
        if mask is not None and mask.ndim == 1:
            pull = pull * mask
        return base + 0.5 * self.lambdaCoeff * jnp.mean(pull)


@_register
class OCNNOutputLayer(LossLayer):
    """One-class neural network output for anomaly detection (reference:
    org.deeplearning4j.nn.conf.ocnn.OCNNOutputLayer — hiddenSize, nu,
    windowSize, rUpdate schedule).

    Score y = w . act(V x); training minimizes the OC-SVM-style objective
      mean(relu(q - y)) / nu - q,   q = nu-quantile of the BATCH scores
    (the paper's alternating scheme: refresh r from the scores, then one
    gradient step at fixed r). The state keeps r as an exponentially-
    smoothed nu-quantile — the INFERENCE threshold; the smoothing
    horizon is windowSize EXAMPLES, the analog of the reference's
    every-windowSize r refresh. At inference, examples with y < r are
    anomalies.

    Two deliberate choices that keep training non-degenerate (seed-era
    collapse: all weights decayed to 0 and scores lost all input
    dependence):
    - weight decay is NOT hardcoded into the loss; like the reference,
      ||V||/||w|| regularization comes from the layer's configured
      l1/l2 (a hardcoded 0.5||.||^2 dominates the bounded hinge force
      and collapses V and w to zero);
    - the default hidden activation is relu: an activation with
      f(0) != 0 (sigmoid) admits a constant-score solution through w
      alone with V = 0, i.e. an anomaly score that ignores the input.
    """

    LOSS_UPDATES_STATE = True

    def __init__(self, nIn=None, hiddenSize=10, nu=0.04, windowSize=10000,
                 activation=None, lossFunction="ocnn", **kw):
        super().__init__(lossFunction=lossFunction,
                         activation=activation or "relu", **kw)
        self.nIn = nIn
        self.hiddenSize = int(hiddenSize)
        self.nu = float(nu)
        self.windowSize = int(windowSize)

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        return InputType.feedForward(1)

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        return {"V": init_weight(self.weightInit, k1,
                                 (self.nIn, self.hiddenSize), self.nIn,
                                 self.hiddenSize, dtype),
                "w": init_weight(self.weightInit, k2, (self.hiddenSize,),
                                 self.hiddenSize, 1, dtype)}

    def _score(self, params, x):
        from deeplearning4j_tpu.nn.activations import resolve_activation

        h = resolve_activation(self.activation)(x @ params["V"])
        return h @ params["w"]

    def apply(self, params, state, x, training, rng):
        return self._score(params, x)[:, None], state

    def _smoothed_r(self, y, state):
        """(batch nu-quantile q, new state with the smoothed r)."""
        q = jnp.quantile(jax.lax.stop_gradient(y), self.nu)
        n = y.shape[0]
        alpha = min(1.0, n / max(self.windowSize, 1))
        seen = state.get("seen", jnp.zeros((), jnp.int32))
        r = jnp.where(seen > 0,
                      (1.0 - alpha) * state["r"] + alpha * q, q)
        return q, {"r": r.astype(state["r"].dtype), "seen": seen + 1}

    def init_state(self, dtype=jnp.float32):
        return {"r": jnp.zeros((), dtype),
                "seen": jnp.zeros((), jnp.int32)}

    def compute_loss_with_state(self, params, x, labels, mask=None,
                                state=None):
        """labels are IGNORED (one-class training trains on normal data
        only, reference semantics). The hinge uses the CURRENT batch's
        quantile q — with the lagging smoothed r the hinge goes quiet
        and nothing counteracts collapse; the smoothed r stays in the
        state as the inference threshold."""
        y = self._score(params, x)
        q, new_state = self._smoothed_r(y, state or self.init_state())
        hinge = jnp.maximum(0.0, q - y)
        if mask is not None and mask.ndim == 1:
            hinge = hinge * mask
        return jnp.mean(hinge) / self.nu - q, new_state

    def compute_loss(self, params, x, labels, mask=None):
        loss, _ = self.compute_loss_with_state(params, x, labels, mask)
        return loss


# ---------------------------------------------------------------------------
# mixture-of-experts
# ---------------------------------------------------------------------------

@_register
class MoELayer(BaseLayer):
    """Mixture-of-Experts FFN block usable anywhere in a
    MultiLayerNetwork: GShard/Switch top-k gating over nExperts expert
    FFNs [nIn -> ffnSize -> nOut], capacity-bounded with overflow drop.

    The load-balancing aux loss (Switch Transformer eq. 4, scaled by
    auxWeight) rides the layer-state channel: apply() stores it under
    "_aux_loss" and MultiLayerNetwork adds every layer's _aux_loss to the
    training objective. No reference analog (SURVEY.md §2.6 marks expert
    parallel "NO") — additive capability; the expert axis shards over an
    `expert` mesh when trained under ShardedTrainer/BertTrainer-style
    GSPMD jits, and runs as E batched einsums on one device otherwise.
    """

    def __init__(self, nIn=None, nOut=None, ffnSize=None, nExperts=4,
                 topK=2, capacityFactor=1.5, auxWeight=1e-2, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.ffnSize = ffnSize
        self.nExperts = int(nExperts)
        self.topK = int(topK)
        self.capacityFactor = float(capacityFactor)
        self.auxWeight = float(auxWeight)

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        self.nOut = self.nOut or self.nIn
        self.ffnSize = self.ffnSize or 4 * self.nIn
        from deeplearning4j_tpu.nn.conf.inputs import InputType as _IT

        return _IT.feedForward(self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        ks = jax.random.split(key, 3)
        e, h, f, o = self.nExperts, self.nIn, self.ffnSize, self.nOut
        wi = self.weightInit
        return {
            "gate_w": init_weight(wi, ks[0], (h, e), h, e, dtype),
            "w1": init_weight(wi, ks[1], (e, h, f), h, f, dtype),
            "b1": jnp.zeros((e, f), dtype),
            "w2": init_weight(wi, ks[2], (e, f, o), f, o, dtype),
            "b2": jnp.zeros((e, o), dtype),
        }

    def init_state(self, dtype=jnp.float32):
        return {"_aux_loss": jnp.zeros((), jnp.float32)}

    def apply(self, params, state, x, training, rng):
        from deeplearning4j_tpu.parallel.moe import moe_apply

        y, aux = moe_apply(params, x, k=self.topK,
                           capacity_factor=self.capacityFactor)
        return self._act(y), {
            "_aux_loss": self.auxWeight * aux.astype(jnp.float32)}
