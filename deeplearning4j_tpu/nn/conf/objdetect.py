"""Object detection: the YOLOv2 output layer.

Reference capability: org.deeplearning4j.nn.conf.layers.objdetect
.Yolo2OutputLayer + nn.layers.objdetect.Yolo2OutputLayer (SURVEY.md §2.5
layer impls; used by the TinyYOLO / YOLO2 zoo models, §2.7). The
reference computes the YOLOv2 loss with per-op dispatch over [N,B*(5+C),
H,W] activations; here the whole loss is one pure jit-able function —
anchor assignment (argmax IoU vs priors) is computed with vectorized
one-hot masks so there is no data-dependent control flow.

Layout contracts (identical to the reference):
  network output: [N, B*(5+C), H, W]   B anchors, C classes,
                  per-anchor channels = (tx, ty, tw, th, to, c_0..c_{C-1})
  labels:         [N, 4+C, H, W]       channels = (x1, y1, x2, y2) in GRID
                  units + one-hot class, zero everywhere for empty cells.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayer, LossLayer, _register)


def _anchor_iou(wh_gt, priors):
    """IoU of centered boxes: wh_gt [..., 2] vs priors [B, 2] -> [..., B]."""
    gw, gh = wh_gt[..., 0:1], wh_gt[..., 1:2]            # [..., 1]
    pw, ph = priors[:, 0], priors[:, 1]                  # [B]
    inter = jnp.minimum(gw, pw) * jnp.minimum(gh, ph)
    union = gw * gh + pw * ph - inter
    return inter / jnp.maximum(union, 1e-9)


@_register
class Yolo2OutputLayer(LossLayer):
    """YOLOv2 detection loss (reference: conf.layers.objdetect
    .Yolo2OutputLayer.Builder with lambdaCoord/lambdaNoObj and the three
    component losses; defaults follow the reference: L2 position/class
    losses, lambdaCoord=5, lambdaNoObj=0.5).

    boundingBoxPriors: [B, 2] anchor (width, height) pairs in grid units.
    """

    def __init__(self, boundingBoxPriors=None, lambdaCoord=5.0,
                 lambdaNoObj=0.5, lossPositionScale="l2",
                 lossClassPredictions="l2", **kw):
        kw.setdefault("lossFunction", "mse")
        super().__init__(**kw)
        if boundingBoxPriors is None:
            raise ValueError("Yolo2OutputLayer requires boundingBoxPriors")
        self.boundingBoxPriors = [[float(v) for v in p]
                                  for p in np.asarray(boundingBoxPriors)]
        self.lambdaCoord = float(lambdaCoord)
        self.lambdaNoObj = float(lambdaNoObj)
        self.lossPositionScale = lossPositionScale
        self.lossClassPredictions = lossClassPredictions
        self.activation = "identity"

    # -- geometry ------------------------------------------------------------
    @property
    def n_anchors(self):
        return len(self.boundingBoxPriors)

    def _split(self, x):
        """[N, B*(5+C), H, W] -> (txy, twh, to, logits) with anchor axis:
        txy [N,B,2,H,W], twh [N,B,2,H,W], to [N,B,H,W],
        logits [N,B,C,H,W]."""
        n, ch, h, w = x.shape
        b = self.n_anchors
        per = ch // b
        x = x.reshape(n, b, per, h, w)
        return (x[:, :, 0:2], x[:, :, 2:4], x[:, :, 4],
                x[:, :, 5:])

    def _decode(self, x):
        """Decoded predictions [N, B, 5+C, H, W]: xy = cell-relative
        sigmoid, wh = prior * exp(twh) (grid units), confidence sigmoid,
        class softmax (reference: nn.layers.objdetect.Yolo2OutputLayer
        .activate)."""
        txy, twh, to, logits = self._split(x)
        priors = jnp.asarray(self.boundingBoxPriors, x.dtype)  # [B, 2]
        xy = jax.nn.sigmoid(txy)
        wh = priors[None, :, :, None, None] * jnp.exp(
            jnp.clip(twh, -10.0, 10.0))
        conf = jax.nn.sigmoid(to)[:, :, None]
        cls = jax.nn.softmax(logits, axis=2)
        return jnp.concatenate([xy, wh, conf, cls], axis=2)

    def apply(self, params, state, x, training, rng):
        return self._decode(x), state

    # -- loss ----------------------------------------------------------------
    def compute_loss(self, params, x, labels, mask=None):
        """YOLOv2 composite loss; labels [N, 4+C, H, W] (grid units)."""
        labels = jnp.asarray(labels, x.dtype)
        n, _, h, w = x.shape
        b = self.n_anchors
        priors = jnp.asarray(self.boundingBoxPriors, x.dtype)  # [B,2]

        txy, twh, to, logits = self._split(x)
        cls_gt = labels[:, 4:]                      # [N, C, H, W]
        obj = (jnp.sum(cls_gt, axis=1) > 0).astype(x.dtype)  # [N, H, W]

        x1, y1, x2, y2 = (labels[:, 0], labels[:, 1], labels[:, 2],
                          labels[:, 3])             # [N, H, W] grid units
        cx, cy = (x1 + x2) * 0.5, (y1 + y2) * 0.5
        gw, gh = x2 - x1, y2 - y1

        # anchor responsibility: argmax IoU(prior, gt wh), one-hot masked
        wh_gt = jnp.stack([gw, gh], axis=-1)        # [N, H, W, 2]
        iou_a = _anchor_iou(wh_gt, priors)          # [N, H, W, B]
        resp = jax.nn.one_hot(jnp.argmax(iou_a, axis=-1), b,
                              dtype=x.dtype)        # [N, H, W, B]
        resp = jnp.moveaxis(resp, -1, 1) * obj[:, None]      # [N, B, H, W]

        # position: sigmoid(txy) vs cell-relative gt center; sqrt wh.
        # lossPositionScale selects the penalty ("l2" default, "l1")
        pen = (jnp.abs if str(self.lossPositionScale).lower() == "l1"
               else jnp.square)
        tx_gt = jnp.clip(cx - jnp.floor(cx), 0.0, 1.0)
        ty_gt = jnp.clip(cy - jnp.floor(cy), 0.0, 1.0)
        pxy = jax.nn.sigmoid(txy)                   # [N, B, 2, H, W]
        pos = (pen(pxy[:, :, 0] - tx_gt[:, None])
               + pen(pxy[:, :, 1] - ty_gt[:, None]))
        pwh = priors[None, :, :, None, None] * jnp.exp(
            jnp.clip(twh, -10.0, 10.0))             # [N, B, 2, H, W]
        eps = 1e-9
        size = (pen(jnp.sqrt(pwh[:, :, 0] + eps)
                    - jnp.sqrt(jnp.maximum(gw, 0.0) + eps)[:, None])
                + pen(jnp.sqrt(pwh[:, :, 1] + eps)
                      - jnp.sqrt(jnp.maximum(gh, 0.0) + eps)[:, None]))
        loss_pos = self.lambdaCoord * jnp.sum(resp * (pos + size))

        # confidence: responsible anchors target IoU(pred, gt); the rest 0
        cell_x = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
        cell_y = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
        pcx = pxy[:, :, 0] + cell_x                 # [N, B, H, W]
        pcy = pxy[:, :, 1] + cell_y
        inter_w = jnp.maximum(0.0, jnp.minimum(pcx + pwh[:, :, 0] / 2,
                                               (cx + gw / 2)[:, None])
                              - jnp.maximum(pcx - pwh[:, :, 0] / 2,
                                            (cx - gw / 2)[:, None]))
        inter_h = jnp.maximum(0.0, jnp.minimum(pcy + pwh[:, :, 1] / 2,
                                               (cy + gh / 2)[:, None])
                              - jnp.maximum(pcy - pwh[:, :, 1] / 2,
                                            (cy - gh / 2)[:, None]))
        inter = inter_w * inter_h
        union = (pwh[:, :, 0] * pwh[:, :, 1]
                 + (gw * gh)[:, None] - inter)
        iou = inter / jnp.maximum(union, 1e-9)      # [N, B, H, W]
        conf = jax.nn.sigmoid(to)
        loss_conf = (jnp.sum(resp * jnp.square(
            conf - jax.lax.stop_gradient(iou)))
            + self.lambdaNoObj * jnp.sum((1.0 - resp)
                                         * jnp.square(conf)))

        # class predictions on responsible anchors
        probs = jax.nn.softmax(logits, axis=2)      # [N, B, C, H, W]
        if str(self.lossClassPredictions).lower() in ("mcxent",
                                                      "negativeloglikelihood"):
            cls_term = -jnp.sum(
                cls_gt[:, None] * jnp.log(jnp.maximum(probs, 1e-9)), axis=2)
        else:  # L2 on the softmax outputs (reference default)
            cls_term = jnp.sum(jnp.square(probs - cls_gt[:, None]), axis=2)
        loss_cls = jnp.sum(resp * cls_term)

        return (loss_pos + loss_conf + loss_cls) / n

    def infer(self, input_type):
        return input_type
