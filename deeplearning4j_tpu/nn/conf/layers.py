"""Layer configuration classes.

Reference capability: org.deeplearning4j.nn.conf.layers.* (the builder DSL,
SURVEY.md §2.5 "Config DSL") fused with the corresponding runtime impls in
org.deeplearning4j.nn.layers.* ("Layer impls"). The reference splits config
from runtime objects that dispatch per-op JNI calls (SURVEY.md §3.1); here a
layer config IS the runtime: it carries
    init_params(key, dtype)          -> trainable param dict
    init_state(dtype)                -> non-trainable state dict (e.g. BN)
    apply(params, state, x, training, rng) -> (y, new_state)
as pure functions, so a whole network lowers to one jittable step and XLA
does the fusion the reference needed cuDNN platform helpers for (the
LayerHelper seam of SURVEY.md §2.5 is therefore intentionally absent).

Conventions (matching DL4J):
  dense inputs  [N, F]; conv inputs [N, C, H, W]; recurrent inputs [N, C, T].
  dropOut(p) is the RETAIN probability (inverted dropout), as in DL4J.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.autodiff.ops import OPS
from deeplearning4j_tpu.nn.activations import resolve_activation
from deeplearning4j_tpu.nn.losses import resolve_loss
from deeplearning4j_tpu.nn.weights import init_weight
from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalFlatType, ConvolutionalType, FeedForwardType, InputType,
    RecurrentType,
)

LAYER_REGISTRY: dict = {}


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


def _register(cls):
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


class _Builder:
    """Generic DL4J-style builder: any method call sets the same-named config
    field (e.g. .nIn(784).nOut(100).activation("relu")); build() constructs
    the layer class."""

    def __init__(self, cls, **preset):
        self._cls = cls
        self._kw = dict(preset)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)

        def setter(*args):
            self._kw[item] = args[0] if len(args) == 1 else list(args)
            return self

        return setter

    def build(self):
        return self._cls(**self._kw)


class BaseLayer:
    """Common config fields + (de)serialization. Subclasses override
    infer() / init_params() / apply()."""

    # fields every layer inherits from the NeuralNetConfiguration defaults
    # when not set explicitly (reference: NeuralNetConfiguration.Builder
    # global defaults cloned into each layer conf)
    INHERITED = ("activation", "weightInit", "biasInit", "updater", "l1",
                 "l2", "dropOut", "gradientNormalization",
                 "gradientNormalizationThreshold")

    def __init__(self, name=None, activation=None, weightInit=None,
                 biasInit=None, updater=None, l1=None, l2=None, dropOut=None,
                 gradientNormalization=None,
                 gradientNormalizationThreshold=None):
        self.name = name
        self.activation = activation
        self.weightInit = weightInit
        self.biasInit = biasInit
        self.updater = updater
        self.l1 = l1
        self.l2 = l2
        self.dropOut = dropOut
        self.gradientNormalization = gradientNormalization
        self.gradientNormalizationThreshold = gradientNormalizationThreshold

    # -- builder -------------------------------------------------------------
    class _BuilderFactory:
        def __get__(self, obj, cls):
            return lambda **kw: _Builder(cls, **kw)

    Builder = _BuilderFactory()

    def apply_defaults(self, defaults: dict):
        import copy

        for f in self.INHERITED:
            if getattr(self, f, None) is None and f in defaults:
                # deep-copy so layers never share mutable config objects
                # (the reference clones the conf per layer)
                setattr(self, f, copy.deepcopy(defaults[f]))
        if self.activation is None:
            self.activation = "identity"
        if self.weightInit is None:
            self.weightInit = "xavier"
        if self.biasInit is None:
            self.biasInit = 0.0

    # -- shape / params ------------------------------------------------------
    def infer(self, input_type):
        """Set nIn-style fields from input_type; return the output type."""
        return input_type

    def init_params(self, key, dtype=jnp.float32) -> dict:
        return {}

    def init_state(self, dtype=jnp.float32) -> dict:
        return {}

    def apply(self, params, state, x, training, rng):
        return x, state

    def _dropout(self, x, training, rng):
        p = self.dropOut
        if not p or p >= 1.0 or not training or rng is None:
            return x
        keep = jax.random.bernoulli(rng, p, x.shape)
        return jnp.where(keep, x / p, jnp.zeros_like(x))

    def _act(self, x):
        # softmax normalizes the CLASS axis: dim 1 in the DL4J NCW
        # time-series layout [N, C, T] (axis -1 there is time)
        if x.ndim == 3 and self.activation in ("softmax", "logsoftmax"):
            fn = (jax.nn.softmax if self.activation == "softmax"
                  else jax.nn.log_softmax)
            return fn(x, axis=1)
        return resolve_activation(self.activation or "identity")(x)

    # -- serde ---------------------------------------------------------------
    def to_json(self):
        d = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            if k.startswith("_") or v is None:
                continue
            if hasattr(v, "to_json"):
                v = {"__layer__": v.to_json()} if isinstance(
                    v, BaseLayer) else v.to_json()
            elif isinstance(v, tuple):
                v = list(v)
            d[k] = v
        return d

    @staticmethod
    def from_json(d):
        d = dict(d)
        cls = LAYER_REGISTRY[d.pop("@class")]
        for k, v in list(d.items()):
            if isinstance(v, dict) and "__layer__" in v:
                d[k] = BaseLayer.from_json(v["__layer__"])
            elif isinstance(v, dict) and "@class" in v:
                from deeplearning4j_tpu.optimize.updaters import (
                    updater_from_config)

                d[k] = updater_from_config(v)
        return cls(**d)

    def __repr__(self):
        fields = ", ".join(f"{k}={v!r}" for k, v in self.__dict__.items()
                           if v is not None and not k.startswith("_"))
        return f"{type(self).__name__}({fields})"


# ---------------------------------------------------------------------------
# feed-forward layers
# ---------------------------------------------------------------------------

@_register
class DenseLayer(BaseLayer):
    """Reference: conf.layers.DenseLayer + nn.layers.feedforward.dense.
    3-D input [N, C, T] is handled natively (per-timestep linear) instead of
    the reference's RnnToFeedForwardPreProcessor reshape round-trip."""

    def __init__(self, nIn=None, nOut=None, hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.hasBias = hasBias

    def infer(self, input_type):
        if isinstance(input_type, RecurrentType):
            self.nIn = self.nIn or input_type.size
            return InputType.recurrent(self.nOut, input_type.timeSeriesLength)
        self.nIn = self.nIn or input_type.arrayElementsPerExample()
        return InputType.feedForward(self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        if self.nIn is None or self.nOut is None:
            raise ValueError(
                f"{type(self).__name__} has nIn={self.nIn}, nOut={self.nOut}:"
                f" set nIn explicitly or declare setInputType on the config")
        kw, kb = jax.random.split(key)
        p = {"W": init_weight(self.weightInit, kw, (self.nIn, self.nOut),
                              self.nIn, self.nOut, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit, dtype)
        return p

    def _linear(self, params, x):
        if x.ndim == 3:  # [N, C, T]: contract the channel axis per timestep
            y = jnp.einsum("nct,ch->nht", x, params["W"])
            if self.hasBias:
                y = y + params["b"][None, :, None]
            return y
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        y = x @ params["W"]
        if self.hasBias:
            y = y + params["b"]
        return y

    def apply(self, params, state, x, training, rng):
        x = self._dropout(x, training, rng)
        return self._act(self._linear(params, x)), state


@_register
class EmbeddingLayer(BaseLayer):
    """Reference: conf.layers.EmbeddingLayer — int indices [N] or [N,1] (or
    one-hot [N, nIn]) -> [N, nOut]. Lookup is a gather, which XLA lowers to
    a dynamic-slice-friendly form on TPU."""

    def __init__(self, nIn=None, nOut=None, hasBias=False, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.hasBias = hasBias

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.arrayElementsPerExample()
        return InputType.feedForward(self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        p = {"W": init_weight(self.weightInit, key, (self.nIn, self.nOut),
                              self.nIn, self.nOut, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        if jnp.issubdtype(x.dtype, jnp.floating) and x.ndim == 2 \
                and x.shape[-1] == self.nIn:
            y = x @ params["W"]  # one-hot path
        else:
            idx = x.astype(jnp.int32)
            if idx.ndim == 2 and idx.shape[-1] == 1:
                idx = idx[:, 0]
            y = params["W"][idx]
        if self.hasBias:
            y = y + params["b"]
        return self._act(y), state


@_register
class EmbeddingSequenceLayer(EmbeddingLayer):
    """[N, T] int tokens -> [N, nOut, T] (recurrent layout)."""

    def infer(self, input_type):
        if self.nIn is None and isinstance(input_type, RecurrentType):
            self.nIn = input_type.size
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(self.nOut, t)

    def apply(self, params, state, x, training, rng):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3:  # [N, 1, T]
            idx = idx[:, 0, :]
        y = params["W"][idx]              # [N, T, nOut]
        if self.hasBias:
            y = y + params["b"]
        return self._act(jnp.moveaxis(y, 1, 2)), state  # [N, nOut, T]


# ---------------------------------------------------------------------------
# convolutional layers
# ---------------------------------------------------------------------------

class ConvolutionMode:
    TRUNCATE = "truncate"
    SAME = "same"


@_register
class ConvolutionLayer(BaseLayer):
    """Reference: conf.layers.ConvolutionLayer + nn.layers.convolution.
    One lax.conv_general_dilated call replaces im2col.cu + the cuDNN platform
    helper (SURVEY.md §2.1/§2.8 item 4-5); weights are OIHW like DL4J."""

    def __init__(self, nIn=None, nOut=None, kernelSize=(3, 3), stride=(1, 1),
                 padding=(0, 0), dilation=(1, 1), convolutionMode=None,
                 hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.kernelSize = _pair(kernelSize)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.dilation = _pair(dilation)
        self.convolutionMode = convolutionMode or ConvolutionMode.TRUNCATE
        self.hasBias = hasBias

    def _same(self):
        return self.convolutionMode == ConvolutionMode.SAME

    def infer(self, input_type):
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(
                f"ConvolutionLayer needs convolutional input, got {input_type}")
        self.nIn = self.nIn or input_type.channels
        kh, kw = self.kernelSize
        sh, sw = self.stride
        ph, pw = self.padding
        dh, dw = self.dilation
        ekh, ekw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
        if self._same():
            oh = -(-input_type.height // sh)
            ow = -(-input_type.width // sw)
        else:
            oh = (input_type.height + 2 * ph - ekh) // sh + 1
            ow = (input_type.width + 2 * pw - ekw) // sw + 1
        return InputType.convolutional(oh, ow, self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = self.kernelSize
        fan_in = self.nIn * kh * kw
        fan_out = self.nOut * kh * kw
        k1, k2 = jax.random.split(key)
        p = {"W": init_weight(self.weightInit, k1,
                              (self.nOut, self.nIn, kh, kw),
                              fan_in, fan_out, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        x = self._dropout(x, training, rng)
        y = OPS["conv2d"](x, params["W"], params.get("b"),
                          strides=self.stride, padding=self.padding,
                          dilation=self.dilation, sameMode=self._same())
        return self._act(y), state


@_register
class Convolution1DLayer(BaseLayer):
    """Input [N, C, T]."""

    def __init__(self, nIn=None, nOut=None, kernelSize=3, stride=1, padding=0,
                 convolutionMode=None, hasBias=True, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.kernelSize = int(kernelSize) if not isinstance(
            kernelSize, (list, tuple)) else int(kernelSize[0])
        self.stride = int(stride) if not isinstance(
            stride, (list, tuple)) else int(stride[0])
        self.padding = int(padding) if not isinstance(
            padding, (list, tuple)) else int(padding[0])
        self.convolutionMode = convolutionMode or ConvolutionMode.TRUNCATE
        self.hasBias = hasBias

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        t = getattr(input_type, "timeSeriesLength", None)
        if t is not None:
            if self.convolutionMode == ConvolutionMode.SAME:
                t = -(-t // self.stride)
            else:
                t = (t + 2 * self.padding - self.kernelSize) // self.stride + 1
        return InputType.recurrent(self.nOut, t)

    def init_params(self, key, dtype=jnp.float32):
        fan_in = self.nIn * self.kernelSize
        fan_out = self.nOut * self.kernelSize
        p = {"W": init_weight(self.weightInit, key,
                              (self.nOut, self.nIn, self.kernelSize),
                              fan_in, fan_out, dtype)}
        if self.hasBias:
            p["b"] = jnp.zeros((self.nOut,), dtype)
        return p

    def apply(self, params, state, x, training, rng):
        y = OPS["conv1d"](x, params["W"], params.get("b"), stride=self.stride,
                          padding=self.padding,
                          sameMode=self.convolutionMode == ConvolutionMode.SAME)
        return self._act(y), state


@_register
class SeparableConvolution2D(ConvolutionLayer):
    """Depthwise (depthMultiplier) + pointwise, as in the reference's
    SeparableConvolution2D."""

    def __init__(self, depthMultiplier=1, **kw):
        super().__init__(**kw)
        self.depthMultiplier = depthMultiplier

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = self.kernelSize
        k1, k2 = jax.random.split(key)
        fan_d = self.nIn * kh * kw
        p = {
            "dW": init_weight(self.weightInit, k1,
                              (self.depthMultiplier, self.nIn, kh, kw),
                              fan_d, self.depthMultiplier * kh * kw, dtype),
            "pW": init_weight(self.weightInit, k2,
                              (self.nOut, self.nIn * self.depthMultiplier,
                               1, 1),
                              self.nIn * self.depthMultiplier, self.nOut,
                              dtype),
        }
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        x = self._dropout(x, training, rng)
        y = OPS["depthwiseConv2d"](x, params["dW"], None,
                                   strides=self.stride, padding=self.padding,
                                   dilation=self.dilation,
                                   sameMode=self._same())
        y = OPS["conv2d"](y, params["pW"], params.get("b"))
        return self._act(y), state


@_register
class DepthwiseConvolution2D(ConvolutionLayer):
    """Depthwise-only convolution (reference:
    conf.layers.DepthwiseConvolution2D): each input channel convolves
    with depthMultiplier filters of its own; nOut = nIn *
    depthMultiplier."""

    def __init__(self, depthMultiplier=1, **kw):
        super().__init__(**kw)
        self.depthMultiplier = int(depthMultiplier)

    def infer(self, input_type):
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(
                f"DepthwiseConvolution2D needs convolutional input, got "
                f"{input_type}")
        self.nIn = self.nIn or input_type.channels
        self.nOut = self.nIn * self.depthMultiplier
        # spatial math (incl. dilation) delegates to the base conv infer
        return super().infer(input_type)

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = self.kernelSize
        fan_in = self.nIn * kh * kw
        # Keras DepthwiseConv2D bias flattening is (in, mult) — the same
        # c*depthMultiplier + m ordering the depthwiseConv2d op emits, so
        # imported biases install without a permute.
        p = {"W": init_weight(self.weightInit, key,
                              (self.depthMultiplier, self.nIn, kh, kw),
                              fan_in, self.depthMultiplier * kh * kw,
                              dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        x = self._dropout(x, training, rng)
        y = OPS["depthwiseConv2d"](x, params["W"], params.get("b"),
                                   strides=self.stride,
                                   padding=self.padding,
                                   dilation=self.dilation,
                                   sameMode=self._same())
        return self._act(y), state


@_register
class Deconvolution2D(ConvolutionLayer):
    def infer(self, input_type):
        self.nIn = self.nIn or input_type.channels
        kh, kw = self.kernelSize
        sh, sw = self.stride
        ph, pw = self.padding
        if self._same():
            oh, ow = input_type.height * sh, input_type.width * sw
        else:
            oh = sh * (input_type.height - 1) + kh - 2 * ph
            ow = sw * (input_type.width - 1) + kw - 2 * pw
        return InputType.convolutional(oh, ow, self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        kh, kw = self.kernelSize
        p = {"W": init_weight(self.weightInit, key,
                              (self.nOut, self.nIn, kh, kw),
                              self.nIn * kh * kw, self.nOut * kh * kw, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        y = OPS["deconv2d"](x, params["W"], params.get("b"),
                            strides=self.stride, padding=self.padding,
                            sameMode=self._same())
        return self._act(y), state


class PoolingType:
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


@_register
class SubsamplingLayer(BaseLayer):
    """Reference: conf.layers.SubsamplingLayer (max/avg pooling)."""

    def __init__(self, poolingType=PoolingType.MAX, kernelSize=(2, 2),
                 stride=(2, 2), padding=(0, 0), convolutionMode=None, **kw):
        super().__init__(**kw)
        self.poolingType = poolingType
        self.kernelSize = _pair(kernelSize)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.convolutionMode = convolutionMode or ConvolutionMode.TRUNCATE

    def infer(self, input_type):
        kh, kw = self.kernelSize
        sh, sw = self.stride
        ph, pw = self.padding
        if self.convolutionMode == ConvolutionMode.SAME:
            oh = -(-input_type.height // sh)
            ow = -(-input_type.width // sw)
        else:
            oh = (input_type.height + 2 * ph - kh) // sh + 1
            ow = (input_type.width + 2 * pw - kw) // sw + 1
        return InputType.convolutional(oh, ow, input_type.channels)

    def apply(self, params, state, x, training, rng):
        same = self.convolutionMode == ConvolutionMode.SAME
        if self.poolingType == PoolingType.MAX:
            y = OPS["maxPooling2d"](x, kernel=self.kernelSize,
                                    strides=self.stride,
                                    padding=self.padding, sameMode=same)
        else:
            y = OPS["avgPooling2d"](x, kernel=self.kernelSize,
                                    strides=self.stride,
                                    padding=self.padding, sameMode=same)
        return y, state


@_register
class Subsampling1DLayer(BaseLayer):
    def __init__(self, poolingType=PoolingType.MAX, kernelSize=2, stride=2,
                 padding=0, **kw):
        super().__init__(**kw)
        self.poolingType = poolingType
        self.kernelSize = int(kernelSize)
        self.stride = int(stride)
        self.padding = int(padding)

    def infer(self, input_type):
        t = getattr(input_type, "timeSeriesLength", None)
        if t is not None:
            t = (t + 2 * self.padding - self.kernelSize) // self.stride + 1
        return InputType.recurrent(input_type.size, t)

    def apply(self, params, state, x, training, rng):
        pad = ((0, 0), (0, 0), (self.padding, self.padding))
        window = (1, 1, self.kernelSize)
        strides = (1, 1, self.stride)
        if self.poolingType == PoolingType.MAX:
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            c = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                  strides, pad)
            y = s / c
        return y, state


@_register
class BatchNormalization(BaseLayer):
    """Reference: conf.layers.BatchNormalization + nn.layers.normalization.
    Running stats live in the layer STATE dict and are updated in the
    compiled train step (no host round-trip); per-channel for conv input,
    per-feature for dense."""

    def __init__(self, nIn=None, nOut=None, decay=0.9, eps=1e-5, gamma=1.0,
                 beta=0.0, lockGammaBeta=False, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.decay = decay
        self.eps = eps
        self.gamma = gamma
        self.beta = beta
        self.lockGammaBeta = lockGammaBeta

    def infer(self, input_type):
        if isinstance(input_type, (ConvolutionalType, RecurrentType)):
            # per-channel stats for conv [N,C,H,W] and recurrent [N,C,T]
            self.nIn = self.nIn or getattr(input_type, "channels",
                                           getattr(input_type, "size", None))
        else:
            self.nIn = self.nIn or input_type.arrayElementsPerExample()
        self.nOut = self.nIn
        return input_type

    def init_params(self, key, dtype=jnp.float32):
        if self.lockGammaBeta:
            return {}
        return {"gamma": jnp.full((self.nIn,), self.gamma, dtype),
                "beta": jnp.full((self.nIn,), self.beta, dtype)}

    def init_state(self, dtype=jnp.float32):
        return {"mean": jnp.zeros((self.nIn,), dtype),
                "var": jnp.ones((self.nIn,), dtype)}

    def apply(self, params, state, x, training, rng):
        axes = tuple(i for i in range(x.ndim) if i != 1) if x.ndim > 2 \
            else (0,)
        shape = [1] * x.ndim
        shape[1 if x.ndim > 2 else -1] = -1
        if training:
            # Stats strategy by activation dtype:
            # - bf16/f16: ONE-PASS E[x^2]-mean^2 with f32 accumulators —
            #   reads x once instead of twice (+9% ResNet-50 bf16 train
            #   throughput on v5e, tools/probe_resnet.py --bn onepass);
            #   any mean>>std cancellation is below the activations' own
            #   quantization noise at these dtypes.
            # - f32: TWO-PASS centered stats — one-pass cancels
            #   catastrophically at mean>>std (guarded by
            #   tests/test_nn.py::TestBatchNormNumerics).
            # mean/var STAY f32 through the rsqrt — they are tiny
            # per-channel vectors, and quantizing them to bf16 before
            # adding eps would absorb eps entirely.
            low_prec = x.dtype in (jnp.bfloat16, jnp.float16)
            xf = x.astype(jnp.float32) if low_prec else x
            mean = jnp.mean(xf, axis=axes)
            if low_prec:
                var = jnp.maximum(
                    jnp.mean(jnp.square(xf), axis=axes)
                    - jnp.square(mean), 0.0)
            else:
                var = jnp.mean(
                    jnp.square(xf - mean.reshape(shape)), axis=axes)
            sdt = state["mean"].dtype
            new_state = {
                "mean": self.decay * state["mean"]
                + (1 - self.decay) * mean.astype(sdt),
                "var": self.decay * state["var"]
                + (1 - self.decay) * var.astype(sdt),
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        xn = (x - mean.reshape(shape).astype(x.dtype)) * lax.rsqrt(
            var.reshape(shape) + self.eps).astype(x.dtype)
        if not self.lockGammaBeta:
            xn = xn * params["gamma"].reshape(shape) \
                + params["beta"].reshape(shape)
        return self._act(xn), new_state


@_register
class LocalResponseNormalization(BaseLayer):
    def __init__(self, k=2.0, n=5, alpha=1e-4, beta=0.75, **kw):
        super().__init__(**kw)
        self.k = k
        self.n = int(n)
        self.alpha = alpha
        self.beta = beta

    def apply(self, params, state, x, training, rng):
        sq = x * x
        half = self.n // 2
        # sum over a window of channels: pad C then reduce_window on axis 1
        window = (1, self.n, 1, 1)
        pad = ((0, 0), (half, half), (0, 0), (0, 0))
        s = lax.reduce_window(sq, 0.0, lax.add, window, (1, 1, 1, 1), pad)
        return x / (self.k + self.alpha * s) ** self.beta, state


@_register
class ZeroPaddingLayer(BaseLayer):
    def __init__(self, padding=(1, 1), **kw):
        super().__init__(**kw)
        p = padding
        if isinstance(p, int):
            p = (p, p, p, p)
        elif len(p) == 2:
            p = (p[0], p[0], p[1], p[1])
        self.padding = tuple(int(v) for v in p)  # top,bottom,left,right

    def infer(self, input_type):
        t, b, l, r = self.padding
        return InputType.convolutional(input_type.height + t + b,
                                       input_type.width + l + r,
                                       input_type.channels)

    def apply(self, params, state, x, training, rng):
        t, b, l, r = self.padding
        return jnp.pad(x, ((0, 0), (0, 0), (t, b), (l, r))), state


@_register
class Upsampling2D(BaseLayer):
    def __init__(self, size=(2, 2), **kw):
        super().__init__(**kw)
        self.size = _pair(size)

    def infer(self, input_type):
        return InputType.convolutional(input_type.height * self.size[0],
                                       input_type.width * self.size[1],
                                       input_type.channels)

    def apply(self, params, state, x, training, rng):
        return OPS["upsampling2d"](x, size=self.size), state


@_register
class SpaceToDepth(BaseLayer):
    """[N,C,H,W] -> [N, C*b*b, H/b, W/b] (reference:
    conf.layers.SpaceToDepthLayer — the YOLO2 'reorg' passthrough)."""

    def __init__(self, blockSize=2, **kw):
        super().__init__(**kw)
        self.blockSize = int(blockSize)

    def infer(self, input_type):
        bsz = self.blockSize
        if input_type.height % bsz or input_type.width % bsz:
            raise ValueError(
                f"SpaceToDepth(blockSize={bsz}) needs spatial dims "
                f"divisible by the block, got "
                f"{input_type.height}x{input_type.width}")
        return InputType.convolutional(input_type.height // bsz,
                                       input_type.width // bsz,
                                       input_type.channels * bsz * bsz)

    def apply(self, params, state, x, training, rng):
        n, c, h, w = x.shape
        bsz = self.blockSize
        x = x.reshape(n, c, h // bsz, bsz, w // bsz, bsz)
        x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
        return x.reshape(n, c * bsz * bsz, h // bsz, w // bsz), state


@_register
class DepthToSpace(BaseLayer):
    """[N, C*b*b, H, W] -> [N, C, H*b, W*b] (inverse of SpaceToDepth)."""

    def __init__(self, blockSize=2, **kw):
        super().__init__(**kw)
        self.blockSize = int(blockSize)

    def infer(self, input_type):
        bsz = self.blockSize
        if input_type.channels % (bsz * bsz):
            raise ValueError(
                f"DepthToSpace(blockSize={bsz}) needs channels divisible "
                f"by block^2, got {input_type.channels}")
        return InputType.convolutional(input_type.height * bsz,
                                       input_type.width * bsz,
                                       input_type.channels // (bsz * bsz))

    def apply(self, params, state, x, training, rng):
        n, c, h, w = x.shape
        bsz = self.blockSize
        cout = c // (bsz * bsz)
        x = x.reshape(n, bsz, bsz, cout, h, w)
        x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
        return x.reshape(n, cout, h * bsz, w * bsz), state


@_register
class GlobalPoolingLayer(BaseLayer):
    """[N,C,H,W] -> [N,C] or [N,C,T] -> [N,C]."""

    def __init__(self, poolingType=PoolingType.AVG, **kw):
        super().__init__(**kw)
        self.poolingType = poolingType

    def infer(self, input_type):
        if isinstance(input_type, ConvolutionalType):
            return InputType.feedForward(input_type.channels)
        if isinstance(input_type, RecurrentType):
            return InputType.feedForward(input_type.size)
        return input_type

    def apply(self, params, state, x, training, rng):
        axes = tuple(range(2, x.ndim))
        if self.poolingType == PoolingType.MAX:
            return jnp.max(x, axis=axes), state
        if self.poolingType == PoolingType.SUM:
            return jnp.sum(x, axis=axes), state
        return jnp.mean(x, axis=axes), state


@_register
class DropoutLayer(BaseLayer):
    def __init__(self, dropOut=0.5, **kw):
        kw["dropOut"] = dropOut
        super().__init__(**kw)

    def apply(self, params, state, x, training, rng):
        return self._dropout(x, training, rng), state


@_register
class ActivationLayer(BaseLayer):
    def apply(self, params, state, x, training, rng):
        return self._act(x), state


# ---------------------------------------------------------------------------
# recurrent layers
# ---------------------------------------------------------------------------

@_register
class LSTM(BaseLayer):
    """Reference: conf.layers.LSTM + nn.layers.recurrent.LSTM (and the cuDNN
    LSTM helper, SURVEY.md §2.5). The recurrence is a lax.scan — one fused
    XLA while loop with weights resident in VMEM across steps, replacing the
    per-timestep JNI dispatch + cuDNN path (SURVEY.md §7 hard part 3).
    Input/output layout [N, C, T]."""

    def __init__(self, nIn=None, nOut=None, forgetGateBiasInit=1.0, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.forgetGateBiasInit = forgetGateBiasInit
        if self.activation is None:
            self.activation = "tanh"

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(self.nOut, t)

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        h = self.nOut
        return {
            "W": init_weight(self.weightInit, k1, (self.nIn, 4 * h),
                             self.nIn, h, dtype),
            "R": init_weight(self.weightInit, k2, (h, 4 * h), h, h, dtype),
            "b": jnp.zeros((4 * h,), dtype),
        }

    IS_RECURRENT = True

    def apply(self, params, state, x, training, rng):
        """When `state` carries {"h","c"} (streaming rnnTimeStep or a TBPTT
        segment, SURVEY.md §2.5 TBPTT row), the recurrence starts from it
        and the updated state is returned; otherwise zero-init stateless."""
        x = self._dropout(x, training, rng)
        h0 = state.get("h") if isinstance(state, dict) else None
        c0 = state.get("c") if isinstance(state, dict) else None
        out, hT, cT = OPS["lstmLayer"](
            x, params["W"], params["R"], params["b"], h0=h0, c0=c0,
            forgetBias=self.forgetGateBiasInit)
        if h0 is not None:
            return out, {"h": hT, "c": cT}
        return out, state

    def streaming_state(self, batch_size, dtype=jnp.float32):
        """Zero carried state for rnnTimeStep / TBPTT segments."""
        h = jnp.zeros((batch_size, self.nOut), dtype)
        return {"h": h, "c": jnp.zeros_like(h)}


@_register
class GravesLSTM(LSTM):
    """Kept for config parity; peephole connections are dropped (the
    reference deprecated GravesLSTM in favor of LSTM for the same reason
    cuDNN did not support them)."""


@_register
class GRU(BaseLayer):
    """Gated recurrent unit (reference: conf.layers.recurrent.GRU /
    libnd4j gruCell+gruLayer declarables, SURVEY.md §2.1). Backed by the
    gruLayer op (input projection hoisted to one MXU matmul; Pallas
    recurrence kernel on TPU when shapes allow). resetAfter=False (the
    default, matching the reference's gruCell/gruLayer classic Cho et
    al. reset-before form with a 3H input bias); True is the
    cuDNN/Keras-v2 convention (b holds [3H input || 3H recurrent]),
    which the Keras importer selects explicitly from reset_after."""

    def __init__(self, nIn=None, nOut=None, resetAfter=False, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.resetAfter = resetAfter
        if self.activation is None:
            self.activation = "tanh"

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(self.nOut, t)

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        h = self.nOut
        nb = 6 * h if self.resetAfter else 3 * h
        return {
            "W": init_weight(self.weightInit, k1, (self.nIn, 3 * h),
                             self.nIn, h, dtype),
            "R": init_weight(self.weightInit, k2, (h, 3 * h), h, h, dtype),
            "b": jnp.zeros((nb,), dtype),
        }

    IS_RECURRENT = True

    def apply(self, params, state, x, training, rng):
        x = self._dropout(x, training, rng)
        h0 = state.get("h") if isinstance(state, dict) else None
        out, hT = OPS["gruLayer"](x, params["W"], params["R"],
                                  params["b"], h0=h0,
                                  resetAfter=self.resetAfter,
                                  activation=self.activation)
        if h0 is not None:
            return out, {"h": hT}
        return out, state

    def streaming_state(self, batch_size, dtype=jnp.float32):
        return {"h": jnp.zeros((batch_size, self.nOut), dtype)}


@_register
class SimpleRnn(BaseLayer):
    def __init__(self, nIn=None, nOut=None, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        if self.activation is None:
            self.activation = "tanh"

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(self.nOut, t)

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        return {
            "W": init_weight(self.weightInit, k1, (self.nIn, self.nOut),
                             self.nIn, self.nOut, dtype),
            "R": init_weight(self.weightInit, k2, (self.nOut, self.nOut),
                             self.nOut, self.nOut, dtype),
            "b": jnp.zeros((self.nOut,), dtype),
        }

    IS_RECURRENT = True

    def apply(self, params, state, x, training, rng):
        h0 = state.get("h") if isinstance(state, dict) else None
        out, hT = OPS["simpleRnnLayer"](x, params["W"], params["R"],
                                        params["b"], h0=h0,
                                        activation=self.activation)
        if h0 is not None:
            return out, {"h": hT}
        return out, state

    def streaming_state(self, batch_size, dtype=jnp.float32):
        return {"h": jnp.zeros((batch_size, self.nOut), dtype)}


@_register
class Bidirectional(BaseLayer):
    """Wrapper running the sub-layer forward and on time-reversed input.
    Reference: conf.layers.recurrent.Bidirectional (modes CONCAT/ADD/
    AVERAGE/MUL)."""

    CONCAT, ADD, AVERAGE, MUL = "concat", "add", "average", "mul"

    def __init__(self, rnn=None, mode="concat", **kw):
        super().__init__(**kw)
        self.rnn = rnn
        self.mode = mode

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        self.rnn.apply_defaults(defaults)

    def infer(self, input_type):
        out = self.rnn.infer(input_type)
        size = out.size * 2 if self.mode == self.CONCAT else out.size
        return InputType.recurrent(size, getattr(out, "timeSeriesLength",
                                                 None))

    def init_params(self, key, dtype=jnp.float32):
        kf, kb = jax.random.split(key)
        return {"fwd": self.rnn.init_params(kf, dtype),
                "bwd": self.rnn.init_params(kb, dtype)}

    def apply(self, params, state, x, training, rng):
        yf, _ = self.rnn.apply(params["fwd"], {}, x, training, rng)
        yb, _ = self.rnn.apply(params["bwd"], {}, x[..., ::-1], training, rng)
        yb = yb[..., ::-1]
        if self.mode == self.CONCAT:
            return jnp.concatenate([yf, yb], axis=1), state
        if self.mode == self.ADD:
            return yf + yb, state
        if self.mode == self.MUL:
            return yf * yb, state
        return (yf + yb) / 2.0, state


@_register
class LastTimeStep(BaseLayer):
    """Wrapper: [N, C, T] -> [N, C] taking the final timestep."""

    def __init__(self, rnn=None, **kw):
        super().__init__(**kw)
        self.rnn = rnn

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        if self.rnn is not None:
            self.rnn.apply_defaults(defaults)

    def infer(self, input_type):
        out = self.rnn.infer(input_type)
        return InputType.feedForward(out.size)

    def init_params(self, key, dtype=jnp.float32):
        return self.rnn.init_params(key, dtype)

    def init_state(self, dtype=jnp.float32):
        return self.rnn.init_state(dtype)

    def apply(self, params, state, x, training, rng):
        y, state = self.rnn.apply(params, state, x, training, rng)
        return y[..., -1], state


# ---------------------------------------------------------------------------
# output layers
# ---------------------------------------------------------------------------

class BaseOutputLayer(DenseLayer):
    def __init__(self, lossFunction="mcxent", **kw):
        super().__init__(**kw)
        self.lossFunction = lossFunction
        # remember whether the user set the activation explicitly so a
        # global .activation(...) default can propagate (DL4J semantics:
        # softmax is the fallback only when NO global default exists)
        self._explicit_activation = self.activation is not None
        if self.activation is None:
            self.activation = "softmax"

    def apply_defaults(self, defaults):
        if (not getattr(self, "_explicit_activation", True)
                and defaults.get("activation") is not None):
            self.activation = defaults["activation"]
        super().apply_defaults(defaults)

    def pre_output(self, params, x):
        return self._linear(params, x)

    def compute_loss(self, params, x, labels, mask=None):
        pre = self.pre_output(params, x)
        return resolve_loss(self.lossFunction)(
            labels, pre, self.activation, mask)


@_register
class OutputLayer(BaseOutputLayer):
    """Reference: conf.layers.OutputLayer (dense + loss)."""


@_register
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep output over [N, C, T]."""

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        return InputType.recurrent(self.nOut,
                                   getattr(input_type, "timeSeriesLength",
                                           None))

    def apply(self, params, state, x, training, rng):
        return self._act(self._linear(params, x)), state


@_register
class LossLayer(BaseLayer):
    """No params: input is already the pre-output."""

    def __init__(self, lossFunction="mcxent", **kw):
        super().__init__(**kw)
        self.lossFunction = lossFunction
        if self.activation is None:
            self.activation = "softmax"

    def pre_output(self, params, x):
        return x

    def compute_loss(self, params, x, labels, mask=None):
        return resolve_loss(self.lossFunction)(
            labels, x, self.activation, mask)

    def apply(self, params, state, x, training, rng):
        return self._act(x), state


OUTPUT_LAYER_TYPES = (BaseOutputLayer, LossLayer)
