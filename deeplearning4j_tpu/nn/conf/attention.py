"""Attention layers over recurrent activations.

Reference capability: the DL4J attention layer family added in 1.0.0-beta4
(org.deeplearning4j.nn.conf.layers.{SelfAttentionLayer,
LearnedSelfAttentionLayer, RecurrentAttentionLayer} and
org.deeplearning4j.nn.conf.graph.AttentionVertex), all built on the
nd4j `multiHeadDotProductAttention` declarable op (SURVEY.md §5
"long-context" row). Layout contract matches the reference: activations
are DL4J time-series [N, C, T]; attention math runs in [N, T, C] and
maps onto the registered OPS (one fused XLA softmax-matmul chain instead
of the reference's per-op dispatch).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.autodiff.ops import OPS
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BaseLayer, _register
from deeplearning4j_tpu.nn.weights import init_weight


def _mh_params(key, n_in, n_heads, head_size, n_out, weight_init, dtype):
    ks = jax.random.split(key, 4)
    proj = n_heads * head_size
    return {
        "Wq": init_weight(weight_init, ks[0], (n_in, proj), n_in, proj,
                          dtype),
        "Wk": init_weight(weight_init, ks[1], (n_in, proj), n_in, proj,
                          dtype),
        "Wv": init_weight(weight_init, ks[2], (n_in, proj), n_in, proj,
                          dtype),
        "Wo": init_weight(weight_init, ks[3], (proj, n_out), proj, n_out,
                          dtype),
    }


@_register
class SelfAttentionLayer(BaseLayer):
    """Multi-head dot-product SELF attention: every timestep attends over
    the whole sequence (reference: conf.layers.SelfAttentionLayer).
    projectInput=False runs raw single-head attention (nOut == nIn)."""

    def __init__(self, nIn=None, nOut=None, nHeads=1, headSize=None,
                 projectInput=True, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.nHeads = int(nHeads)
        self.headSize = headSize
        self.projectInput = projectInput

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        if not self.projectInput:
            if self.nHeads != 1:
                raise ValueError("projectInput=False requires nHeads=1")
            self.nOut = self.nIn
        elif self.nOut is None:
            raise ValueError("SelfAttentionLayer needs nOut when "
                             "projectInput=True")
        if self.headSize is None:
            if self.projectInput and self.nOut % self.nHeads:
                raise ValueError(
                    f"nOut={self.nOut} not divisible by nHeads="
                    f"{self.nHeads}: set headSize explicitly")
            self.headSize = (self.nOut // self.nHeads if self.projectInput
                             else self.nIn)
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(self.nOut, t)

    def init_params(self, key, dtype=jnp.float32):
        if not self.projectInput:
            return {}
        return _mh_params(key, self.nIn, self.nHeads, self.headSize,
                          self.nOut, self.weightInit, dtype)

    def apply(self, params, state, x, training, rng):
        xt = jnp.swapaxes(x, 1, 2)               # [N, T, C]
        if self.projectInput:
            y = OPS["multiHeadDotProductAttention"](
                xt, xt, xt, params["Wq"], params["Wk"], params["Wv"],
                params["Wo"], numHeads=self.nHeads)
        else:
            y = OPS["dotProductAttention"](xt, xt, xt)
        # activation AFTER the swap back: _act's softmax path assumes the
        # DL4J [N, C, T] layout (class axis = 1)
        return self._act(jnp.swapaxes(y, 1, 2)), state


@_register
class LearnedSelfAttentionLayer(BaseLayer):
    """Attention with LEARNED query vectors: pools a variable-length
    sequence into a fixed nQueries-step output (reference:
    conf.layers.LearnedSelfAttentionLayer)."""

    def __init__(self, nIn=None, nOut=None, nHeads=1, headSize=None,
                 nQueries=1, projectInput=True, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        self.nHeads = int(nHeads)
        self.headSize = headSize
        self.nQueries = int(nQueries)
        self.projectInput = projectInput

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        if not self.projectInput:
            if self.nHeads != 1:
                raise ValueError("projectInput=False requires nHeads=1")
            self.nOut = self.nIn
        elif self.nOut is None:
            raise ValueError("LearnedSelfAttentionLayer needs nOut when "
                             "projectInput=True")
        if self.headSize is None:
            if self.projectInput and self.nOut % self.nHeads:
                raise ValueError(
                    f"nOut={self.nOut} not divisible by nHeads="
                    f"{self.nHeads}: set headSize explicitly")
            self.headSize = (self.nOut // self.nHeads if self.projectInput
                             else self.nIn)
        return InputType.recurrent(self.nOut, self.nQueries)

    def init_params(self, key, dtype=jnp.float32):
        kq, kp = jax.random.split(key)
        p = {} if not self.projectInput else _mh_params(
            kq, self.nIn, self.nHeads, self.headSize, self.nOut,
            self.weightInit, dtype)
        p["Q"] = init_weight(self.weightInit, kp,
                             (self.nQueries, self.nIn), self.nIn,
                             self.nQueries, dtype)
        return p

    def apply(self, params, state, x, training, rng):
        xt = jnp.swapaxes(x, 1, 2)               # [N, T, C]
        q = jnp.broadcast_to(params["Q"],
                             (xt.shape[0],) + params["Q"].shape)
        if self.projectInput:
            y = OPS["multiHeadDotProductAttention"](
                q, xt, xt, params["Wq"], params["Wk"], params["Wv"],
                params["Wo"], numHeads=self.nHeads)
        else:
            y = OPS["dotProductAttention"](q, xt, xt)
        return self._act(jnp.swapaxes(y, 1, 2)), state


@_register
class RecurrentAttentionLayer(BaseLayer):
    """Recurrent cell with per-timestep attention over the FULL input
    sequence (reference: conf.layers.RecurrentAttentionLayer — an RNN
    whose step input is augmented with an attention readout queried by
    the previous hidden state). Lowered to one lax.scan, the XLA
    analogue of the reference's per-step while loop."""

    IS_RECURRENT = True

    def __init__(self, nIn=None, nOut=None, **kw):
        super().__init__(**kw)
        self.nIn = nIn
        self.nOut = nOut
        if self.activation is None:
            self.activation = "tanh"

    def infer(self, input_type):
        self.nIn = self.nIn or input_type.size
        t = getattr(input_type, "timeSeriesLength", None)
        return InputType.recurrent(self.nOut, t)

    def init_params(self, key, dtype=jnp.float32):
        ks = jax.random.split(key, 4)
        return {
            "W": init_weight(self.weightInit, ks[0],
                             (self.nIn, self.nOut), self.nIn, self.nOut,
                             dtype),
            "R": init_weight(self.weightInit, ks[1],
                             (self.nOut, self.nOut), self.nOut, self.nOut,
                             dtype),
            "A": init_weight(self.weightInit, ks[2],
                             (self.nIn, self.nOut), self.nIn, self.nOut,
                             dtype),
            "Wq": init_weight(self.weightInit, ks[3],
                              (self.nOut, self.nIn), self.nOut, self.nIn,
                              dtype),
            "b": jnp.zeros((self.nOut,), dtype),
        }

    def apply(self, params, state, x, training, rng):
        from deeplearning4j_tpu.nn.activations import resolve_activation

        act = resolve_activation(self.activation)
        xt = jnp.swapaxes(x, 1, 2)               # [N, T, C]
        n = xt.shape[0]
        h0 = state.get("h") if isinstance(state, dict) and state else None
        if h0 is None:
            h0 = jnp.zeros((n, self.nOut), xt.dtype)

        def step(h, x_t):
            q = (h @ params["Wq"])[:, None, :]   # [N, 1, C]
            a = OPS["dotProductAttention"](q, xt, xt)[:, 0]  # [N, C]
            h_new = act(x_t @ params["W"] + a @ params["A"]
                        + h @ params["R"] + params["b"])
            return h_new, h_new

        hT, hs = lax.scan(step, h0, jnp.swapaxes(xt, 0, 1))
        y = jnp.transpose(hs, (1, 2, 0))         # [N, nOut, T]
        if isinstance(state, dict) and state:
            return y, {"h": hT}
        return y, state

    def streaming_state(self, batch_size, dtype=jnp.float32):
        return {"h": jnp.zeros((batch_size, self.nOut), dtype)}


@_register
class AttentionVertex(BaseLayer):
    """Graph vertex: multi-head attention over separate (queries, keys,
    values) inputs (reference: conf.graph.AttentionVertex). A
    parameterized MULTI-input graph node — the graph runtime feeds it the
    full input list."""

    MULTI_INPUT = True

    def __init__(self, nInQueries=None, nInKeys=None, nInValues=None,
                 nOut=None, nHeads=1, headSize=None, projectInput=True,
                 **kw):
        super().__init__(**kw)
        self.nInQueries = nInQueries
        self.nInKeys = nInKeys
        self.nInValues = nInValues
        self.nOut = nOut
        self.nHeads = int(nHeads)
        self.headSize = headSize
        self.projectInput = projectInput

    def infer(self, *input_types):
        tq = input_types[0]
        self.nInQueries = self.nInQueries or tq.size
        if len(input_types) > 1:
            self.nInKeys = self.nInKeys or input_types[1].size
            self.nInValues = self.nInValues or input_types[-1].size
        else:
            self.nInKeys = self.nInKeys or self.nInQueries
            self.nInValues = self.nInValues or self.nInQueries
        if not self.projectInput:
            if self.nHeads != 1:
                raise ValueError(
                    "AttentionVertex: projectInput=False requires "
                    f"nHeads=1 (got nHeads={self.nHeads}); without "
                    "projections the single-head dotProductAttention "
                    "path is used")
            self.nOut = self.nInValues
        if self.headSize is None:
            self.headSize = (self.nOut // self.nHeads if self.projectInput
                             else self.nInKeys)
        return InputType.recurrent(
            self.nOut, getattr(tq, "timeSeriesLength", None))

    def init_params(self, key, dtype=jnp.float32):
        if not self.projectInput:
            return {}
        ks = jax.random.split(key, 4)
        proj = self.nHeads * self.headSize
        wi = self.weightInit
        return {
            "Wq": init_weight(wi, ks[0], (self.nInQueries, proj),
                              self.nInQueries, proj, dtype),
            "Wk": init_weight(wi, ks[1], (self.nInKeys, proj),
                              self.nInKeys, proj, dtype),
            "Wv": init_weight(wi, ks[2], (self.nInValues, proj),
                              self.nInValues, proj, dtype),
            "Wo": init_weight(wi, ks[3], (proj, self.nOut), proj,
                              self.nOut, dtype),
        }

    def apply(self, params, state, xs, training, rng):
        if not isinstance(xs, (list, tuple)):
            xs = [xs]
        q = jnp.swapaxes(xs[0], 1, 2)
        k = jnp.swapaxes(xs[1], 1, 2) if len(xs) > 1 else q
        v = jnp.swapaxes(xs[2], 1, 2) if len(xs) > 2 else k
        if self.projectInput:
            y = OPS["multiHeadDotProductAttention"](
                q, k, v, params["Wq"], params["Wk"], params["Wv"],
                params["Wo"], numHeads=self.nHeads)
        else:
            y = OPS["dotProductAttention"](q, k, v)
        return self._act(jnp.swapaxes(y, 1, 2)), state
