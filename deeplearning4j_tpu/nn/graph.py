"""ComputationGraph: the DAG-network runtime (multi-input / multi-output).

Reference capability: org.deeplearning4j.nn.graph.ComputationGraph
(SURVEY.md §2.5, call stack §3.2). As with MultiLayerNetwork, the DAG is
lowered to one pure function over the precomputed topological order and
trained with a single donated-buffer XLA step per minibatch.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff.samediff import (
    _as_batches, _host_array, _ones_mask, _pad_to_bucket, _prepare_batches,
    _split_dataset_full)
from deeplearning4j_tpu.evaluation import Evaluation
from deeplearning4j_tpu.ndarray import INDArray
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ComputationGraphConfiguration, GraphVertex)
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayer, OUTPUT_LAYER_TYPES)
from deeplearning4j_tpu.nn.multilayer import _normalize_grads, _unwrap


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        for out in conf.outputs:
            node, _ = conf.nodes[out]
            if not isinstance(node, OUTPUT_LAYER_TYPES):
                raise ValueError(f"output node {out!r} must be an "
                                 f"OutputLayer/LossLayer")
        self._params: dict[str, dict] = {}
        self._states: dict[str, dict] = {}
        self._opt_states: dict = {}
        self._prec_state: dict = {}  # loss-scaler state (ISSUE 4); {} = off
        self._listeners: list = []
        self._train_step = None
        self._train_step_plan = None  # health BuildPlan compiled into it
        self._multi_step = None
        self._bucket = None  # fit batch-size bucket (pad ragged tail)
        self._infer_fn_cache = {}
        self._iteration = 0
        self._epoch = 0
        self._score = None
        self._initialized = False

    def init(self):
        # master weights in the policy's param dtype (fp32 under any
        # *_mixed policy); exactly conf.dtype without a policy
        pol = self._precision_policy()
        dtype = pol.param_jnp
        key = jax.random.key(self.conf.seed)
        for i, name in enumerate(self.conf.topo_order):
            node, _ = self.conf.nodes[name]
            if isinstance(node, BaseLayer):
                self._params[name] = node.init_params(
                    jax.random.fold_in(key, i), dtype)
                self._states[name] = node.init_state(dtype)
            else:
                self._params[name] = {}
                self._states[name] = {}
        self._opt_states = {
            name: (self._updater(name).init_state(p) if p else ())
            for name, p in self._params.items()
        }
        scaler = self._loss_scaler()
        self._prec_state = scaler.init_state() if scaler else {}
        self._initialized = True
        return self

    def _precision_policy(self):
        return self.conf.precision_policy

    def _loss_scaler(self):
        from deeplearning4j_tpu.precision import DynamicLossScaler

        if not hasattr(self, "_scaler_cache"):
            self._scaler_cache = DynamicLossScaler.for_policy(
                self._precision_policy())
        return self._scaler_cache

    def _updater(self, name):
        node, _ = self.conf.nodes[name]
        u = getattr(node, "updater", None)
        return u if u is not None else self.conf.defaults["updater"]

    def _check_init(self):
        if not self._initialized:
            raise RuntimeError("call init() first")

    # -- pure forward over the DAG ------------------------------------------
    def _forward(self, params, states, inputs: dict, training, rng,
                 stop_before_output=False):
        # float inputs follow the policy's compute dtype (== the
        # configured dataType without a policy); int inputs (embedding
        # ids) pass through, and f64 is left alone — the gradient-check
        # harness runs fp64
        dt = self._precision_policy().compute_jnp
        env = {}
        for k, v in inputs.items():
            v = jnp.asarray(v)
            if jnp.issubdtype(v.dtype, jnp.floating) and v.dtype != dt \
                    and v.dtype != jnp.float64:
                v = v.astype(dt)
            env[k] = v
        new_states = {}
        for i, name in enumerate(self.conf.topo_order):
            node, ins = self.conf.nodes[name]
            xs = [env[n] for n in ins]
            if isinstance(node, GraphVertex):
                env[name] = node.apply(*xs)
                new_states[name] = {}
            elif stop_before_output and name in self.conf.outputs:
                # leave the pre-output input available for the loss
                env[name] = xs[0]
                new_states[name] = states[name]
            else:
                lrng = jax.random.fold_in(rng, i) if rng is not None else None
                arg = xs if getattr(node, "MULTI_INPUT", False) else xs[0]
                y, st = node.apply(params[name], states[name], arg,
                                   training, lrng)
                env[name] = y
                new_states[name] = st
        return env, new_states

    def _loss_from(self, params, states, inputs, labels: dict, training, rng,
                   masks: dict | None = None):
        from deeplearning4j_tpu.precision import cast_floating

        pol = self._precision_policy()
        if pol.is_mixed:
            # cast INSIDE whatever is differentiated: the transpose
            # upcasts gradients back to the master dtype
            params = cast_floating(params, pol.compute_jnp)
        env, new_states = self._forward(params, states, inputs, training, rng,
                                        stop_before_output=True)
        loss = 0.0
        for out in self.conf.outputs:
            node, _ = self.conf.nodes[out]
            mask = None if masks is None else masks.get(out)
            if training and getattr(node, "LOSS_UPDATES_STATE", False):
                # loss-state channel (see MultiLayerNetwork._loss_from)
                term, new_states[out] = node.compute_loss_with_state(
                    params[out], env[out], labels[out], mask, states[out])
                loss = loss + term
            else:
                loss = loss + node.compute_loss(params[out], env[out],
                                                labels[out], mask)
        # regularization
        for name, (node, _) in self.conf.nodes.items():
            p = params.get(name)
            if not p:
                continue
            l2 = getattr(node, "l2", None) or 0.0
            l1 = getattr(node, "l1", None) or 0.0
            if l2:
                loss = loss + 0.5 * l2 * sum(
                    jnp.sum(w * w) for w in jax.tree_util.tree_leaves(p))
            if l1:
                loss = loss + l1 * sum(
                    jnp.sum(jnp.abs(w)) for w in jax.tree_util.tree_leaves(p))
        return loss, new_states

    # -- training ------------------------------------------------------------
    def _layer_labels(self):
        """Health-row labels (one per node + the trailing loss row),
        row-aligned with the health array the step returns (same
        iteration order as _step_math)."""
        from deeplearning4j_tpu.telemetry import health as _health

        return _health.with_loss_row(
            f"{name}:{type(node).__name__}"
            for name, (node, _) in self.conf.nodes.items())

    def _step_math(self, params, states, opt_states, prec, inputs, labels,
                   masks, rng, it, health_plan=None):
        """One optimizer step as a pure traced function (shared by the
        single-step jit and the scan-of-K-steps jit). Health stats ride
        along per node when the plan collects, and the precision
        policy's loss scaler (scale/unscale/finite-gate/state-advance)
        compiles in exactly as in MultiLayerNetwork._step_math."""
        from deeplearning4j_tpu.telemetry import health as _health

        plan = health_plan or _health.INACTIVE
        scaler = self._loss_scaler()
        scaling = scaler is not None and bool(prec)

        def loss_fn(p):
            loss, ns = self._loss_from(p, states, inputs, labels, True,
                                       rng, masks)
            if scaling:
                return scaler.scale_loss(loss, prec), (loss, ns)
            return loss, (loss, ns)

        (_, (loss, new_states)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if scaling:
            grads = scaler.unscale(grads, prec)
            finite = scaler.all_finite(grads)
        new_params, new_opts, stats = {}, {}, []
        for name, (node, _) in self.conf.nodes.items():
            g = grads.get(name)
            if not g:
                new_params[name] = params[name]
                new_opts[name] = opt_states[name]
                if plan.collect:
                    stats.append(_health.zero_stats())
                continue
            g = _normalize_grads(
                g, getattr(node, "gradientNormalization", None),
                getattr(node, "gradientNormalizationThreshold", None)
                or 1.0)
            upd, new_opt = self._updater(name).apply_mixed(
                g, opt_states[name], params[name], it)
            new_params[name] = jax.tree_util.tree_map(
                lambda p, u: p - u, params[name], upd)
            new_opts[name] = new_opt
            if plan.collect:
                stats.append(_health.layer_stats(g, upd, new_params[name]))
        if plan.collect:
            stats.append(_health.loss_stats(loss))
        health = _health.stack_stats(stats) if plan.collect else None
        if scaling:
            new_params = _health.keep_if(finite, new_params, params)
            new_opts = _health.keep_if(finite, new_opts, opt_states)
            new_states = _health.keep_if(finite, new_states, states)
            new_prec = scaler.next_state(prec, finite)
        else:
            new_prec = prec
        if plan.skip:
            ok = _health.step_ok(health)
            new_params = _health.keep_if(ok, new_params, params)
            new_opts = _health.keep_if(ok, new_opts, opt_states)
            new_states = _health.keep_if(ok, new_states, states)
        return loss, new_params, new_states, new_opts, health, new_prec

    def _build_train_step(self, health_plan=None):
        def step(params, states, opt_states, prec, inputs, labels, masks,
                 rng, it):
            return self._step_math(params, states, opt_states, prec,
                                   inputs, labels, masks, rng, it,
                                   health_plan=health_plan)

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _policy_label(self, plan):
        return (f"{self._precision_policy().name}"
                f"/h{int(plan.collect)}{int(plan.skip)}")

    def _refresh_train_step(self):
        """(re)build the compiled step when missing or when the health
        build plan changed (see MultiLayerNetwork._refresh_train_step)."""
        from deeplearning4j_tpu import compilestore
        from deeplearning4j_tpu.telemetry import health as _health

        plan = _health.build_plan(self._listeners)
        if self._train_step is None or \
                getattr(self, "_train_step_plan", None) != plan:
            step = self._build_train_step(plan)
            if compilestore.enabled():
                # ISSUE 13: warm restarts deserialize instead of
                # recompiling (program digest = full graph conf)
                step = compilestore.StoredJit(
                    step, "graph",
                    program=(f"train:ComputationGraph:"
                             f"{self.conf.to_json()}"
                             f":policy={self._policy_label(plan)}"),
                    policy=self._policy_label(plan),
                    donation=(0, 1, 2))
            self._train_step = step
            self._train_step_plan = plan
        return plan

    def _build_multi_step(self, repeats=1, health_plan=None):
        from deeplearning4j_tpu.telemetry import health as _health

        plan = health_plan or _health.INACTIVE

        def many(params, states, opts, prec, inputs_k, labels_k, masks_k,
                 rng0, it0):
            def body(carry, xs):
                params, states, opts, prec, it = carry
                inputs, labels, masks = xs
                rng = jax.random.fold_in(rng0, it)
                loss, params, states, opts, health, prec = self._step_math(
                    params, states, opts, prec, inputs, labels, masks,
                    rng, it, health_plan=plan)
                ys = (loss, health) if plan.collect else loss
                return (params, states, opts, prec, it + 1), ys

            def scan_once(carry, _):
                return jax.lax.scan(body, carry,
                                    (inputs_k, labels_k, masks_k))

            carry = (params, states, opts, prec, it0)
            if repeats == 1:
                carry, ys = scan_once(carry, None)
            else:
                carry, ys_r = jax.lax.scan(scan_once, carry, None,
                                           length=repeats)
                ys = jax.tree_util.tree_map(lambda a: a[-1], ys_r)
            losses, healths = ys if plan.collect else (ys, None)
            params, states, opts, prec, _ = carry
            return losses, params, states, opts, healths, prec

        return jax.jit(many, donate_argnums=(0, 1, 2))

    def fitMultiBatch(self, features_k, labels_k, repeats: int = 1):
        """K optimizer steps in ONE device launch over stacked [K, B, ...]
        minibatches via lax.scan (see MultiLayerNetwork.fitMultiBatch:
        amortizes per-dispatch RPC latency; repeats=R makes R passes in
        the launch). Single-input single-output graphs only. Returns the
        [K] losses (last pass)."""
        self._check_init()
        from deeplearning4j_tpu.telemetry import health as _health

        plan = _health.build_plan(self._listeners)
        if not isinstance(getattr(self, "_multi_step", None), dict):
            self._multi_step = {}
        key = (repeats, plan)
        if key not in self._multi_step:
            self._multi_step[key] = self._build_multi_step(repeats, plan)
        # keep device-resident stacks on device (a _host_array bounce
        # would round-trip the whole [K,B,...] block D2H then H2D)
        f_k = _unwrap(features_k) if isinstance(
            features_k, (jax.Array, INDArray)) else _host_array(features_k)
        l_k = _unwrap(labels_k) if isinstance(
            labels_k, (jax.Array, INDArray)) else _host_array(labels_k)
        inputs_k = {self.conf.inputs[0]: f_k}
        labels_k = {self.conf.outputs[0]: l_k}
        masks_k = {self.conf.outputs[0]: np.ones(
            (l_k.shape[0],) + _ones_mask(l_k[0]).shape, np.float32)}
        rng0 = jax.random.key(self.conf.seed + 1)
        it0 = self._iteration
        from deeplearning4j_tpu import precision as _precision

        pm = _precision.monitor_for("graph", self._precision_policy())
        if pm is not None:
            pm.baseline_from(self._prec_state)
        (losses, self._params, self._states, self._opt_states, healths,
         self._prec_state) = self._multi_step[key](
                self._params, self._states, self._opt_states,
                self._prec_state, inputs_k, labels_k, masks_k, rng0,
                jnp.asarray(self._iteration, jnp.int32))
        self._iteration += int(f_k.shape[0]) * repeats
        self._score = float(losses[-1])
        if pm is not None:
            pm.on_launch(range(it0, self._iteration), self._prec_state)
        if healths is not None:
            hm = _health.monitor_for("graph", self._layer_labels(),
                                     self._listeners)
            if hm is not None:
                hm.precision = pm
                base = it0 + (repeats - 1) * int(f_k.shape[0])
                for k in range(int(f_k.shape[0])):
                    hm.on_step(base + k, healths[k])
                hm.flush()
        return losses

    def _feeds(self, ds, with_ones_masks=False):
        """Host-side feed dicts (numpy throughout: committed-vs-uncommitted
        inputs key separate jit cache entries even at identical avals, and
        a jnp bounce would cost a device round-trip per batch)."""
        feats, labels, _, lmasks = _split_dataset_full(ds)
        inputs = {n: _host_array(f) for n, f in zip(self.conf.inputs, feats)}
        lab = {n: _host_array(l) for n, l in zip(self.conf.outputs, labels)}
        masks = {}
        for n, m in zip(self.conf.outputs, lmasks):
            if m is not None:
                masks[n] = _host_array(m, np.float32)
            elif with_ones_masks:
                masks[n] = _ones_mask(lab[n])
        return inputs, lab, masks

    # -- TBPTT + streaming state (reference: ComputationGraph truncated
    # BPTT + rnnTimeStep; same chunked-segment scheme as
    # MultiLayerNetwork._fit_tbptt, over the DAG's recurrent nodes) ---------
    def _recurrent_nodes(self, forbid_bidirectional=False):
        from deeplearning4j_tpu.nn.conf.layers import Bidirectional

        out = []
        for name, (node, _ins) in self.conf.nodes.items():
            if isinstance(node, Bidirectional):
                if forbid_bidirectional:
                    raise ValueError(
                        f"node {name!r} is Bidirectional: streaming "
                        f"rnnTimeStep/TBPTT cannot carry state through a "
                        f"layer that consumes the whole sequence")
                continue
            if getattr(node, "IS_RECURRENT", False) or getattr(
                    getattr(node, "rnn", None), "IS_RECURRENT", False):
                out.append(name)
        return out

    def _seed_rnn_states(self, states, batch_size):
        dtype = self.conf.dtype
        out = dict(states)
        for name in self._recurrent_nodes():
            node, _ = self.conf.nodes[name]
            target = node.rnn if hasattr(node, "rnn") and getattr(
                node.rnn, "IS_RECURRENT", False) and not getattr(
                node, "IS_RECURRENT", False) else node
            out[name] = target.streaming_state(batch_size, dtype)
        return out

    def _strip_rnn_states(self, states):
        out = dict(states)
        for name in self._recurrent_nodes():
            out[name] = {}
        return out

    def _fit_tbptt(self, params, states, opts, prec, inputs, labels, masks,
                   base_key, hm=None, pm=None):
        from deeplearning4j_tpu.nn.conf.configuration import BackpropType

        assert self.conf.backpropType == BackpropType.TruncatedBPTT
        L = self.conf.tbpttLength
        T = max(v.shape[2] for v in inputs.values() if v.ndim == 3)
        n = next(iter(inputs.values())).shape[0]
        self._recurrent_nodes(forbid_bidirectional=True)
        states = self._seed_rnn_states(states, n)
        loss = None
        for t0 in range(0, T, L):
            def chunk(v, is_mask=False):
                if is_mask:
                    return v[:, t0:t0 + L] if v.ndim == 2 else v
                return v[:, :, t0:t0 + L] if v.ndim == 3 else v

            ic = {k: chunk(v) for k, v in inputs.items()}
            lc = {k: chunk(v) for k, v in labels.items()}
            mc = {k: chunk(v, is_mask=True) for k, v in masks.items()}
            seg = min(L, T - t0)
            if seg < L:
                # zero-pad the tail segment to the fixed tbptt shape and
                # mask the padded timesteps out of the loss
                pad = L - seg
                ic = {k: (np.concatenate(
                    [v, np.zeros(v.shape[:2] + (pad,), v.dtype)], axis=2)
                    if v.ndim == 3 else v) for k, v in ic.items()}
                lc = {k: (np.concatenate(
                    [v, np.zeros(v.shape[:2] + (pad,), v.dtype)], axis=2)
                    if v.ndim == 3 else v) for k, v in lc.items()}
                mc = {k: (np.concatenate(
                    [v, np.zeros((v.shape[0], pad), v.dtype)], axis=1)
                    if v.ndim == 2 else v) for k, v in mc.items()}
            it_used = self._iteration
            rng = jax.random.fold_in(base_key, it_used)
            loss, params, states, opts, health, prec = self._train_step(
                params, states, opts, prec, ic, lc, mc, rng, it_used)
            self._iteration += 1
            if hm is not None or pm is not None:
                # rebind first: on_step may raise (HALT) and the caller
                # must not be left holding this step's donated buffers
                self._params, self._states, self._opt_states = (
                    params, self._strip_rnn_states(states), opts)
                self._prec_state = prec
                if pm is not None:
                    pm.on_step(it_used, prec)
                if hm is not None:
                    hm.on_step(it_used, health)
        return loss, params, self._strip_rnn_states(states), opts, prec

    def rnnTimeStep(self, *xs):
        """Streaming inference with carried recurrent state; each x is
        [N, C] (one timestep) or [N, C, T] (a chunk)."""
        self._check_init()
        arrs = [_unwrap(x) for x in xs]
        single = arrs[0].ndim == 2
        if single:
            arrs = [a[:, :, None] for a in arrs]
        n = arrs[0].shape[0]
        rec = set(self._recurrent_nodes(forbid_bidirectional=True))
        if getattr(self, "_stream_states", None) is None or \
                getattr(self, "_stream_batch", None) != n:
            seeded = self._seed_rnn_states(self._states, n)
            self._stream_states = {k: seeded[k] for k in rec}
            self._stream_batch = n
        # only the recurrent carry is cached; everything else (BN running
        # stats, ...) comes fresh from self._states so an interleaved
        # fit() (which rebinds self._states after donation) can't leave
        # stale or deleted buffers behind
        states = {k: (self._stream_states[k] if k in rec else v)
                  for k, v in self._states.items()}
        inputs = {k: v for k, v in zip(self.conf.inputs, arrs)}
        key = "stream"
        if key not in self._infer_fn_cache:
            def fn(params, states, inputs):
                params = self._cast_for_inference(params)
                env, ns = self._forward(params, states, inputs, False, None)
                return [self._cast_output(env[o])
                        for o in self.conf.outputs], ns

            self._infer_fn_cache[key] = jax.jit(fn)
        ys, new_states = self._infer_fn_cache[key](
            self._params, states, inputs)
        self._stream_states = {k: new_states[k] for k in rec}
        outs = [INDArray(y[:, :, 0]) if single and y.ndim == 3
                else INDArray(y) for y in ys]
        return outs[0] if len(outs) == 1 else outs

    def rnnClearPreviousState(self):
        self._stream_states = None
        self._stream_batch = None

    def fit(self, data, epochs: int = 1):
        self._check_init()
        import time as _time

        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.telemetry import health as _health

        plan = self._refresh_train_step()
        policy_label = self._policy_label(plan)
        params, states, opts = self._params, self._states, self._opt_states
        prec = self._prec_state
        base_key = jax.random.key(self.conf.seed + 1)
        last = None
        # one flag check per fit(): with telemetry disabled both are
        # None and the loop body makes zero registry calls per step
        tele = telemetry.loop_instruments("graph")
        hm = _health.monitor_for("graph", self._layer_labels(),
                                 self._listeners)
        from deeplearning4j_tpu import precision as _precision

        pm = _precision.monitor_for("graph", self._precision_policy())
        if pm is not None:
            pm.baseline_from(prec)
        if hm is not None:
            hm.precision = pm
        # sampled trace root + step-time-throttled XLA cost attribution
        # (ISSUE 10) — the MultiLayerNetwork.fit treatment, graph loop
        from deeplearning4j_tpu.telemetry import (
            compile_ledger, costmodel, memledger, tracing)
        import sys as _sys

        # HBM ownership claim (ISSUE 14): same contract as the
        # multilayer loop — per-net key, None when disabled, one
        # gauge-set per step
        mem = None if tele is None else memledger.claim_for_owner(
            self, "train", "graph",
            tree={"p": params, "s": states, "o": opts, "prec": prec},
            model=type(self).__name__)

        tspan = tracing.trace_or_span("train.graph", loop="graph")
        tspan.__enter__()
        steps_seen = 0
        try:
            for epoch_i in range(epochs):
                batches, data = _prepare_batches(data, epoch_i, epochs)
                for ds in batches:
                    # explicit ones masks keep the jit signature stable
                    # across masked/unmasked and padded batches (one
                    # executable)
                    inputs, labels, masks = self._feeds(
                        ds, with_ones_masks=True)
                    n = next(iter(inputs.values())).shape[0]
                    if self._bucket is None or n > self._bucket:
                        self._bucket = n
                    if n < self._bucket:
                        for k in inputs:
                            (inputs[k],), _, _ = _pad_to_bucket(
                                [inputs[k]], np.ones((n,), np.float32),
                                self._bucket)
                        for k in labels:
                            (labels[k],), masks[k], _ = _pad_to_bucket(
                                [labels[k]], masks[k], self._bucket)
                    from deeplearning4j_tpu.nn.conf.configuration import (
                        BackpropType)

                    tbptt = (self.conf.backpropType ==
                             BackpropType.TruncatedBPTT
                             and self.conf.tbpttLength
                             and any(v.ndim == 3
                                     and v.shape[2] > self.conf.tbpttLength
                                     for v in inputs.values()))
                    if tele is not None:
                        t_step = _time.perf_counter()
                    try:
                        if tbptt:
                            loss, params, states, opts, prec = \
                                self._fit_tbptt(
                                    params, states, opts, prec, inputs,
                                    labels, masks, base_key, hm=hm, pm=pm)
                        else:
                            it_used = self._iteration
                            rng = jax.random.fold_in(base_key, it_used)
                            (loss, params, states, opts, health,
                             prec) = self._train_step(
                                params, states, opts, prec, inputs,
                                labels, masks, rng, it_used)
                            self._iteration += 1
                    except Exception as e:
                        # OOM forensics (ISSUE 14): typed error + flight
                        # event naming this seam and the top HBM claims
                        memledger.raise_if_oom(e, site="train.graph",
                                               step=self._iteration)
                        raise
                    if tele is not None:
                        dt_step = _time.perf_counter() - t_step
                        tele.record_step(dt_step, n,
                                         exemplar=tspan.trace_id)
                        if mem is not None:
                            # steady state: ONE gauge-set per step
                            mem.touch()
                        if tspan and not tbptt:
                            tracing.emit("train.step", tspan.ctx(),
                                         t_step, t_step + dt_step,
                                         step=it_used)
                        steps_seen += 1
                        if not tbptt:
                            costmodel.maybe_attribute(
                                tele, "graph", self._train_step,
                                (params, states, opts, prec, inputs,
                                 labels, masks, rng, it_used),
                                self, steps_seen, dt_step)
                            # recompile forensics (ISSUE 11): one
                            # thread-local read unless this step
                            # actually compiled
                            compile_ledger.note_step(
                                "graph", self._train_step,
                                (params, states, opts, prec, inputs,
                                 labels, masks, rng, it_used),
                                policy=policy_label,
                                window=(t_step, t_step + dt_step))
                    # rebind BEFORE the health monitor runs: its HALT
                    # policy raises out of fit() and the caller must find
                    # live params, not the buffers this step donated
                    self._params, self._states, self._opt_states = (
                        params, states, opts)
                    self._prec_state = prec
                    if not tbptt:
                        if pm is not None:
                            pm.on_step(it_used, prec)  # before hm
                        if hm is not None:
                            hm.on_step(it_used, health)
                    last = loss
                    if self._listeners:
                        self._score = float(loss)
                        for listener in self._listeners:
                            listener.iterationDone(self, self._iteration,
                                                   self._epoch)
                self._epoch += 1
            if pm is not None:
                pm.flush()   # before hm.flush: same-step skip handshake
            if hm is not None:
                hm.flush()   # drain the one-behind slot (HALT may raise)
            if last is not None:
                self._score = float(last)
            return self
        finally:
            tspan.__exit__(*_sys.exc_info())

    # -- inference -----------------------------------------------------------
    def _cast_for_inference(self, params):
        """Mixed policy: inference runs in the compute dtype too (the
        input cast in _forward already truncates, so casting the params
        is what actually buys the bf16 matmuls); identity otherwise."""
        from deeplearning4j_tpu.precision import cast_floating

        pol = self._precision_policy()
        return cast_floating(params, pol.compute_jnp) if pol.is_mixed \
            else params

    def _cast_output(self, y):
        pol = self._precision_policy()
        if jnp.issubdtype(y.dtype, jnp.floating) and \
                y.dtype != pol.output_jnp:
            return y.astype(pol.output_jnp)
        return y

    def output(self, *xs, train=False):
        """output(x1, x2, ...) -> list of output arrays (one per configured
        output)."""
        self._check_init()
        inputs = {n: _unwrap(x) for n, x in zip(self.conf.inputs, xs)}
        key = ("out", train)
        if key not in self._infer_fn_cache:
            def fn(params, states, inputs):
                params = self._cast_for_inference(params)
                env, _ = self._forward(params, states, inputs, train, None)
                return [self._cast_output(env[o])
                        for o in self.conf.outputs]

            self._infer_fn_cache[key] = jax.jit(fn)
        ys = self._infer_fn_cache[key](self._params, self._states, inputs)
        return [INDArray(y) for y in ys]

    def outputSingle(self, *xs, train=False) -> INDArray:
        return self.output(*xs, train=train)[0]

    def score(self, dataset=None) -> float:
        self._check_init()
        if dataset is None:
            if self._score is None:
                raise ValueError("no score yet")
            return self._score
        inputs, labels, masks = self._feeds(dataset)
        loss, _ = self._loss_from(self._params, self._states, inputs, labels,
                                  False, None, masks)
        return float(loss)

    def evaluate(self, iterator, numClasses=None) -> Evaluation:
        """Ragged final batches pad up to the running bucket (serving
        `pad_rows`) and slice back, so eval compiles ONE executable."""
        from deeplearning4j_tpu.serving.buckets import pad_rows

        self._check_init()
        ev = Evaluation(numClasses)
        bucket = None
        for ds in _as_batches(iterator):
            feats, labels, _, lmasks = _split_dataset_full(ds)
            fs = [_host_array(f) for f in feats]
            n = fs[0].shape[0]
            if bucket is None or n > bucket:
                bucket = n
            out = self.output(*[pad_rows(f, bucket) for f in fs])[0]
            ev.eval(labels[0], out.toNumpy()[:n], mask=lmasks[0])
        return ev

    def numParams(self) -> int:
        return sum(int(np.prod(v.shape)) for p in self._params.values()
                   for v in p.values())

    def params(self) -> INDArray:
        leaves = []
        for name in self.conf.topo_order:
            p = self._params[name]
            for k in sorted(p):
                leaves.append(jnp.ravel(p[k]))
        if not leaves:
            return INDArray(jnp.zeros((0,)))
        return INDArray(jnp.concatenate(leaves))

    def setParams(self, flat):
        """Install a flat vector in params() order (topo order, sorted
        param names per node)."""
        flat = jnp.asarray(flat).reshape(-1)
        off = 0
        for name in self.conf.topo_order:
            p = self._params[name]
            for k in sorted(p):
                n = int(np.prod(p[k].shape)) if p[k].shape else 1
                p[k] = flat[off: off + n].reshape(p[k].shape).astype(
                    p[k].dtype)
                off += n
        self._train_step = None
        self._multi_step = None

    def getParam(self, node: str, name: str) -> INDArray:
        return INDArray(self._params[node][name])

    def setListeners(self, *listeners):
        self._listeners = list(listeners)
        return self

    def gradients(self, inputs_and_labels) -> dict:
        """Per-node analytic gradients for the gradient-check harness."""
        self._check_init()
        inputs, labels, masks = self._feeds(inputs_and_labels)

        def loss_fn(p):
            loss, _ = self._loss_from(p, self._states, inputs, labels, False,
                                      None, masks)
            return loss

        return jax.grad(loss_fn)(self._params)

    def summary(self) -> str:
        lines = [f"{'name':<24}{'type':<26}{'nParams':<10}{'inputs'}"]
        for name in self.conf.topo_order:
            node, ins = self.conf.nodes[name]
            n = sum(int(np.prod(v.shape))
                    for v in self._params.get(name, {}).values())
            lines.append(f"{name:<24}{type(node).__name__:<26}{n:<10}{ins}")
        lines.append(f"Total params: {self.numParams()}")
        return "\n".join(lines)
