"""Activation functions for layer configs.

Reference capability: org.nd4j.linalg.activations.Activation enum +
IActivation impls (SURVEY.md §2.5 layer impls call these as nd4j transform
ops). Here each activation is a pure jnp function that XLA fuses into the
surrounding matmul/conv — there is no separate kernel to dispatch, which is
the TPU-native replacement for the reference's per-op JNI transform calls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_CUBE = lambda x: x ** 3  # noqa: E731

ACTIVATIONS = {
    "identity": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "logsoftmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, 0.01),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.silu,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "hardtanh": lambda x: jnp.clip(x, -1.0, 1.0),
    "hardsigmoid": jax.nn.hard_sigmoid,
    "rationaltanh": lambda x: 1.7159 * jnp.tanh(2.0 * x / 3.0),
    "rectifiedtanh": lambda x: jnp.maximum(jnp.tanh(x), 0.0),
    "cube": _CUBE,
    "thresholdedrelu": lambda x: jnp.where(x > 1.0, x, 0.0),
}


class Activation:
    """Enum-style accessors: Activation.RELU == "relu" (string names keep the
    config JSON-serializable exactly like the reference's enum names)."""

    IDENTITY = "identity"
    RELU = "relu"
    RELU6 = "relu6"
    TANH = "tanh"
    SIGMOID = "sigmoid"
    SOFTMAX = "softmax"
    LOGSOFTMAX = "logsoftmax"
    LEAKYRELU = "leakyrelu"
    ELU = "elu"
    SELU = "selu"
    GELU = "gelu"
    SWISH = "swish"
    MISH = "mish"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    HARDTANH = "hardtanh"
    HARDSIGMOID = "hardsigmoid"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    CUBE = "cube"
    THRESHOLDEDRELU = "thresholdedrelu"


def resolve_activation(name):
    """Accept a name string, an Activation constant, or a callable.
    "leakyrelu:<alpha>" parametrizes the negative slope (serializes as a
    plain string, like DL4J's ActivationLReLU(alpha))."""
    if callable(name):
        return name
    key = str(name).lower().replace("_", "")
    if key.startswith("leakyrelu:"):
        alpha = float(key.split(":", 1)[1])
        return lambda x: jax.nn.leaky_relu(x, alpha)
    if key.startswith("elu:"):
        alpha = float(key.split(":", 1)[1])
        return lambda x: jax.nn.elu(x, alpha)
    if key.startswith("thresholdedrelu:"):
        theta = float(key.split(":", 1)[1])
        return lambda x: jnp.where(x > theta, x, 0.0)
    if key not in ACTIVATIONS:
        raise ValueError(f"unknown activation {name!r}")
    return ACTIVATIONS[key]
