"""Loss functions for output layers.

Reference capability: org.nd4j.linalg.lossfunctions.LossFunctions.LossFunction
enum + ILossFunction impls (used by BaseOutputLayer.computeScore, SURVEY.md
§2.5). Each loss maps (labels, pre_output, activation_name, mask) -> scalar
mean-per-example score. Softmax+MCXENT and sigmoid+XENT fuse into
numerically-stable logit formulations (log_softmax / logaddexp) instead of
activating first — the fused form is also what XLA wants to see.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import resolve_activation


class LossFunction:
    MCXENT = "mcxent"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    MSE = "mse"
    L2 = "l2"
    XENT = "xent"
    MAE = "mae"
    L1 = "l1"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    POISSON = "poisson"
    COSINE_PROXIMITY = "cosine_proximity"
    SPARSE_MCXENT = "sparse_mcxent"


# DL4J alias: LossFunctions.LossFunction.NEGATIVELOGLIKELIHOOD is MCXENT
# with softmax clamping; both reduce to CE-with-logits here.
_XENT_FAMILY = {LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD}


def _flatten_time(labels, pre):
    """RNN outputs arrive as [N, C, T] (DL4J NCW) and segmentation
    outputs as [N, C, H, W]. Fold time/space into batch so every loss
    sees [N*, C]."""
    if pre.ndim >= 3:
        c = pre.shape[1]
        pre = jnp.reshape(jnp.moveaxis(pre, 1, -1), (-1, c))
        labels = jnp.reshape(jnp.moveaxis(labels, 1, -1),
                             (-1, labels.shape[1]))
    return labels, pre


def _per_example(loss_fn):
    def wrapped(labels, pre_output, activation, mask=None):
        labels, pre_output = _flatten_time(labels, pre_output)
        per_ex = loss_fn(labels, pre_output, activation)  # [N*]
        if mask is not None:
            m = jnp.reshape(mask, (-1,)).astype(per_ex.dtype)
            if m.size != per_ex.size and per_ex.size % m.size == 0:
                # per-example mask against per-timestep/pixel entries
                m = jnp.repeat(m, per_ex.size // m.size)
            return jnp.sum(per_ex * m) / jnp.maximum(jnp.sum(m), 1.0)
        return jnp.mean(per_ex)

    return wrapped


def _mcxent(labels, pre, activation):
    if activation == "softmax":
        logp = jax.nn.log_softmax(pre, axis=-1)
    elif activation in ("identity", "logsoftmax"):
        logp = pre if activation == "logsoftmax" else jnp.log(
            jnp.clip(pre, 1e-10, 1.0))
    else:
        out = resolve_activation(activation)(pre)
        logp = jnp.log(jnp.clip(out, 1e-10, 1.0))
    return -jnp.sum(labels * logp, axis=-1)


def _sparse_mcxent(labels, pre, activation):
    logp = jax.nn.log_softmax(pre, axis=-1)
    idx = labels.astype(jnp.int32)
    if idx.ndim == logp.ndim:  # [N,1] -> [N]
        idx = idx[..., 0]
    return -jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]


def _xent(labels, pre, activation):
    if activation == "sigmoid":
        # stable binary CE from logits: max(x,0) - x*z + log1p(exp(-|x|))
        per = (jnp.maximum(pre, 0) - pre * labels
               + jnp.log1p(jnp.exp(-jnp.abs(pre))))
    else:
        out = jnp.clip(resolve_activation(activation)(pre), 1e-10, 1 - 1e-10)
        per = -(labels * jnp.log(out) + (1 - labels) * jnp.log(1 - out))
    return jnp.sum(per, axis=-1)


def _mse(labels, pre, activation):
    out = resolve_activation(activation)(pre)
    return jnp.mean((labels - out) ** 2, axis=-1)


def _l2(labels, pre, activation):
    out = resolve_activation(activation)(pre)
    return jnp.sum((labels - out) ** 2, axis=-1)


def _mae(labels, pre, activation):
    out = resolve_activation(activation)(pre)
    return jnp.mean(jnp.abs(labels - out), axis=-1)


def _l1(labels, pre, activation):
    out = resolve_activation(activation)(pre)
    return jnp.sum(jnp.abs(labels - out), axis=-1)


def _hinge(labels, pre, activation):
    out = resolve_activation(activation)(pre)
    return jnp.sum(jnp.maximum(0.0, 1.0 - labels * out), axis=-1)


def _squared_hinge(labels, pre, activation):
    out = resolve_activation(activation)(pre)
    return jnp.sum(jnp.maximum(0.0, 1.0 - labels * out) ** 2, axis=-1)


def _kld(labels, pre, activation):
    out = jnp.clip(resolve_activation(activation)(pre), 1e-10, 1.0)
    lab = jnp.clip(labels, 1e-10, 1.0)
    return jnp.sum(labels * (jnp.log(lab) - jnp.log(out)), axis=-1)


def _poisson(labels, pre, activation):
    out = resolve_activation(activation)(pre)
    return jnp.sum(out - labels * jnp.log(jnp.clip(out, 1e-10, None)), axis=-1)


def _cosine(labels, pre, activation):
    out = resolve_activation(activation)(pre)
    dot = jnp.sum(labels * out, axis=-1)
    norms = (jnp.linalg.norm(labels, axis=-1)
             * jnp.linalg.norm(out, axis=-1))
    return -dot / jnp.maximum(norms, 1e-10)


_LOSSES = {
    LossFunction.MCXENT: _mcxent,
    LossFunction.NEGATIVELOGLIKELIHOOD: _mcxent,
    LossFunction.SPARSE_MCXENT: _sparse_mcxent,
    LossFunction.MSE: _mse,
    LossFunction.L2: _l2,
    LossFunction.XENT: _xent,
    LossFunction.MAE: _mae,
    LossFunction.L1: _l1,
    LossFunction.HINGE: _hinge,
    LossFunction.SQUARED_HINGE: _squared_hinge,
    LossFunction.KL_DIVERGENCE: _kld,
    LossFunction.POISSON: _poisson,
    LossFunction.COSINE_PROXIMITY: _cosine,
}


def resolve_loss(name):
    key = str(name).lower()
    if key not in _LOSSES:
        raise ValueError(f"unknown loss function {name!r}")
    return _per_example(_LOSSES[key])
