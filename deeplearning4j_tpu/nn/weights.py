"""Weight initialization schemes.

Reference capability: org.deeplearning4j.nn.weights.WeightInit +
WeightInitUtil (SURVEY.md §2.5 "Param init & flat params"). Initializers are
(key, shape, fan_in, fan_out) -> array; fan values follow DL4J's conventions
(for conv: fanIn = inC*kH*kW, fanOut = outC*kH*kW).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _normal(key, shape, std):
    return jax.random.normal(key, shape) * std


def _uniform(key, shape, limit):
    return jax.random.uniform(key, shape, minval=-limit, maxval=limit)


_INITS = {
    # DL4J XAVIER: gaussian with var 2/(fanIn+fanOut)
    "xavier": lambda k, s, fi, fo: _normal(k, s, math.sqrt(2.0 / (fi + fo))),
    "xavier_uniform": lambda k, s, fi, fo: _uniform(
        k, s, math.sqrt(6.0 / (fi + fo))),
    "xavier_fan_in": lambda k, s, fi, fo: _normal(k, s, math.sqrt(1.0 / fi)),
    # He / RELU: gaussian with var 2/fanIn
    "relu": lambda k, s, fi, fo: _normal(k, s, math.sqrt(2.0 / fi)),
    "relu_uniform": lambda k, s, fi, fo: _uniform(k, s, math.sqrt(6.0 / fi)),
    "lecun_normal": lambda k, s, fi, fo: _normal(k, s, math.sqrt(1.0 / fi)),
    "lecun_uniform": lambda k, s, fi, fo: _uniform(k, s, math.sqrt(3.0 / fi)),
    "normal": lambda k, s, fi, fo: _normal(k, s, 1.0 / math.sqrt(fi)),
    "uniform": lambda k, s, fi, fo: _uniform(
        k, s, 1.0 / math.sqrt(fi)),
    "sigmoid_uniform": lambda k, s, fi, fo: _uniform(
        k, s, 4.0 * math.sqrt(6.0 / (fi + fo))),
    "zero": lambda k, s, fi, fo: jnp.zeros(s),
    "ones": lambda k, s, fi, fo: jnp.ones(s),
}


class WeightInit:
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    NORMAL = "normal"
    UNIFORM = "uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    ZERO = "zero"
    ONES = "ones"


def init_weight(name, key, shape, fan_in, fan_out, dtype=jnp.float32):
    if callable(name):
        return jnp.asarray(name(key, shape), dtype)
    key_name = str(name).lower()
    if key_name not in _INITS:
        raise ValueError(f"unknown weight init {name!r}")
    return _INITS[key_name](key, shape, fan_in, fan_out).astype(dtype)
