"""Neural-net API layer (reference L4: deeplearning4j-nn, SURVEY.md §2.5)."""

from deeplearning4j_tpu.nn.activations import Activation  # noqa: F401
from deeplearning4j_tpu.nn.weights import WeightInit  # noqa: F401
from deeplearning4j_tpu.nn.losses import LossFunction  # noqa: F401
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf.configuration import (  # noqa: F401
    BackpropType, MultiLayerConfiguration, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.graph_conf import (  # noqa: F401
    ComputationGraphConfiguration, ElementWiseVertex, GraphVertex,
    L2NormalizeVertex, MergeVertex, ReshapeVertex, ScaleVertex, ShiftVertex,
    StackVertex, SubsetVertex)
from deeplearning4j_tpu.nn.conf import layers  # noqa: F401
from deeplearning4j_tpu.nn.conf.layers import (  # noqa: F401
    ActivationLayer, BatchNormalization, Bidirectional, Convolution1DLayer,
    ConvolutionLayer, ConvolutionMode, Deconvolution2D, DenseLayer,
    DropoutLayer, EmbeddingLayer, EmbeddingSequenceLayer, GlobalPoolingLayer,
    GravesLSTM, GRU, LastTimeStep, LocalResponseNormalization, LossLayer,
    LSTM,
    DepthToSpace, OutputLayer, PoolingType, RnnOutputLayer,
    DepthwiseConvolution2D, SeparableConvolution2D, SimpleRnn, SpaceToDepth, Subsampling1DLayer,
    SubsamplingLayer, Upsampling2D, ZeroPaddingLayer)
from deeplearning4j_tpu.nn.conf.objdetect import (  # noqa: F401
    Yolo2OutputLayer)
from deeplearning4j_tpu.nn.conf.attention import (  # noqa: F401
    AttentionVertex, LearnedSelfAttentionLayer, RecurrentAttentionLayer,
    SelfAttentionLayer)
from deeplearning4j_tpu.nn.conf.capsnet import (  # noqa: F401
    CapsuleLayer, CapsuleStrengthLayer, PrimaryCapsules)
from deeplearning4j_tpu.nn.conf.layers_extra import (  # noqa: F401
    CenterLossOutputLayer, Convolution3D, Cropping1D, Cropping2D,
    Cropping3D, ElementWiseMultiplicationLayer, FrozenLayer,
    LocallyConnected1D, LocallyConnected2D, MaskZeroLayer, MoELayer,
    OCNNOutputLayer, PReLULayer, RepeatVector, Subsampling3DLayer,
    Upsampling1D, Upsampling3D)
from deeplearning4j_tpu.nn.objdetect import (  # noqa: F401
    DetectedObject, YoloUtils)
from deeplearning4j_tpu.nn.conf.variational import (  # noqa: F401
    AutoEncoder, BernoulliReconstructionDistribution,
    GaussianReconstructionDistribution, VariationalAutoencoder)
from deeplearning4j_tpu.nn.multilayer import (  # noqa: F401
    GradientNormalization, MultiLayerNetwork)
from deeplearning4j_tpu.nn.graph import ComputationGraph  # noqa: F401
