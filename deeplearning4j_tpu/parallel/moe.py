"""Mixture-of-Experts with expert parallelism over the `expert` mesh axis.

Reference capability: ABSENT in the reference (SURVEY.md §2.6 marks
expert parallel "NO") — additive capability, built the TPU-native way
(GShard/Switch formulation): top-k gating produces dense one-hot
dispatch/combine tensors, expert FFNs are batched einsums with the expert
axis sharded over `expert`, and XLA inserts the all-to-alls that move
tokens to their experts. No custom scheduler, no per-expert kernels —
the MXU sees E parallel [C, H] x [H, F] matmuls.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, EXPERT_AXIS, spec_for)


def moe_init(key, hidden: int, ffn: int, n_experts: int,
             dtype=jnp.float32) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / math.sqrt(hidden)
    s2 = 1.0 / math.sqrt(ffn)
    return {
        "gate_w": jax.random.normal(k1, (hidden, n_experts), dtype) * s1,
        "w1": jax.random.normal(k2, (n_experts, hidden, ffn), dtype) * s1,
        "b1": jnp.zeros((n_experts, ffn), dtype),
        "w2": jax.random.normal(k3, (n_experts, ffn, hidden), dtype) * s2,
        "b2": jnp.zeros((n_experts, hidden), dtype),
    }


def moe_param_specs() -> dict:
    """PartitionSpecs: experts sharded over the expert axis."""
    return {
        "gate_w": P(),
        "w1": P(EXPERT_AXIS), "b1": P(EXPERT_AXIS),
        "w2": P(EXPERT_AXIS), "b2": P(EXPERT_AXIS),
    }


def moe_apply(params, x, k: int = 2, capacity_factor: float = 1.5):
    """x: [N, H] tokens -> ([N, H], aux_loss).

    Top-k gating with per-expert capacity C = ceil(k*N/E * cf). Overflow
    tokens are dropped (standard GShard behavior); aux_loss is the load-
    balancing loss (Switch Transformer eq. 4)."""
    n, h = x.shape
    e = params["gate_w"].shape[1]
    c = int(math.ceil(k * n / e * capacity_factor))

    # gating math in f32 regardless of activation dtype: routing decisions
    # and the aux loss are tiny tensors but precision-sensitive
    logits = x.astype(jnp.float32) @ params["gate_w"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)           # [N, E] f32

    # load-balancing aux loss: E * sum_e (frac tokens to e * mean prob e)
    top1 = jnp.argmax(probs, axis=-1)
    frac = jnp.mean(jax.nn.one_hot(top1, e, dtype=probs.dtype), axis=0)
    aux = e * jnp.sum(frac * jnp.mean(probs, axis=0))

    # top-k expert choice per token
    topk_p, topk_i = jax.lax.top_k(probs, k)          # [N, k]
    topk_p = topk_p / jnp.maximum(
        jnp.sum(topk_p, axis=-1, keepdims=True), 1e-9)

    # position of each (token, choice) within its expert's capacity:
    # cumulative count of earlier tokens routed to the same expert
    oh = jax.nn.one_hot(topk_i, e, dtype=jnp.int32)   # [N, k, E]
    flat = oh.reshape(n * k, e)
    pos_flat = jnp.cumsum(flat, axis=0) - flat        # [N*k, E]
    pos = jnp.sum(pos_flat.reshape(n, k, e) * oh, axis=-1)  # [N, k]
    keep = pos < c                                    # capacity mask

    # dense dispatch/combine tensors [N, E, C] in the activation dtype
    # (these feed the big MXU einsums)
    pos_oh = jax.nn.one_hot(pos, c, dtype=jnp.float32) * keep[..., None]
    disp = jnp.einsum("nke,nkc->nec", oh.astype(jnp.float32),
                      pos_oh).astype(x.dtype)
    comb = jnp.einsum("nk,nke,nkc->nec", topk_p, oh.astype(jnp.float32),
                      pos_oh).astype(x.dtype)

    # to experts, through the FFN, back — XLA turns the sharded-E einsums
    # into all-to-alls over the expert axis
    expert_in = jnp.einsum("nec,nh->ech", disp, x)
    hmid = jax.nn.gelu(
        jnp.einsum("ech,ehf->ecf", expert_in, params["w1"])
        + params["b1"][:, None, :])
    expert_out = (jnp.einsum("ecf,efh->ech", hmid, params["w2"])
                  + params["b2"][:, None, :])
    y = jnp.einsum("nec,ech->nh", comb, expert_out)
    return y, aux


class MoELayerTrainer:
    """Minimal expert-parallel trainer: one MoE FFN block regressing
    targets, params expert-sharded, batch data-sharded."""

    def __init__(self, mesh: Mesh, hidden=16, ffn=32, n_experts=4, k=2,
                 lr=1e-2, aux_weight=1e-2, seed=0):
        self.mesh = mesh
        self.k = k
        self.lr = lr
        self.aux_weight = aux_weight
        params = moe_init(jax.random.key(seed), hidden, ffn, n_experts)
        to_sh = lambda s: NamedSharding(  # noqa: E731
            mesh, P(*[a if a in mesh.axis_names else None for a in s]))
        self.p_sh = {kk: to_sh(s) for kk, s in moe_param_specs().items()}
        self.params = jax.device_put(params, self.p_sh)
        self.x_sh = NamedSharding(mesh, spec_for(mesh, DATA_AXIS))
        self._step_fn = None

    def loss(self, params, x, y):
        out, aux = moe_apply(params, x, k=self.k)
        return jnp.mean((out - y) ** 2) + self.aux_weight * aux

    def _build(self):
        repl = NamedSharding(self.mesh, P())

        def step(params, x, y):
            loss, grads = jax.value_and_grad(self.loss)(params, x, y)
            params = jax.tree_util.tree_map(
                lambda p, g: p - self.lr * g, params, grads)
            return loss, params

        return jax.jit(step, in_shardings=(self.p_sh, self.x_sh, self.x_sh),
                       out_shardings=(repl, self.p_sh), donate_argnums=(0,))

    def train_step(self, x, y):
        if self._step_fn is None:
            self._step_fn = self._build()
        loss, self.params = self._step_fn(self.params, np.asarray(x),
                                          np.asarray(y))
        return loss
