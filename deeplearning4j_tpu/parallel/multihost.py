"""Multi-host (multi-process) distributed initialization.

Reference capability: the reference's distributed transport — NCCL/MPI +
Aeron UDP parameter serving behind `VoidConfiguration`
(controllerAddress/networkMask/unicastPort, SURVEY.md §2.6/§5). On TPU
pods the transport tier is JAX's distributed runtime: every host runs
the same program, `jax.distributed.initialize` wires the processes
together, and from then on `jax.devices()` spans the whole pod — the
SAME MeshConfig/ShardedTrainer code paths used single-host compile to
collectives that ride ICI within a slice and DCN across slices. No
in-framework transport exists to configure, which is the design the
survey prescribes ("the transport layer is deleted, not ported").

Single-host processes (and the CI environment, which has one chip) can
exercise the full code path with num_processes=1.
"""

from __future__ import annotations

import jax


class VoidConfiguration:
    """Facade with the reference's field names. controllerAddress maps to
    the JAX coordinator address; networkMask/ports collapse (the JAX
    runtime multiplexes one coordinator endpoint)."""

    _FIELDS = ("controllerAddress", "networkMask", "unicastPort",
               "streamId")

    def __init__(self, controllerAddress="127.0.0.1:8476",
                 networkMask=None, unicastPort=None, streamId=None):
        self.controllerAddress = controllerAddress
        if networkMask is not None or unicastPort is not None \
                or streamId is not None:
            from deeplearning4j_tpu.parallel.trainer import _warn_noop_knob

            _warn_noop_knob(
                "VoidConfiguration.networkMask/unicastPort/streamId",
                "the JAX distributed runtime uses one coordinator "
                "endpoint")

    @staticmethod
    def builder():
        class _B:
            def __init__(self):
                self._kw = {}

            def __getattr__(self, item):
                if item not in VoidConfiguration._FIELDS:
                    raise AttributeError(
                        f"VoidConfiguration has no field {item!r} "
                        f"(known: {VoidConfiguration._FIELDS})")

                def setter(v):
                    self._kw[item] = v
                    return self

                return setter

            def build(self):
                return VoidConfiguration(**self._kw)

        return _B()


class MultiHost:
    """Process-group lifecycle for pod-scale training."""

    _initialized = False
    _init_args = None

    @staticmethod
    def initialize(void_config: VoidConfiguration | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None):
        """Wire this process into the pod's process group. Call once per
        process BEFORE any device access; afterwards jax.devices() spans
        all hosts and every existing mesh/trainer scales transparently.

        With num_processes=1 (or under a TPU runtime that provides the
        topology, where all args may be None) this is a no-op beyond
        marking the group initialized."""
        args = ((void_config or VoidConfiguration()).controllerAddress,
                num_processes, process_id)
        if MultiHost._initialized:
            if MultiHost._init_args is not None \
                    and args != MultiHost._init_args \
                    and any(a is not None for a in args[1:]):
                raise RuntimeError(
                    f"MultiHost already initialized with "
                    f"{MultiHost._init_args}; cannot re-initialize with "
                    f"{args} — call shutdown() first")
            return MultiHost.topology()
        coord = args[0]
        if num_processes is not None and num_processes > 1:
            try:
                # the CPU backend needs an explicit cross-process
                # collectives implementation (TPU/GPU wire theirs up in
                # PJRT); without it every multi-process CPU computation
                # fails with "Multiprocess computations aren't
                # implemented on the CPU backend". Must be set BEFORE
                # backend init, so no jax.devices()/default_backend()
                # probing here — harmless for non-CPU backends.
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass  # jax version without the option
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=num_processes,
                                       process_id=process_id)
        MultiHost._initialized = True
        MultiHost._init_args = args
        return MultiHost.topology()

    @staticmethod
    def topology() -> dict:
        return {
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "local_devices": len(jax.local_devices()),
            "global_devices": len(jax.devices()),
        }

    @staticmethod
    def shutdown():
        if MultiHost._initialized and jax.process_count() > 1:
            jax.distributed.shutdown()
        MultiHost._initialized = False
        MultiHost._init_args = None
