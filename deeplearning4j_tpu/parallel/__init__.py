"""Parallelism layer (reference L5: ParallelWrapper / Spark / parameter
server — SURVEY.md §2.6 — rebuilt as mesh + GSPMD shardings + in-step XLA
collectives)."""

from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS, EXPERT_AXIS, MeshConfig, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
    replicated, shard_batch, spec_for)
from deeplearning4j_tpu.parallel.trainer import (  # noqa: F401
    ParallelInference, ParallelWrapper, ParameterAveragingTrainingMaster,
    ShardedTrainer, SharedTrainingMaster, SparkDl4jMultiLayer)
from deeplearning4j_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention)
from deeplearning4j_tpu.parallel.sharding import (  # noqa: F401
    alternating_dense_specs, replicated_specs)
from deeplearning4j_tpu.parallel.multihost import (  # noqa: F401
    MultiHost, VoidConfiguration)
from deeplearning4j_tpu.parallel.elastic import (  # noqa: F401
    ElasticTrainer, PreemptionCheckpoint)
from deeplearning4j_tpu.parallel.pipeline_trainer import (  # noqa: F401
    PipelineParallelTrainer)
