"""Sharded training: the TPU-native replacement for the reference's entire
scale-out stack.

Reference capability: ParallelWrapper + SharedTrainingMaster +
VoidParameterServer/Aeron (SURVEY.md §2.6, call stack §3.5). The reference
clones the model per device thread, trains asynchronously, and exchanges
threshold-compressed updates over UDP. Here ONE jitted train step is
compiled with GSPMD shardings over a named mesh:

  - batch sharded over the 'data' axis, params replicated (DP) or sharded
    per the param_specs pytree (TP);
  - XLA emits the gradient all-reduce (psum over 'data') INSIDE the step
    HLO, riding ICI — there is no transport layer to port, and sync is
    exact (vs the reference's stale-tolerant async updates, a convergence
    semantics difference SURVEY.md §3.5 flags);
  - donation keeps params device-resident across steps.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, MeshConfig, global_batch, host_sharded_batch, spec_for)


def _host_scalar(x) -> float:
    """float(x) that also works on multi-process replicated outputs (not
    fully addressable -> read this process's shard, which holds the full
    replicated value)."""
    if getattr(x, "is_fully_addressable", True):
        return float(x)
    return float(np.asarray(x.addressable_data(0)))


def _pad_batch(arr, multiple):
    """Pad the batch axis up to a multiple by repeating the last row, and
    return (padded, real_count). The loss weighting uses real_count so
    padding rows do not bias gradients."""
    n = arr.shape[0]
    rem = n % multiple
    if rem == 0:
        return arr, n
    pad = multiple - rem
    reps = np.repeat(arr[-1:], pad, axis=0)
    return np.concatenate([arr, reps], axis=0), n


class ShardedTrainer:
    """Data/tensor-parallel trainer around a MultiLayerNetwork.

    param_specs: optional pytree (same structure as net._params) of
    PartitionSpec for tensor parallelism; default fully replicated."""

    def __init__(self, net, mesh: Mesh | None = None, param_specs=None):
        self.net = net
        self.mesh = mesh or MeshConfig.data_parallel()
        self.param_specs = param_specs
        self._step_fn = None
        self._step_plan = None   # health BuildPlan compiled into it
        self._n_data = self.mesh.shape.get(DATA_AXIS, 1)

    def _shardings(self):
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        if self.param_specs is None:
            p_shard = jax.tree_util.tree_map(lambda _: repl,
                                             self.net._params)
        else:
            p_shard = jax.tree_util.tree_map(
                lambda spec: NamedSharding(mesh, spec), self.param_specs,
                is_leaf=lambda x: isinstance(x, P))
        s_shard = jax.tree_util.tree_map(lambda _: repl, self.net._states)
        # optimizer state mirrors param sharding (TP memory savings depend
        # on m/v being sharded like their params); updater states are
        # param-shaped subtrees ({"m": params_like, ...}), so map each
        # state entry through the layer's param shardings
        o_shard = []
        for i, ost in enumerate(self.net._opt_states):
            if not ost:
                o_shard.append(())
                continue
            try:
                o_shard.append({
                    k: jax.tree_util.tree_map(lambda _, s: s, v, p_shard[i])
                    for k, v in ost.items()})
            except (ValueError, TypeError):
                o_shard.append(jax.tree_util.tree_map(lambda _: repl, ost))
        batch = NamedSharding(mesh, spec_for(mesh, DATA_AXIS))
        return p_shard, s_shard, o_shard, batch, repl

    def _build_step(self, health_plan=None):
        net = self.net
        updaters = [net._layer_updater(i) for i in range(len(net.layers))]
        p_sh, s_sh, o_sh, b_sh, repl = self._shardings()

        from deeplearning4j_tpu.nn.multilayer import _normalize_grads
        from deeplearning4j_tpu.telemetry import health as _health

        plan = health_plan or _health.INACTIVE
        scaler = net._loss_scaler()
        scaling = scaler is not None and bool(net._prec_state)
        # scaler state is a few replicated scalars; the finite-check
        # reduction over the sharded grads gets its psum from GSPMD just
        # like the health stats — the policy survives sharding intact
        prec_sh = jax.tree_util.tree_map(lambda _: repl, net._prec_state)

        def step(params, states, opt_states, prec, f, l, mask, rng, it):
            def loss_fn(p):
                loss, ns = net._loss_from(p, states, f, l, True, rng,
                                          mask=mask)
                if scaling:
                    return scaler.scale_loss(loss, prec), (loss, ns)
                return loss, (loss, ns)

            (_, (loss, new_states)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if scaling:
                grads = scaler.unscale(grads, prec)
                finite = scaler.all_finite(grads)
            new_params, new_opts, stats = [], [], []
            for i, lr in enumerate(net.layers):
                g = grads[i]
                if not g:
                    new_params.append(params[i])
                    new_opts.append(opt_states[i])
                    if plan.collect:
                        stats.append(_health.zero_stats())
                    continue
                g = _normalize_grads(g, lr.gradientNormalization,
                                     lr.gradientNormalizationThreshold
                                     or 1.0)
                upd, new_opt = updaters[i].apply_mixed(g, opt_states[i],
                                                       params[i], it)
                new_params.append(jax.tree_util.tree_map(
                    lambda p, u: p - u, params[i], upd))
                new_opts.append(new_opt)
                if plan.collect:
                    # fused reductions over the SHARDED grads/params —
                    # XLA inserts the cross-device psum inside the step
                    stats.append(_health.layer_stats(g, upd,
                                                     new_params[-1]))
            if plan.collect:
                stats.append(_health.loss_stats(loss))
            health = _health.stack_stats(stats) if plan.collect else None
            if scaling:
                new_params = _health.keep_if(finite, new_params, params)
                new_opts = _health.keep_if(finite, new_opts, opt_states)
                new_states = _health.keep_if(finite, new_states, states)
                new_prec = scaler.next_state(prec, finite)
            else:
                new_prec = prec
            if plan.skip:
                ok = _health.step_ok(health)
                new_params = _health.keep_if(ok, new_params, params)
                new_opts = _health.keep_if(ok, new_opts, opt_states)
                new_states = _health.keep_if(ok, new_states, states)
            return loss, new_params, new_states, new_opts, health, new_prec

        out_health = (repl,) if plan.collect else (None,)
        return jax.jit(
            step,
            in_shardings=(p_sh, s_sh, o_sh, prec_sh, b_sh, b_sh, b_sh,
                          repl, repl),
            out_shardings=(repl, p_sh, s_sh, o_sh) + out_health
            + (prec_sh,),
            donate_argnums=(0, 1, 2),
        )

    def place_params(self):
        """Device_put params/states/opt with their shardings (replicates or
        shards across the mesh; multi-process assembles global arrays from
        the identical host copies every process initialized)."""
        p_sh, s_sh, o_sh, _, repl = self._shardings()
        net = self.net
        if jax.process_count() > 1:
            def put(tree, sh_tree):
                def one(a, s):
                    host = np.asarray(jax.device_get(a))
                    return jax.make_array_from_callback(
                        host.shape, s, lambda idx, h=host: h[idx])
                return jax.tree_util.tree_map(one, tree, sh_tree)
        else:
            put = jax.device_put
        net._params = put(net._params, p_sh)
        net._states = put(net._states, s_sh)
        net._opt_states = put(net._opt_states, o_sh)
        if net._prec_state:
            net._prec_state = put(
                net._prec_state,
                jax.tree_util.tree_map(lambda _: repl, net._prec_state))

    def _prefetch_prepare(self):
        """Host-side batch prep (split + pad-to-multiple + mask) plus
        the sharded device_put, run in the DevicePrefetcher's producer
        thread so the H2D transfer of batch k+1 overlaps the step of
        batch k. Single-process only (the multi-host path assembles
        global arrays inline)."""
        from deeplearning4j_tpu.autodiff.samediff import _split_dataset
        from deeplearning4j_tpu.datasets.prefetch import DeviceBatch

        batch_sh = self._shardings()[3]

        def prepare(ds):
            feats, labels = _split_dataset(ds)
            if len(feats) != 1 or len(labels) != 1:
                return ds
            f = np.asarray(feats[0])
            l = np.asarray(labels[0])
            if f.dtype != np.float32:
                f = f.astype(np.float32)
            f, real = _pad_batch(f, self._n_data)
            l, _ = _pad_batch(l, self._n_data)
            mshape = ((l.shape[0], l.shape[2]) if l.ndim == 3
                      else (l.shape[0],))
            mask = np.ones(mshape, np.float32)
            mask[real:] = 0.0
            return DeviceBatch(jax.device_put(f, batch_sh),
                               jax.device_put(l, batch_sh),
                               jax.device_put(mask, batch_sh),
                               real=real)

        return prepare

    def _wrap_prefetch(self, data):
        from deeplearning4j_tpu.datasets import prefetch as _prefetch
        from deeplearning4j_tpu.datasets.iterator import (
            DataSetIterator as _DSI)

        if (jax.process_count() == 1
                and isinstance(data, _DSI)
                and not isinstance(data, _prefetch.DevicePrefetcher)
                and data.asyncSupported()
                and _prefetch.default_depth() > 0):
            wrapped = _prefetch.DevicePrefetcher(
                data, prepare=self._prefetch_prepare(), loop="sharded")
            return wrapped, wrapped
        return data, None

    def fit(self, data, epochs: int = 1):
        import time

        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.autodiff.samediff import (
            _as_batches, _split_dataset)
        from deeplearning4j_tpu.datasets.prefetch import DeviceBatch
        from deeplearning4j_tpu.telemetry import health as _health

        net = self.net
        if self._step_fn is None:
            self.place_params()
        plan = _health.build_plan(net._listeners)
        if self._step_fn is None or self._step_plan != plan:
            step = self._build_step(plan)
            from deeplearning4j_tpu import compilestore

            if compilestore.enabled():
                # ISSUE 13: the mesh topology is part of the program
                # digest — a sharded executable bakes in its device
                # assignment, so a differently-shaped mesh must miss
                step = compilestore.StoredJit(
                    step, "sharded",
                    program=(f"train:ShardedTrainer:"
                             f"{net.conf.to_json()}"
                             f":mesh={sorted(self.mesh.shape.items())}"
                             f":ndev={self.mesh.devices.size}"
                             f":specs={self.param_specs!r}"
                             f":policy={net._precision_policy().name}"
                             f"/h{int(plan.collect)}{int(plan.skip)}"),
                    policy=(f"{net._precision_policy().name}"
                            f"/h{int(plan.collect)}{int(plan.skip)}"),
                    donation=(0, 1, 2))
            self._step_fn = step
            self._step_plan = plan
        data, _prefetcher = self._wrap_prefetch(data)
        assemble = (host_sharded_batch
                    if getattr(data, "hostSharded", False)
                    else global_batch)
        params, states, opts = net._params, net._states, net._opt_states
        prec = net._prec_state
        base_key = jax.random.key(net.conf.seed + 1)
        last = None
        # one flag check per fit(): tele is None when telemetry is
        # disabled, and the loop body then makes zero registry calls
        tele = telemetry.loop_instruments("sharded")
        hm = _health.monitor_for("sharded", net._layer_labels(),
                                 net._listeners)
        from deeplearning4j_tpu import precision as _precision

        pm = _precision.monitor_for("sharded", net._precision_policy())
        if pm is not None:
            pm.baseline_from(prec)
        if hm is not None:
            hm.precision = pm
        # sampled trace root + cost attribution (ISSUE 10): same
        # treatment as MultiLayerNetwork.fit, loop="sharded"
        import sys as _sys

        from deeplearning4j_tpu.telemetry import (
            compile_ledger, costmodel, tracing)

        # compile-ledger policy label (ISSUE 11): precision policy +
        # health build plan, both compiled into the sharded step
        policy_label = (f"{net._precision_policy().name}"
                        f"/h{int(plan.collect)}{int(plan.skip)}")

        from deeplearning4j_tpu.telemetry import memledger

        # HBM ownership claim (ISSUE 14): the sharded replicas of
        # params/updater/loss-scale state, keyed to the NET — None when
        # disabled, one gauge-set per step (the multilayer contract)
        mem = None if tele is None else memledger.claim_for_owner(
            net, "train", "sharded",
            tree={"p": params, "s": states, "o": opts, "prec": prec},
            mesh=str(sorted(self.mesh.shape.items())))

        tspan = tracing.trace_or_span("train.sharded", loop="sharded")
        tspan.__enter__()
        steps_seen = 0
        try:
            for _ in range(epochs):
                batch_iter = iter(_as_batches(data))
                while True:
                    if tele is not None:
                        t_etl = time.perf_counter()
                    ds = next(batch_iter, None)
                    if ds is None:
                        break
                    if tele is not None:
                        tele.record_etl_wait(time.perf_counter() - t_etl)
                    if isinstance(ds, DeviceBatch):
                        # prefetched: pad/mask/sharded-placement already
                        # happened in the producer thread
                        f, l, mask, real = (ds.features, ds.labels, ds.mask,
                                            ds.real)
                    else:
                        feats, labels = _split_dataset(ds)
                        f = np.asarray(feats[0])
                        l = np.asarray(labels[0])
                        f, real = _pad_batch(f, self._n_data)
                        l, _ = _pad_batch(l, self._n_data)
                        # zero-weight the padding rows so repeated examples
                        # do not bias gradients ([N] for 2D labels, [N,T]
                        # for NCW labels)
                        mshape = ((l.shape[0], l.shape[2]) if l.ndim == 3
                                  else (l.shape[0],))
                        mask = np.ones(mshape, np.float32)
                        mask[real:] = 0.0
                        if jax.process_count() > 1:
                            # multi-host SPMD. Host-sharded pipelines
                            # (shardByHost) feed per-process-DISTINCT
                            # batches that concatenate into the global
                            # batch; everything else follows the
                            # identical-copy convention where each
                            # device takes its own slice
                            f = assemble(self.mesh, f)
                            l = assemble(self.mesh, l)
                            mask = assemble(self.mesh, mask)
                    it_used = net._iteration
                    rng = jax.random.fold_in(base_key, it_used)
                    if tele is None:
                        try:
                            loss, params, states, opts, health, prec = \
                                self._step_fn(params, states, opts, prec,
                                              f, l, mask, rng, it_used)
                        except Exception as e:
                            # OOM forensics (ISSUE 14): typed error +
                            # flight event naming this seam
                            memledger.raise_if_oom(
                                e, site="train.sharded", step=it_used)
                            raise
                    else:
                        # the span is also a TraceAnnotation, so the host
                        # step region lines up with XPlane device traces;
                        # dispatch-queue backpressure makes its wall time
                        # equal the device step time in steady state (no
                        # sync added)
                        sp = tele.step_span()
                        sp.exemplar = tspan.trace_id
                        t_step = time.perf_counter()
                        try:
                            with sp:
                                loss, params, states, opts, health, \
                                    prec = self._step_fn(
                                        params, states, opts, prec, f,
                                        l, mask, rng, it_used)
                        except Exception as e:
                            memledger.raise_if_oom(
                                e, site="train.sharded", step=it_used)
                            raise
                        dt_step = time.perf_counter() - t_step
                        if mem is not None:
                            # steady state: ONE gauge-set per step
                            mem.touch()
                        if tspan:
                            tracing.emit("train.step", tspan.ctx(),
                                         t_step, t_step + dt_step,
                                         step=it_used)
                        tele.examples.inc(real)
                        if tele.step_flops:
                            # this loop records through the Timer span,
                            # not record_step, so the live MFU gauge
                            # refreshes here
                            costmodel.publish_mfu("sharded",
                                                  tele.step_flops,
                                                  dt_step)
                        steps_seen += 1
                        costmodel.maybe_attribute(
                            tele, "sharded", self._step_fn,
                            (params, states, opts, prec, f, l, mask,
                             rng, it_used), self, steps_seen, dt_step)
                        # recompile forensics (ISSUE 11): one
                        # thread-local read unless this step compiled
                        compile_ledger.note_step(
                            "sharded", self._step_fn,
                            (params, states, opts, prec, f, l, mask,
                             rng, it_used), policy=policy_label,
                            window=(t_step, t_step + dt_step))
                    # rebind BEFORE the health monitor runs: its HALT policy
                    # raises out of fit() and the caller must find live
                    # params, not the buffers this step donated
                    net._params, net._states, net._opt_states = (
                        params, states, opts)
                    net._prec_state = prec
                    if pm is not None:
                        pm.on_step(it_used, prec)   # before hm (skip set)
                    if hm is not None:
                        hm.on_step(it_used, health)
                    net._iteration += 1
                    last = loss
                    if net._listeners:
                        net._score = _host_scalar(loss)
                        for listener in net._listeners:
                            listener.iterationDone(net, net._iteration,
                                                   net._epoch)
                net._epoch += 1
        finally:
            tspan.__exit__(*_sys.exc_info())
            # deterministic producer shutdown (see
            # MultiLayerNetwork.fit): a raising fit must not
            # leave a prefetch thread racing the next attempt
            if _prefetcher is not None:
                _prefetcher.close()
        if pm is not None:
            pm.flush()   # before hm.flush: same-step skip handshake
        if hm is not None:
            hm.flush()   # drain the one-behind slot (HALT may raise here)
        if last is not None:
            net._score = _host_scalar(last)
        return net


# ---------------------------------------------------------------------------
# facades with the reference's API shapes
# ---------------------------------------------------------------------------


_WARNED_KNOBS: set = set()


def _warn_noop_knob(knob, why):
    """One-time notice that a parity knob is accepted but has no effect
    here (VERDICT.md round-1 weak item 7: silent no-ops surprise users)."""
    if knob in _WARNED_KNOBS:
        return
    _WARNED_KNOBS.add(knob)
    import warnings

    warnings.warn(f"{knob} is accepted for DL4J API parity but has no "
                  f"effect on TPU: {why}", stacklevel=3)


class ParallelWrapper:
    """Reference: org.deeplearning4j.parallelism.ParallelWrapper.Builder
    (SURVEY.md §2.6). workers() picks how many devices join the data axis;
    averaging/gradient-sharing knobs are accepted for API parity but the
    sync is always the exact in-step all-reduce."""

    class Builder:
        def __init__(self, net):
            self._net = net
            self._workers = None
            self._prefetch = 2

        def workers(self, n):
            self._workers = n
            return self

        def prefetchBuffer(self, n):
            self._prefetch = n
            return self

        def averagingFrequency(self, n):
            _warn_noop_knob("ParallelWrapper.averagingFrequency",
                            "gradients all-reduce exactly every step "
                            "inside the compiled executable")
            return self

        def trainingMode(self, *_):
            return self

        def workspaceMode(self, *_):
            return self

        def build(self):
            devices = jax.devices()
            n = self._workers or len(devices)
            mesh = MeshConfig(data=n, devices=devices[:n]).build()
            return ParallelWrapper(self._net, mesh, self._prefetch)

    def __init__(self, net, mesh, prefetch=2):
        self.net = net
        self.mesh = mesh
        self.prefetch = prefetch
        self._trainer = ShardedTrainer(net, mesh)

    def fit(self, iterator, epochs: int = 1):
        from deeplearning4j_tpu.datasets.iterator import (
            AsyncDataSetIterator, DataSetIterator)

        data = iterator
        if isinstance(iterator, DataSetIterator) and self.prefetch > 0 \
                and iterator.asyncSupported():
            data = AsyncDataSetIterator(iterator, self.prefetch)
        self._trainer.fit(data, epochs)
        return self.net

    def shutdown(self):
        pass


class ParallelInference:
    """Reference: org.deeplearning4j.parallelism.ParallelInference —
    batched inference over all devices (batch sharded over 'data')."""

    class Builder:
        def __init__(self, net):
            self._net = net
            self._batch_limit = 32

        def inferenceMode(self, *_):
            return self

        def batchLimit(self, n):
            self._batch_limit = n
            return self

        def workers(self, n):
            return self

        def build(self):
            return ParallelInference(self._net, self._batch_limit)

    def __init__(self, net, batch_limit=32):
        self.net = net
        self.batch_limit = batch_limit
        self.mesh = MeshConfig.data_parallel()
        self._fn = None
        self._n_data = self.mesh.shape.get(DATA_AXIS, 1)

    def output(self, x):
        from deeplearning4j_tpu.ndarray import INDArray

        net = self.net
        if self._fn is None:
            mesh = self.mesh
            repl = NamedSharding(mesh, P())
            b_sh = NamedSharding(mesh, spec_for(mesh, DATA_AXIS))
            p_sh = jax.tree_util.tree_map(lambda _: repl, net._params)
            s_sh = jax.tree_util.tree_map(lambda _: repl, net._states)

            def fn(params, states, xb):
                y, _ = net._forward(params, states, xb, False, None)
                return y

            self._fn = jax.jit(fn, in_shardings=(p_sh, s_sh, b_sh),
                               out_shardings=b_sh)
        xb = np.asarray(x)
        xb, real = _pad_batch(xb, self._n_data)
        y = self._fn(net._params, net._states, xb)
        return INDArray(y[:real])


class ParameterAveragingTrainingMaster:
    """Reference: dl4j-spark ParameterAveragingTrainingMaster.Builder —
    kept as a mesh-size configuration facade (averaging IS all-reduce when
    done every step)."""

    class Builder:
        def __init__(self, *_args):
            self._batch = 32

        def batchSizePerWorker(self, n):
            self._batch = n
            return self

        def averagingFrequency(self, n):
            _warn_noop_knob("TrainingMaster.averagingFrequency",
                            "averaging IS the in-step all-reduce here")
            return self

        def workerPrefetchNumBatches(self, n):
            return self

        def build(self):
            return ParameterAveragingTrainingMaster(self._batch)

    def __init__(self, batch_per_worker=32):
        self.batch_per_worker = batch_per_worker


class SharedTrainingMaster(ParameterAveragingTrainingMaster):
    """Reference: gradient-sharing SharedTrainingMaster (threshold-
    compressed async updates). The compression knobs are accepted and
    ignored: dense synchronous all-reduce over ICI replaces sparse async
    UDP (SURVEY.md §2.6 item 'Gradient sharing')."""

    class Builder(ParameterAveragingTrainingMaster.Builder):
        def thresholdAlgorithm(self, *_):
            _warn_noop_knob("SharedTrainingMaster.thresholdAlgorithm",
                            "dense synchronous all-reduce over ICI "
                            "replaces threshold-compressed async updates")
            return self

        def residualPostProcessor(self, *_):
            return self

        def build(self):
            return SharedTrainingMaster(self._batch)


class SparkDl4jMultiLayer:
    """Reference: org.deeplearning4j.spark.impl.multilayer
    .SparkDl4jMultiLayer — the Spark driver role collapses to 'shard the
    batch over the mesh'; `sc` is accepted for signature parity."""

    def __init__(self, sc, net_or_conf, training_master=None):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if hasattr(net_or_conf, "layers") and not hasattr(net_or_conf,
                                                          "fit"):
            net = MultiLayerNetwork(net_or_conf)
            net.init()
        else:
            net = net_or_conf
        self.net = net
        self.training_master = training_master
        self._trainer = ShardedTrainer(net)

    def fit(self, data, epochs: int = 1):
        self._trainer.fit(data, epochs)
        return self.net

    def getNetwork(self):
        return self.net
