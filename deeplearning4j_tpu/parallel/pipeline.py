"""GPipe-style pipeline parallelism over the `pipe` mesh axis.

Reference capability: ABSENT in the reference (SURVEY.md §2.6 marks
pipeline parallel "NO", with the prescribed TPU mapping "XLA
multi-computation + collective permute") — this is additive capability,
built the TPU-native way:

- the network is split into S equal-structure STAGES whose params are
  stacked on a leading axis sharded over `pipe` (device s holds stage s);
- a microbatched forward runs S + M - 1 ticks inside `shard_map`; each
  tick every device applies its stage to its current activation and
  `ppermute`s the result to the next device (the bubble is the standard
  GPipe (S-1)/(S+M-1) overhead);
- backward needs no hand scheduling: `jax.grad` through the functional
  forward reverses every `ppermute` automatically, yielding the GPipe
  backward pipeline.

Composes with data parallelism: build a dp x pp mesh and shard the batch
over `data` as usual; the pipeline loop runs per data-shard.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, PIPE_AXIS, MeshConfig, spec_for)


def _stage_spec(mesh):
    """Stage-stacked arrays [S, ...]: leading axis over pipe."""
    return spec_for(mesh, PIPE_AXIS)


def pipeline_apply(stage_fn, stage_params, x_mb, mesh):
    """Run the S-stage pipeline over M microbatches.

    stage_fn:      (params_one_stage, x, microbatch_index) -> y (same
                   structure per stage); microbatch_index is the
                   microbatch the stage is consuming at that tick (a
                   traced int32 scalar) — stage bodies needing
                   per-microbatch state (dropout RNG) key off it, others
                   ignore it.
    stage_params:  pytree with leading axis S (sharded over `pipe`)
    x_mb:          [M, mb, ...] microbatches (replicated over `pipe`,
                   shardable over `data`)
    returns        [M, mb, ...] outputs of the last stage.
    """
    n_stages = mesh.shape.get(PIPE_AXIS, 1)
    if n_stages == 1:
        def seq(params, x, mb_idx):
            s = jax.tree_util.tree_leaves(params)[0].shape[0]
            y = x
            for i in range(s):
                p_i = jax.tree_util.tree_map(lambda a: a[i], params)
                y = stage_fn(p_i, y, mb_idx)
            return y
        m1 = x_mb.shape[0]
        return jax.vmap(lambda mb, i: seq(stage_params, mb, i))(
            x_mb, jnp.arange(m1, dtype=jnp.int32))

    m = x_mb.shape[0]
    p_spec = _stage_spec(mesh)
    x_spec = spec_for(mesh, None, DATA_AXIS)   # [M, mb(data-sharded), ...]
    param_specs = jax.tree_util.tree_map(lambda _: p_spec, stage_params)

    @partial(shard_map, mesh=mesh,
             in_specs=(param_specs, x_spec), out_specs=x_spec,
             check_rep=False)
    def run(params_local, x_local):
        # params_local leaves: [1, ...] (this device's stage)
        p_here = jax.tree_util.tree_map(lambda a: a[0], params_local)
        stage = jax.lax.axis_index(PIPE_AXIS)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        state = jnp.zeros_like(x_local[0])
        outs = jnp.zeros_like(x_local)
        for t in range(m + n_stages - 1):
            # first stage consumes microbatch t; others consume the
            # activation handed to them last tick. Stage s at tick t is
            # working on microbatch t - s (clipped; out-of-range ticks
            # are pipeline-bubble work that never reaches the output).
            mb_idx = jnp.clip(t - stage, 0, m - 1).astype(jnp.int32)
            inp = jnp.where(stage == 0,
                            x_local[jnp.minimum(t, m - 1)], state)
            out = stage_fn(p_here, inp, mb_idx)
            # collect on the LAST stage once the pipe is full
            is_ready = jnp.logical_and(stage == n_stages - 1,
                                       t >= n_stages - 1)
            slot = jnp.clip(t - (n_stages - 1), 0, m - 1)
            outs = jnp.where(
                is_ready,
                jax.lax.dynamic_update_index_in_dim(
                    outs, out, slot, axis=0),
                outs)
            state = jax.lax.ppermute(out, PIPE_AXIS, perm)
        # every device holds an `outs` buffer but only the last stage's is
        # real; zero the rest and psum to broadcast (ppermute cannot
        # one-to-many)
        outs = jnp.where(stage == n_stages - 1, outs,
                         jnp.zeros_like(outs))
        return jax.lax.psum(outs, PIPE_AXIS)

    return run(stage_params, x_mb)


class PipelineMlp:
    """A pipelined MLP: S stages x [hidden -> hidden] blocks, demonstrating
    dp x pp training end-to-end (VERDICT.md round-1 item 8)."""

    def __init__(self, mesh: Mesh, hidden: int, n_stages: int | None = None,
                 microbatches: int = 4, lr: float = 1e-2, seed: int = 0):
        self.mesh = mesh
        self.hidden = hidden
        self.n_stages = n_stages or mesh.shape.get(PIPE_AXIS, 1)
        self.microbatches = microbatches
        self.lr = lr
        key = jax.random.key(seed)
        k1, k2 = jax.random.split(key)
        scale = 1.0 / np.sqrt(hidden)
        params = {
            "W": jax.random.normal(
                k1, (self.n_stages, hidden, hidden), jnp.float32) * scale,
            "b": jnp.zeros((self.n_stages, hidden), jnp.float32),
        }
        sh = NamedSharding(mesh, _stage_spec(mesh))
        self.params = jax.device_put(params, {"W": sh, "b": sh})
        self._step_fn = None

    @staticmethod
    def stage_fn(p, x, mb_idx):
        del mb_idx  # stateless stage
        return jnp.tanh(x @ p["W"] + p["b"])

    def forward(self, params, x_mb):
        return pipeline_apply(self.stage_fn, params, x_mb, self.mesh)

    def loss(self, params, x_mb, y_mb):
        out = self.forward(params, x_mb)
        return jnp.mean((out - y_mb) ** 2)

    def _build(self):
        mesh = self.mesh
        x_sh = NamedSharding(mesh, spec_for(mesh, None, DATA_AXIS))
        p_sh = jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, _stage_spec(mesh)), self.params)
        repl = NamedSharding(mesh, P())

        def step(params, x_mb, y_mb):
            loss, grads = jax.value_and_grad(self.loss)(params, x_mb, y_mb)
            params = jax.tree_util.tree_map(
                lambda p, g: p - self.lr * g, params, grads)
            return loss, params

        return jax.jit(step, in_shardings=(p_sh, x_sh, x_sh),
                       out_shardings=(repl, p_sh), donate_argnums=(0,))

    def train_step(self, x, y):
        """x/y: [batch, hidden]; batch is split into `microbatches`."""
        if self._step_fn is None:
            self._step_fn = self._build()
        m = self.microbatches
        x_mb = np.asarray(x).reshape(m, -1, self.hidden)
        y_mb = np.asarray(y).reshape(m, -1, self.hidden)
        loss, self.params = self._step_fn(self.params, x_mb, y_mb)
        return loss


def pipeline_dryrun(devices):
    """dp x pp leg of the driver's multichip dryrun: 2-stage pipeline with
    data parallelism, two training steps, loss must fall."""
    n = len(devices)
    pp = 2 if n % 2 == 0 else 1
    dp = n // pp
    mesh = MeshConfig(data=dp, pipe=pp, devices=devices).build()
    hidden, mb, per_mb = 16, 4, max(2 * dp, dp)
    model = PipelineMlp(mesh, hidden, microbatches=mb, lr=5e-2, seed=1)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(mb * per_mb, hidden)).astype(np.float32)
    y = np.tanh(rng.normal(size=(mb * per_mb, hidden))).astype(np.float32)
    l1 = float(model.train_step(x, y))
    l2 = float(model.train_step(x, y))
    print(f"pipeline_dryrun: mesh={dict(mesh.shape)} "
          f"loss {l1:.4f} -> {l2:.4f}")
    assert l2 < l1, "pipeline training did not reduce loss"
