"""Device mesh & topology configuration.

Reference capability: the role of VoidConfiguration + ParallelWrapper's
device management (SURVEY.md §2.6). The reference organizes devices via
host threads (CudaAffinityManager) and UDP mesh membership
(MeshOrganizer); here topology is a jax.sharding.Mesh with named axes and
ALL communication is XLA collectives over ICI/DCN compiled into the step
(SURVEY.md §5 "Distributed communication backend" — the transport layer is
deleted, not ported).

Axis names (the scaling-book convention):
  data   — batch (data parallel), gradients all-reduced over this axis
  model  — tensor parallel (weights sharded)
  seq    — sequence/context parallel (ring attention over this axis)
  pipe   — pipeline stages
  expert — MoE expert parallel
"""

from __future__ import annotations

import math

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


class MeshConfig:
    """Declarative mesh: MeshConfig(data=4, model=2) -> 8-device mesh.

    Unspecified axes get size 1; data absorbs leftover devices when
    data=-1 (the common 'use everything for DP' case)."""

    def __init__(self, data=-1, model=1, seq=1, pipe=1, expert=1,
                 devices=None):
        self.sizes = {DATA_AXIS: data, MODEL_AXIS: model, SEQ_AXIS: seq,
                      PIPE_AXIS: pipe, EXPERT_AXIS: expert}
        self.devices = devices

    def build(self) -> Mesh:
        devices = self.devices if self.devices is not None else jax.devices()
        n = len(devices)
        fixed = math.prod(v for v in self.sizes.values() if v > 0)
        sizes = dict(self.sizes)
        n_auto = sum(1 for v in sizes.values() if v <= 0)
        if n_auto > 1:
            raise ValueError("at most one axis may be -1 (auto)")
        if n_auto == 1:
            if n % fixed != 0:
                raise ValueError(
                    f"{n} devices not divisible by fixed axes {fixed}")
            auto = n // fixed
            for k, v in sizes.items():
                if v <= 0:
                    sizes[k] = auto
        total = math.prod(sizes.values())
        if total != n:
            raise ValueError(
                f"mesh {sizes} needs {total} devices, have {n}")
        # drop size-1 axes from the physical mesh but remember them so
        # PartitionSpecs referencing them resolve to None
        axis_names = [a for a in (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, PIPE_AXIS,
                                  EXPERT_AXIS) if sizes[a] > 1]
        if not axis_names:
            axis_names = [DATA_AXIS]
        shape = [sizes[a] if sizes[a] > 1 else 1 for a in axis_names]
        dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, axis_names)

    @staticmethod
    def data_parallel(devices=None) -> Mesh:
        return MeshConfig(data=-1, devices=devices).build()


def spec_for(mesh: Mesh, *axes) -> P:
    """PartitionSpec dropping axes the mesh doesn't have (size-1 axes)."""
    names = set(mesh.axis_names)
    return P(*[a if (a in names) else None for a in axes])


def shard_batch(mesh: Mesh, batch):
    """Place a host array sharded over the data axis."""
    spec = spec_for(mesh, DATA_AXIS)
    return jax.device_put(batch, NamedSharding(mesh, spec))


def global_batch(mesh: Mesh, arr, spec=None):
    """Assemble a (possibly multi-process) global device array from a host
    array every process holds in full — the SPMD input convention for
    multi-host training (each host runs the same input pipeline; each
    device takes its addressable shard). Single-process: plain device_put.
    """
    import numpy as _np

    spec = spec if spec is not None else spec_for(mesh, DATA_AXIS)
    sh = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sh)
    arr = _np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sh,
                                        lambda idx: arr[idx])


def host_sharded_batch(mesh: Mesh, arr, spec=None):
    """Assemble a global device array from PER-PROCESS-DISTINCT host
    shards: each process contributes its own local rows and the global
    batch is their concatenation in process order (global batch size =
    local batch size × process_count). This is the input convention for
    host-sharded pipelines (ParallelImageDataSetIterator shardByHost),
    where each host decodes a disjoint file shard — feeding those
    through :func:`global_batch` would silently drop every row outside
    the host's own addressable slice. Single-process: plain device_put.
    """
    import numpy as _np

    spec = spec if spec is not None else spec_for(mesh, DATA_AXIS)
    sh = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sh)
    arr = _np.asarray(arr)
    global_shape = (arr.shape[0] * jax.process_count(),) + arr.shape[1:]
    return jax.make_array_from_process_local_data(sh, arr, global_shape)


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def replica_devices(n=None, mesh=None, devices=None) -> list:
    """Distinct devices to pin model-serving replicas to (ISSUE 8):
    the mesh's devices in data-axis order when a mesh is given, else
    the process's addressable devices. `n=None` takes them all; an `n`
    beyond the device count round-robins (deliberate oversubscription —
    on CPU more replicas than devices can still help when dispatches
    are host-overhead-bound)."""
    if devices is None:
        devices = (list(mesh.devices.flat) if mesh is not None
                   else jax.local_devices())
    devices = list(devices)
    if not devices:
        raise ValueError("no devices available for replica placement")
    if n is None:
        n = len(devices)
    if n < 1:
        raise ValueError(f"need n >= 1 replicas, got {n}")
    return [devices[i % len(devices)] for i in range(n)]
