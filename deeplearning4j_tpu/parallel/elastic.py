"""Elastic training: preemption-safe checkpointing + resume.

Reference capability (SURVEY.md §5 fault-tolerance row): the reference's
story is checkpoint/resume (Spark training masters re-submit failed
stages but model state rides on checkpoints), so the TPU-native design
makes that story explicit and preemption-aware rather than porting a
transport-layer recovery protocol:

- TPU pods are preempted with SIGTERM; `ElasticTrainer.fit` installs a
  handler that checkpoints synchronously before exiting (the standard
  maintenance-event drill), plus periodic every-N-iteration checkpoints
  with rotation;
- multi-host: only process 0 writes; the checkpoint directory MUST be
  shared storage (NFS/GCS-fuse) so every process resumes from the same
  file after a restart — training is SPMD-deterministic from there, so
  global state stays consistent;
- `ElasticTrainer.resume()` restores net + updater state + iteration
  counter; `fit(data, epochs=TOTAL)` treats `epochs` as the TOTAL
  budget and skips the epochs the iteration counter already covers
  (when `data` is a sized list of batches), so a preempted job rerun
  with the SAME command line completes only the remaining work.
"""

from __future__ import annotations

import os
import signal
import time

import jax


class PreemptionCheckpoint(SystemExit):
    """Raised (after checkpointing) when fit() is interrupted by
    SIGTERM/SIGINT; carries the checkpoint path — on multi-host
    processes other than 0, `path` is None (process 0 owns the write)."""

    def __init__(self, path):
        super().__init__(143)
        self.path = path


class ElasticTrainer:
    """Preemption-safe fit wrapper around MultiLayerNetwork /
    ComputationGraph (anything ModelSerializer handles)."""

    def __init__(self, net, checkpointDir, everyNIterations=100,
                 keepLast=3, saveUpdaterState=True, sharded=False):
        self.net = net
        self.dir = str(checkpointDir)
        self.every = int(everyNIterations)
        self.keep = int(keepLast)
        self.save_updater = saveUpdaterState
        # sharded=True: pod-scale checkpoints — every process writes its
        # own param shards into a checkpoint DIRECTORY (SURVEY §5
        # "sharded save for pod-scale params"); resume re-shards onto
        # the current topology, so a job can resume after a re-scale
        self.sharded = bool(sharded)
        os.makedirs(self.dir, exist_ok=True)

    # -- checkpoint files ---------------------------------------------------
    def _path(self, iteration):
        suffix = "" if self.sharded else ".zip"
        return os.path.join(self.dir,
                            f"checkpoint_{iteration:010d}{suffix}")

    @staticmethod
    def latest(checkpointDir):
        """Newest checkpoint path in the directory (zip file or sharded
        directory), or None."""
        if not os.path.isdir(checkpointDir):
            return None
        from deeplearning4j_tpu.utils.sharded_checkpoint import MANIFEST

        cps = sorted(
            f for f in os.listdir(checkpointDir)
            if f.startswith("checkpoint_") and
            (f.endswith(".zip") or os.path.exists(os.path.join(
                checkpointDir, f, MANIFEST))))
        return os.path.join(checkpointDir, cps[-1]) if cps else None

    def _write(self, iteration):
        """Checkpoint write with rotation. Single-file mode: process 0
        writes the zip. Sharded mode: EVERY process writes its shard
        directory entry (save_sharded syncs internally; the manifest
        lands only after all shards are complete)."""
        from deeplearning4j_tpu.utils import ModelSerializer

        t0 = time.perf_counter()
        path = self._path(iteration)
        is_writer = True
        if self.sharded:
            # telemetry recorded inside save_sharded (every process
            # writes a shard) — recording here too would double-count
            ModelSerializer.writeModel(self.net, path, self.save_updater,
                                       sharded=True)
        else:
            is_writer = jax.process_index() == 0
            if is_writer:
                tmp = path + ".tmp"
                ModelSerializer.writeModel(self.net, tmp,
                                           self.save_updater)
                os.replace(tmp, path)  # atomic: preempt leaves .tmp
            # EVERY process records (non-writers with 0 bytes): the
            # multi-host aggregate contract requires identical
            # instrument sets on all hosts (telemetry/aggregate.py)
            from deeplearning4j_tpu.utils.sharded_checkpoint import (
                _record_checkpoint)

            _record_checkpoint(
                "save", t0,
                os.path.getsize(path)
                if is_writer and os.path.exists(path) else 0)
            if not is_writer:
                return None
        if jax.process_index() == 0:
            from deeplearning4j_tpu.utils.sharded_checkpoint import (
                MANIFEST)
            import shutil

            complete, dead = [], []
            for f in sorted(os.listdir(self.dir)):
                if not f.startswith("checkpoint_") or f.endswith(".tmp"):
                    continue
                full = os.path.join(self.dir, f)
                if os.path.isdir(full):
                    # a manifest-less directory is a mid-save remnant
                    # (save_sharded writes the manifest last, after the
                    # cross-process sync) — it must not count toward
                    # keepLast, and it never becomes restorable
                    (complete if os.path.exists(
                        os.path.join(full, MANIFEST)) else dead).append(f)
                else:
                    complete.append(f)
            for old in complete[:-self.keep] + dead:
                full = os.path.join(self.dir, old)
                if os.path.isdir(full):
                    shutil.rmtree(full)
                else:
                    os.remove(full)
        return path

    # -- resume -------------------------------------------------------------
    @classmethod
    def resume(cls, checkpointDir, graph=False, **kw):
        """Restore the newest checkpoint into a fresh ElasticTrainer.
        Returns None when the directory holds no checkpoint (caller
        starts from scratch)."""
        path = cls.latest(checkpointDir)
        if path is None:
            return None
        from deeplearning4j_tpu.utils import ModelSerializer

        sharded = os.path.isdir(path)
        if graph:
            net = ModelSerializer.restoreComputationGraph(
                path, True, sharded=sharded)
        else:
            net = ModelSerializer.restoreMultiLayerNetwork(
                path, True, sharded=sharded)
        kw.setdefault("sharded", sharded)
        return cls(net, checkpointDir, **kw)

    # -- preemption-safe fit ------------------------------------------------
    def fit(self, data, epochs=1):
        """net.fit with periodic checkpoints and SIGTERM/SIGINT
        checkpoint-then-exit. Raises PreemptionCheckpoint (a SystemExit)
        after a signal-triggered save so process managers see rc 143.

        `epochs` is the TOTAL training budget: when `data` is a sized
        list of batches, epochs already covered by the restored
        iteration counter are skipped, so rerunning the same command
        after a preemption trains only the remainder. (For one-shot
        iterables the epoch count cannot be inferred; all `epochs`
        passes run.)"""
        try:
            iters_per_epoch = len(data)
        except TypeError:
            iters_per_epoch = None
        remaining = epochs
        if iters_per_epoch:
            done = self.net._iteration // iters_per_epoch
            remaining = max(0, epochs - done)

        preempted = {"flag": False}

        def on_signal(signum, frame):
            preempted["flag"] = True

        old_term = signal.signal(signal.SIGTERM, on_signal)
        old_int = signal.signal(signal.SIGINT, on_signal)
        last_cp = [self.net._iteration]

        class _Every:
            """Listener-shaped hook: checkpoint every N iterations and
            honor a pending preemption between iterations."""

            def __init__(self, outer):
                self.outer = outer

            def iterationDone(self, model, iteration, epoch=None,
                              loss=None):
                if preempted["flag"]:
                    path = self.outer._write(iteration)
                    raise PreemptionCheckpoint(path)
                if iteration - last_cp[0] >= self.outer.every:
                    self.outer._write(iteration)
                    last_cp[0] = iteration

        hook = _Every(self)
        prior = list(getattr(self.net, "_listeners", []))
        try:
            self.net.setListeners(*(prior + [hook]))
            if remaining > 0:
                self.net.fit(data, remaining)
            final_path = self._write(self.net._iteration)
            if preempted["flag"]:
                # a signal landed after the last in-loop check (or this
                # fit had nothing left to do): state is saved — honor
                # the termination request instead of dropping it
                raise PreemptionCheckpoint(final_path)
        finally:
            self.net.setListeners(*prior)
            signal.signal(signal.SIGTERM, old_term)
            signal.signal(signal.SIGINT, old_int)
        return self.net
