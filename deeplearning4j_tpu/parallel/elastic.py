"""Elastic training: preemption-safe checkpointing + resume.

Reference capability (SURVEY.md §5 fault-tolerance row): the reference's
story is checkpoint/resume (Spark training masters re-submit failed
stages but model state rides on checkpoints), so the TPU-native design
makes that story explicit and preemption-aware rather than porting a
transport-layer recovery protocol:

- TPU pods are preempted with SIGTERM; `ElasticTrainer.fit` installs a
  handler that checkpoints synchronously before exiting (the standard
  maintenance-event drill), plus periodic every-N-iteration checkpoints
  with rotation;
- `asyncSave=True` (resilience ISSUE 5) moves the periodic write off
  the train loop: the loop only pays for a device-side snapshot clone,
  and a background writer (resilience/async_ckpt.py) serializes and
  atomically commits — same artifact layout, interchangeable at
  restore time. Preemption and end-of-fit still write synchronously
  (durability before exit beats latency there);
- multi-host: only process 0 writes; the checkpoint directory MUST be
  shared storage (NFS/GCS-fuse) so every process resumes from the same
  file after a restart — training is SPMD-deterministic from there, so
  global state stays consistent;
- `ElasticTrainer.resume()` restores net + updater state + iteration
  counter; `fit(data, epochs=TOTAL)` treats `epochs` as the TOTAL
  budget and skips the work the iteration counter already covers
  (when `data` is a sized list of batches) — including the consumed
  PREFIX of an interrupted epoch, so a mid-epoch resume replays the
  exact batch-per-iteration schedule of an uninterrupted run and the
  resumed state is bit-identical (the Supervisor's kill-and-resume
  contract);
- `faults=` accepts a resilience FaultPlan: its iteration faults fire
  between steps and its IO faults fire inside the checkpoint writer —
  the deterministic substrate the resilience tests (and the
  supervisor) are built on.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import jax


class PreemptionCheckpoint(SystemExit):
    """Raised (after checkpointing) when fit() is interrupted by
    SIGTERM/SIGINT; carries the checkpoint path — on multi-host
    processes other than 0, `path` is None (process 0 owns the write)."""

    def __init__(self, path):
        super().__init__(143)
        self.path = path


class ElasticTrainer:
    """Preemption-safe fit wrapper around MultiLayerNetwork /
    ComputationGraph (anything ModelSerializer handles)."""

    def __init__(self, net, checkpointDir, everyNIterations=100,
                 keepLast=3, saveUpdaterState=True, sharded=False,
                 asyncSave=False, faults=None, runner=None):
        self.net = net
        self.dir = str(checkpointDir)
        self.every = int(everyNIterations)
        self.keep = int(keepLast)
        self.save_updater = saveUpdaterState
        # sharded=True: pod-scale checkpoints — every process writes its
        # own param shards into a checkpoint DIRECTORY (SURVEY §5
        # "sharded save for pod-scale params"); resume re-shards onto
        # the current topology, so a job can resume after a re-scale
        self.sharded = bool(sharded)
        self.asyncSave = bool(asyncSave)
        self.faults = faults
        # runner: the object whose .fit(data, epochs) drives training —
        # the net itself by default, or e.g. a ShardedTrainer built
        # around it (Supervisor's runner_factory)
        self.runner = runner if runner is not None else net
        self._async = None
        os.makedirs(self.dir, exist_ok=True)

    # -- checkpoint files ---------------------------------------------------
    def _path(self, iteration):
        suffix = "" if self.sharded else ".zip"
        return os.path.join(self.dir,
                            f"checkpoint_{iteration:010d}{suffix}")

    @staticmethod
    def latest(checkpointDir):
        """Newest checkpoint path in the directory (zip file or sharded
        directory), or None."""
        if not os.path.isdir(checkpointDir):
            return None
        from deeplearning4j_tpu.utils.sharded_checkpoint import MANIFEST

        cps = sorted(
            f for f in os.listdir(checkpointDir)
            if f.startswith("checkpoint_") and
            (f.endswith(".zip") or os.path.exists(os.path.join(
                checkpointDir, f, MANIFEST))))
        return os.path.join(checkpointDir, cps[-1]) if cps else None

    @staticmethod
    def latest_agreed(checkpointDir):
        """Newest checkpoint complete on EVERY host (multi-host sharded
        directories are checked manifest + all shard files; zips are
        atomic). See resilience.async_ckpt.latest_agreed."""
        from deeplearning4j_tpu.resilience.async_ckpt import latest_agreed

        return latest_agreed(checkpointDir)

    # -- rotation + GC ------------------------------------------------------
    def _rotate(self):
        """keepLast rotation + garbage collection: incomplete shard
        directories (mid-save remnants) and stale ``*.tmp`` files from
        writes a preemption cut short. A remnant is stale once a
        complete checkpoint at the same or a later iteration exists —
        an in-flight async write (always for a NEWER iteration than the
        newest commit) is never touched. Shared logic:
        resilience.async_ckpt.rotate_checkpoints."""
        from deeplearning4j_tpu.resilience.async_ckpt import (
            rotate_checkpoints)

        rotate_checkpoints(self.dir, self.keep)

    # -- checkpoint writes --------------------------------------------------
    def _checkpointer(self):
        if self._async is None:
            from deeplearning4j_tpu.resilience.async_ckpt import (
                AsyncCheckpointer)

            self._async = AsyncCheckpointer(
                self.dir, keepLast=self.keep, sharded=self.sharded,
                saveUpdater=self.save_updater, faults=self.faults,
                rotate=self._rotate)
        return self._async

    def _checkpoint(self, iteration):
        """Periodic checkpoint: async snapshot+submit, or sync write."""
        if self.asyncSave:
            self._checkpointer().checkpoint(self.net, iteration)
            return None
        return self._write(iteration)

    def _write(self, iteration):
        """Synchronous checkpoint write with rotation. Single-file
        mode: process 0 writes the zip (tmp + atomic replace). Sharded
        mode: EVERY process writes its shard directory entry
        (save_sharded syncs internally; the manifest lands only after
        all shards are complete)."""
        from deeplearning4j_tpu.utils import ModelSerializer
        from deeplearning4j_tpu.utils.checkpoint import atomic_save

        t0 = time.perf_counter()
        path = self._path(iteration)
        if self.faults is not None:
            self.faults.check_write(iteration, "write")
        pre_commit = None
        if self.faults is not None:
            pre_commit = lambda: self.faults.check_write(  # noqa: E731
                iteration, "commit")
        is_writer = True
        if self.sharded:
            # telemetry recorded inside save_sharded (every process
            # writes a shard) — recording here too would double-count
            ModelSerializer.writeModel(self.net, path, self.save_updater,
                                       sharded=True,
                                       pre_commit=pre_commit)
        else:
            is_writer = jax.process_index() == 0
            if is_writer:
                atomic_save(
                    path,
                    lambda tmp: ModelSerializer.writeModel(
                        self.net, tmp, self.save_updater),
                    pre_commit=pre_commit)
            # EVERY process records (non-writers with 0 bytes): the
            # multi-host aggregate contract requires identical
            # instrument sets on all hosts (telemetry/aggregate.py)
            from deeplearning4j_tpu.utils.sharded_checkpoint import (
                _record_checkpoint)

            _record_checkpoint(
                "save", t0,
                os.path.getsize(path)
                if is_writer and os.path.exists(path) else 0)
        from deeplearning4j_tpu.resilience.async_ckpt import note_commit

        note_commit(path, iteration, time.perf_counter() - t0, "sync")
        self._rotate()
        return path if is_writer else None

    def _durable_write(self, iteration):
        """The before-exit write: drain any in-flight async snapshot,
        then write the CURRENT state synchronously (durability beats
        latency when the process is about to die)."""
        if self._async is not None:
            self._async.drain()
        return self._write(iteration)

    def close(self):
        """Stop the background writer (drains first). Idempotent."""
        if self._async is not None:
            self._async.close()
            self._async = None

    # -- resume -------------------------------------------------------------
    @classmethod
    def resume(cls, checkpointDir, graph=False, **kw):
        """Restore the newest COMPLETE checkpoint into a fresh
        ElasticTrainer (latest_agreed: for async-written sharded
        directories a manifest alone does not certify the other hosts'
        shards — every referenced shard file must exist). Returns None
        when the directory holds no checkpoint (caller starts from
        scratch)."""
        path = cls.latest_agreed(checkpointDir)
        if path is None:
            return None
        from deeplearning4j_tpu.utils import ModelSerializer

        sharded = os.path.isdir(path)
        if graph:
            net = ModelSerializer.restoreComputationGraph(
                path, True, sharded=sharded)
        else:
            net = ModelSerializer.restoreMultiLayerNetwork(
                path, True, sharded=sharded)
        kw.setdefault("sharded", sharded)
        return cls(net, checkpointDir, **kw)

    # -- preemption-safe fit ------------------------------------------------
    def fit(self, data, epochs=1):
        """net.fit with periodic checkpoints and SIGTERM/SIGINT
        checkpoint-then-exit. Raises PreemptionCheckpoint (a SystemExit)
        after a signal-triggered save so process managers see rc 143.

        `epochs` is the TOTAL training budget: when `data` is a sized
        list of batches, work already covered by the restored iteration
        counter is skipped — full epochs AND the consumed prefix of an
        interrupted epoch, so rerunning the same command after a
        preemption trains exactly the remainder, batch-aligned with an
        uninterrupted run (bit-identical resume). (For one-shot
        iterables the position cannot be inferred; all `epochs` passes
        run.)"""
        try:
            iters_per_epoch = len(data)
        except TypeError:
            iters_per_epoch = None
        remaining, offset = epochs, 0
        if iters_per_epoch:
            done = self.net._iteration // iters_per_epoch
            offset = self.net._iteration % iters_per_epoch
            remaining = max(0, epochs - done)
            if hasattr(data, "set_epoch"):
                # epoch-aware iterators (seeded epoch shuffling): tell
                # the data which epoch the checkpoint left off in so a
                # resumed run replays the SAME per-epoch batch->file
                # assignment as an uninterrupted one (bit-identical
                # resume extends to shuffled input)
                data.set_epoch(done)

        preempted = {"flag": False}

        def on_signal(signum, frame):
            preempted["flag"] = True

        # signal handlers can only be installed from the main thread;
        # a fit running on a worker thread (the fleet fine-tuner) skips
        # signal-based preemption and keeps the periodic checkpoints
        on_main = (threading.current_thread()
                   is threading.main_thread())
        old_term = old_int = None
        if on_main:
            old_term = signal.signal(signal.SIGTERM, on_signal)
            old_int = signal.signal(signal.SIGINT, on_signal)
        last_cp = [self.net._iteration]

        class _Every:
            """Listener-shaped hook: checkpoint every N iterations and
            honor a pending preemption between iterations."""

            def __init__(self, outer):
                self.outer = outer

            def iterationDone(self, model, iteration, epoch=None,
                              loss=None):
                if preempted["flag"]:
                    path = self.outer._durable_write(iteration)
                    raise PreemptionCheckpoint(path)
                if iteration - last_cp[0] >= self.outer.every:
                    self.outer._checkpoint(iteration)
                    last_cp[0] = iteration

        hook = _Every(self)
        prior = list(getattr(self.net, "_listeners", []))
        # the fault injector runs BEFORE the checkpoint hook so an
        # injected preemption signal is honored within the same
        # iteration (mirroring a real SIGTERM landing mid-step)
        injected = ([self.faults.listener()] if self.faults is not None
                    else [])
        from deeplearning4j_tpu.resilience.async_ckpt import (
            mark_active, mark_idle)
        from deeplearning4j_tpu.telemetry import tracing

        # trace root for the WHOLE elastic run (ISSUE 10): the nested
        # net.fit spans AND the checkpoint snapshot/write spans (taken
        # from the in-loop listener hook) parent here, so one sampled
        # run exports as one connected tree
        tspan = tracing.trace_or_span("train.elastic", every=self.every)
        tspan.__enter__()
        import sys as _sys

        mark_active()   # checkpoint staleness judgements apply in here
        try:
            self.net.setListeners(*(prior + injected + [hook]))
            if remaining > 0 and offset:
                # finish the interrupted epoch first: replay only the
                # batches the checkpointed iteration count has not
                # consumed, keeping batch<->iteration alignment exact
                try:
                    partial = data[offset:]
                except TypeError:
                    import itertools

                    partial = list(itertools.islice(iter(data), offset,
                                                    None))
                if len(partial):
                    self.runner.fit(partial, 1)
                remaining -= 1
            if remaining > 0:
                self.runner.fit(data, remaining)
            final_path = self._durable_write(self.net._iteration)
            if preempted["flag"]:
                # a signal landed after the last in-loop check (or this
                # fit had nothing left to do): state is saved — honor
                # the termination request instead of dropping it
                raise PreemptionCheckpoint(final_path)
        finally:
            tspan.__exit__(*_sys.exc_info())
            mark_idle()
            self.net.setListeners(*prior)
            if on_main:
                signal.signal(signal.SIGTERM, old_term)
                signal.signal(signal.SIGINT, old_int)
        return self.net
