"""Pipeline-parallel training for user-built MultiLayerNetworks.

VERDICT r3 item 3: pipeline parallelism must be reachable from the
public net API, not only from the BERT flagship. The reference's L5
wraps arbitrary user nets (`ParallelWrapper.fit(anyNet)` — SURVEY.md
§2.6); its pipeline row is "NO", so this is additive capability with
the reference's wrap-any-net ergonomics.

How a net becomes a pipeline:
- the trainer locates the longest contiguous run of layers with
  IDENTICAL structure (same class, same param-tree shapes/dtypes,
  stateless, no preprocessor inside the run) — e.g. the stacked Dense
  trunk of an MLP or the stacked LSTM trunk of TextGenerationLSTM;
- the run is split into S = mesh.shape['pipe'] stages; per-layer param
  trees are stacked to leaves [S, run/S, ...] sharded over `pipe`;
- layers BEFORE the run (input adapters) and AFTER it (incl. the output
  layer's loss) run replicated on every device on the flat batch — they
  are assumed small next to the trunk;
- the GPipe schedule comes from parallel.pipeline.pipeline_apply; the
  backward pipeline falls out of jax.grad reversing every ppermute.

Heterogeneous stacks are rejected loudly with the per-layer structure
signatures so the user can see why (VERDICT r3: "reject heterogeneous
stacks loudly"). Same restriction as BertPipelineTrainer for layers
carrying aux losses (MoE): the stage scan would drop them.

Parity contract: with the same seed/updater and dropout off, the loss
sequence matches MultiLayerNetwork.fit on one device step for step —
tested in tests/test_pipeline_trainer.py on the 8-device CPU mesh.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, PIPE_AXIS, spec_for)
from deeplearning4j_tpu.parallel.pipeline import pipeline_apply


def _cfg_fingerprint(obj):
    """Primitive-valued config attrs of a layer/updater — the part of
    its behavior not visible in param shapes (activation, l1/l2,
    dropout, learning rate, ...)."""
    return tuple(sorted(
        (k, v) for k, v in vars(obj).items()
        if isinstance(v, (int, float, str, bool, type(None)))))


def _layer_signature(net, i):
    """Structure AND config signature deciding stage-stackability: class,
    param leaf shapes/dtypes, full primitive config (activation etc.),
    updater config, presence of preprocessor. Config is included because
    the stage scan executes every run layer through layer lo's apply —
    two Dense layers with equal shapes but different activations must NOT
    be stacked (they'd silently both run with lo's activation)."""
    lr = net.layers[i]
    params = net._params[i]
    leaves = jax.tree_util.tree_leaves(params)
    treedef = jax.tree_util.tree_structure(params)
    upd = net._layer_updater(i)
    return (
        type(lr).__name__,
        str(treedef),
        tuple((tuple(l.shape), str(l.dtype)) for l in leaves),
        _cfg_fingerprint(lr),
        (type(upd).__name__, _cfg_fingerprint(upd)),
        net.conf.preprocessors[i] is not None,
    )


def find_stackable_run(net, n_stages):
    """Longest contiguous run of identically-structured layers (excluding
    the output layer) whose length is divisible by n_stages and >= it.
    Returns (lo, hi). Raises with the full signature table if none."""
    n = len(net.layers) - 1  # never include the output layer
    sigs = [_layer_signature(net, i) for i in range(n)]
    best = None
    i = 0
    while i < n:
        j = i + 1
        while j < n and sigs[j] == sigs[i] \
                and not net.conf.preprocessors[j]:
            j += 1
        run = (j - i) - (j - i) % n_stages
        if run >= max(n_stages, 2) and (best is None
                                        or run > best[1] - best[0]):
            best = (i, i + run)
        i = j
    if best is None:
        table = "\n".join(f"  layer {i}: {s[0]} params={s[2]}"
                          for i, s in enumerate(sigs))
        raise ValueError(
            f"no contiguous run of >= max({n_stages}, 2) identically-"
            f"structured layers divisible by pipe={n_stages} — this net "
            f"cannot be stage-stacked. Layer structure:\n{table}")
    return best


def stack_run_params(param_list, n_stages):
    """[R layers of identical trees] -> one tree with leaves
    [S, R/S, ...]."""
    r = len(param_list)
    per = r // n_stages
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves).reshape(
            (n_stages, per) + leaves[0].shape), *param_list)


def unstack_run_params(stacked):
    """Inverse of stack_run_params -> list of R per-layer trees."""
    lead = jax.tree_util.tree_leaves(stacked)[0]
    s, per = lead.shape[0], lead.shape[1]
    return [jax.tree_util.tree_map(lambda a, si=si, li=li: a[si, li],
                                   stacked)
            for si in range(s) for li in range(per)]


class PipelineParallelTrainer:
    """GPipe training of a MultiLayerNetwork on a dp x pp mesh.

    net must be init()'d; its params are MOVED into the trainer
    (stage-stacked + sharded); call sync_to_net() to write the trained
    values back for evaluation/serialization via the net's own API.
    """

    def __init__(self, net, mesh: Mesh, microbatches: int = 4,
                 run: tuple | None = None):
        net._check_init()
        self.net = net
        self.mesh = mesh
        self.microbatches = microbatches
        self.n_stages = mesh.shape.get(PIPE_AXIS, 1)
        self.lo, self.hi = run or find_stackable_run(net, self.n_stages)
        self._validate()

        stacked = stack_run_params(net._params[self.lo:self.hi],
                                   self.n_stages)
        outer = [net._params[i] for i in range(len(net.layers))
                 if not (self.lo <= i < self.hi)]
        self.params = {"outer": outer, "run": stacked}

        repl = NamedSharding(mesh, P())
        stage_sh = NamedSharding(mesh, spec_for(mesh, PIPE_AXIS))
        self.p_sh = {
            "outer": jax.tree_util.tree_map(lambda _: repl, outer),
            "run": jax.tree_util.tree_map(lambda _: stage_sh, stacked),
        }
        self.params = jax.device_put(self.params, self.p_sh)
        upds = self._updaters()
        self.opt = {
            "outer": [u.init_state(p) if p else ()
                      for u, p in zip(upds["outer"], outer)],
            "run": upds["run"].init_state(stacked),
        }
        self.o_sh = jax.tree_util.tree_map(
            lambda _: repl, self.opt,
            is_leaf=lambda x: isinstance(x, jnp.ndarray))
        # run-group opt-state leaves are stage-stacked like the params
        self.o_sh["run"] = jax.tree_util.tree_map(
            lambda _: stage_sh, self.opt["run"])
        self.opt = jax.device_put(self.opt, self.o_sh)
        self._flat_sh = NamedSharding(mesh, spec_for(mesh, DATA_AXIS))
        self._step_fn = None
        self._step_plan = None   # health BuildPlan compiled into it
        self._monitor = None     # cached for standalone train_step calls
        self._monitor_plan = None
        self._it = 0
        self.lossCurve: list = []

    # -- validation ---------------------------------------------------------
    def _validate(self):
        net, lo, hi = self.net, self.lo, self.hi
        if (hi - lo) % self.n_stages:
            raise ValueError(
                f"run length {hi - lo} not divisible by "
                f"pipe={self.n_stages}")
        # an explicit run= tuple must pass the same homogeneity bar the
        # auto-detection enforces — otherwise heterogeneous layers would
        # silently execute with layer lo's config
        sig0 = _layer_signature(net, lo)
        for i in range(lo, hi):
            if _layer_signature(net, i) != sig0:
                raise ValueError(
                    f"run layer {i} differs in structure/config from "
                    f"layer {lo}; stage stacking requires identical "
                    "layers (class, shapes, activation, updater, "
                    "regularization)")
            if net.conf.preprocessors[i] is not None:
                raise ValueError(
                    f"layer {i} has an input preprocessor inside the "
                    "pipelined run; preprocessors are only supported "
                    "before/after the run")
        for i, lr in enumerate(net.layers):
            # EVERY layer runs with an empty state dict and rng=None in
            # this trainer: stateful layers (BatchNormalization running
            # stats, aux-loss channels) and dropout would silently
            # train differently from MultiLayerNetwork.fit — reject.
            if net._states[i]:
                raise ValueError(
                    f"layer {i} ({type(lr).__name__}) carries state "
                    "(running stats / aux-loss / streaming); "
                    "PipelineParallelTrainer drops layer state — train "
                    "this net data-parallel instead")
            if getattr(lr, "dropOut", None):
                raise ValueError(
                    f"layer {i} ({type(lr).__name__}) configures "
                    "dropout; this trainer runs layers without an RNG "
                    "(parity contract is dropout-off) — remove dropOut "
                    "or train data-parallel")
            if getattr(lr, "gradientNormalization", None):
                raise ValueError(
                    f"layer {i} sets gradientNormalization: per-layer "
                    "norms differ across a stacked stage group — "
                    "remove it or train data-parallel")

    def _updaters(self):
        net = self.net
        outer = [net._layer_updater(i) for i in range(len(net.layers))
                 if not (self.lo <= i < self.hi)]
        return {"outer": outer, "run": net._layer_updater(self.lo)}

    # -- forward ------------------------------------------------------------
    def _stage_fn(self, stage_params, x, mb_idx):
        del mb_idx  # deterministic stages (dropout off — parity contract)
        proto = self.net.layers[self.lo]

        def body(h, lp):
            y, _ = proto.apply(lp, {}, h, True, None)
            return y, None

        y, _ = jax.lax.scan(body, x, stage_params)
        return y

    def _loss(self, params, f, l, lmask):
        net, lo, hi = self.net, self.lo, self.hi
        # mixed-precision policy (ISSUE 4): cast master params + input
        # to the compute dtype INSIDE the differentiated function — the
        # cast happens before the pipeline's shard_map, so stage params
        # stay sharded and the transpose upcasts grads back to the
        # master dtype. (Dynamic loss scaling is not wired through this
        # trainer; bf16's fp32-range exponents make unscaled pipeline
        # training safe — see docs/PRECISION.md.)
        pol = net._precision_policy()
        if pol.is_mixed:
            from deeplearning4j_tpu.precision import cast_floating

            params = cast_floating(params, pol.compute_jnp)
        outer = iter(params["outer"])
        outer_params = [
            (next(outer) if not (lo <= i < hi) else None)
            for i in range(len(net.layers))
        ]
        m = self.microbatches
        x = jnp.asarray(f, pol.compute_jnp) \
            if jnp.issubdtype(jnp.asarray(f).dtype, jnp.floating) else f

        from deeplearning4j_tpu.nn.multilayer import _apply_preprocessor

        # head (flat batch, replicated)
        for i in range(lo):
            x = _apply_preprocessor(net.conf.preprocessors[i], x)
            x, _ = net.layers[i].apply(outer_params[i], {}, x, True, None)
        # pipelined trunk ([M, mb, ...])
        x_mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])
        y_mb = pipeline_apply(self._stage_fn, params["run"], x_mb,
                              self.mesh)
        h = y_mb.reshape((-1,) + y_mb.shape[2:])
        # tail + loss (flat batch, replicated)
        out_idx = len(net.layers) - 1
        for i in range(hi, out_idx):
            x_ = _apply_preprocessor(net.conf.preprocessors[i], h)
            h, _ = net.layers[i].apply(outer_params[i], {}, x_, True,
                                       None)
        h = _apply_preprocessor(net.conf.preprocessors[out_idx], h)
        loss = net.layers[out_idx].compute_loss(
            outer_params[out_idx], h, l, lmask)
        # L1/L2 regularization, mirroring MultiLayerNetwork._loss_from
        reg = 0.0
        for i, lr in enumerate(net.layers):
            p_i = outer_params[i]
            if lo <= i < hi:
                continue  # handled stacked below
            if not p_i:
                continue
            if lr.l2:
                reg = reg + lr.l2 * 0.5 * sum(
                    jnp.sum(w * w)
                    for w in jax.tree_util.tree_leaves(p_i))
            if lr.l1:
                reg = reg + lr.l1 * sum(
                    jnp.sum(jnp.abs(w))
                    for w in jax.tree_util.tree_leaves(p_i))
        proto = net.layers[lo]
        if proto.l2:
            reg = reg + proto.l2 * 0.5 * sum(
                jnp.sum(w * w)
                for w in jax.tree_util.tree_leaves(params["run"]))
        if proto.l1:
            reg = reg + proto.l1 * sum(
                jnp.sum(jnp.abs(w))
                for w in jax.tree_util.tree_leaves(params["run"]))
        return loss + reg

    # -- one donated compiled step ------------------------------------------
    def health_labels(self):
        """Health-row labels: the outer layers (original indices, in
        order), the stage-stacked run as ONE aggregated row, then the
        loss row."""
        from deeplearning4j_tpu.telemetry import health as _health

        net, lo, hi = self.net, self.lo, self.hi
        labels = [f"{i}:{type(net.layers[i]).__name__}"
                  for i in range(len(net.layers)) if not (lo <= i < hi)]
        labels.append(f"run[{lo}:{hi}]:{type(net.layers[lo]).__name__}")
        return _health.with_loss_row(labels)

    def _build(self, health_plan=None):
        from deeplearning4j_tpu.telemetry import health as _health

        plan = health_plan or _health.INACTIVE
        repl = NamedSharding(self.mesh, P())
        upds = self._updaters()

        def step(params, opt, f, l, lmask, it):
            loss, grads = jax.value_and_grad(self._loss)(params, f, l,
                                                         lmask)
            new_outer_p, new_outer_o, stats = [], [], []
            for u, p, g, o in zip(upds["outer"], params["outer"],
                                  grads["outer"], opt["outer"]):
                if not p:
                    new_outer_p.append(p)
                    new_outer_o.append(o)
                    if plan.collect:
                        stats.append(_health.zero_stats())
                    continue
                upd, o2 = u.apply_mixed(g, o, p, it)
                new_outer_p.append(jax.tree_util.tree_map(
                    lambda a, b: a - b, p, upd))
                new_outer_o.append(o2)
                if plan.collect:
                    stats.append(_health.layer_stats(g, upd,
                                                     new_outer_p[-1]))
            upd, run_o = upds["run"].apply_mixed(grads["run"], opt["run"],
                                                 params["run"], it)
            new_run = jax.tree_util.tree_map(lambda a, b: a - b,
                                             params["run"], upd)
            new_params = {"outer": new_outer_p, "run": new_run}
            new_opt = {"outer": new_outer_o, "run": run_o}
            health = None
            if plan.collect:
                stats.append(_health.layer_stats(grads["run"], upd,
                                                 new_run))
                stats.append(_health.loss_stats(loss))
                health = _health.stack_stats(stats)
            if plan.skip:
                ok = _health.step_ok(health)
                new_params = _health.keep_if(ok, new_params, params)
                new_opt = _health.keep_if(ok, new_opt, opt)
            return loss, new_params, new_opt, health

        out_health = (repl,) if plan.collect else (None,)
        return jax.jit(
            step,
            in_shardings=(self.p_sh, self.o_sh, self._flat_sh,
                          self._flat_sh, repl, repl),
            out_shardings=(repl, self.p_sh, self.o_sh) + out_health,
            donate_argnums=(0, 1),
        )

    def _refresh_step(self):
        from deeplearning4j_tpu.telemetry import health as _health

        plan = _health.build_plan(self.net._listeners)
        if self._step_fn is None or self._step_plan != plan:
            self._step_fn = self._build(plan)
            self._step_plan = plan
        return plan

    def train_step(self, features, labels, labels_mask=None,
                   _tele=None, _hm=None) -> float:
        import time

        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.telemetry import health as _health

        plan = self._refresh_step()
        # fit() passes its per-loop instruments; standalone calls do one
        # flag check (None when telemetry is disabled: no registry calls)
        tele = _tele if _tele is not None else \
            telemetry.loop_instruments("pipeline")
        if _hm is not None:
            hm = _hm
        else:
            # cache the monitor across standalone calls (keyed on the
            # plan, so a cached None for the disabled case also sticks):
            # building the per-layer instrument bindings per step would
            # defeat the list-indexing publish path
            if self._monitor_plan != plan:
                self._monitor = _health.monitor_for(
                    "pipeline", self.health_labels(),
                    self.net._listeners)
                self._monitor_plan = plan
            hm = self._monitor
        f = np.asarray(features)
        if f.shape[0] % self.microbatches:
            raise ValueError(
                f"batch {f.shape[0]} not divisible by microbatches="
                f"{self.microbatches}")
        if tele is not None:
            t0 = time.perf_counter()
        it_used = self._it
        loss, self.params, self.opt, health = self._step_fn(
            self.params, self.opt, jnp.asarray(f),
            jnp.asarray(np.asarray(labels)),
            None if labels_mask is None else jnp.asarray(labels_mask),
            jnp.asarray(it_used, jnp.int32))
        self._it += 1
        val = float(loss)
        if tele is not None:
            # float(loss) above synced, so this span is the TRUE device
            # step time for the pipeline schedule
            tele.record_step(time.perf_counter() - t0, f.shape[0])
        if hm is not None:
            hm.on_step(it_used, health)
            if _hm is None:
                # standalone call: float(loss) above already synced the
                # step, so draining the pending slot costs no extra sync
                hm.flush()
        self.lossCurve.append(val)
        return val

    def fit(self, data, epochs: int = 1):
        """data: iterable of (features, labels) or DataSet-likes."""
        import time

        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.telemetry import health as _health

        tele = telemetry.loop_instruments("pipeline")
        hm = _health.monitor_for("pipeline", self.health_labels(),
                                 self.net._listeners)
        for _ in range(epochs):
            it = iter(data)
            while True:
                if tele is not None:
                    t_etl = time.perf_counter()
                d = next(it, None)
                if d is None:
                    break
                if tele is not None:
                    tele.record_etl_wait(time.perf_counter() - t_etl)
                if hasattr(d, "getFeatures"):
                    lm = None
                    if hasattr(d, "getLabelsMaskArray"):
                        lm = d.getLabelsMaskArray()
                        lm = None if lm is None else np.asarray(lm)
                    self.train_step(np.asarray(d.getFeatures()),
                                    np.asarray(d.getLabels()),
                                    labels_mask=lm, _tele=tele, _hm=hm)
                else:
                    self.train_step(*d, _tele=tele, _hm=hm)
            if hasattr(data, "reset"):
                data.reset()
        if hm is not None:
            hm.flush()   # drain the one-behind slot (HALT may raise here)
        return self

    def sync_to_net(self):
        """Write trained params back into the wrapped net (host copy), so
        the net's own output/evaluate/serialization APIs see them."""
        net, lo, hi = self.net, self.lo, self.hi
        params = jax.device_get(self.params)
        run_list = unstack_run_params(params["run"])
        outer = iter(params["outer"])
        for i in range(len(net.layers)):
            if lo <= i < hi:
                net._params[i] = jax.tree_util.tree_map(
                    jnp.asarray, run_list[i - lo])
            else:
                net._params[i] = jax.tree_util.tree_map(
                    jnp.asarray, next(outer))
        return net
