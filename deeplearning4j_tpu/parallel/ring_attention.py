"""Ring attention: sequence/context parallelism over the 'seq' mesh axis.

Reference capability: NONE — SURVEY.md §5 "Long-context" records that the
reference has no sequence parallelism (TBPTT only); this is the additive
TPU-native answer it prescribes: shard the sequence axis across devices,
rotate K/V blocks around the ring with ppermute while accumulating
flash-style online softmax, so attention memory per device is O(T/n) and
the K/V transfer overlaps with compute on ICI neighbors.

Layout: q, k, v are [batch, heads, seq, head_dim] GLOBAL arrays sharded on
the seq axis; ring_attention returns the same-sharded output."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # pre-0.6 jax ships it under experimental
    from jax.experimental.shard_map import shard_map

from deeplearning4j_tpu.parallel.mesh import SEQ_AXIS


def _ring_attention_local(q, k, v, axis_name, causal, scale, n):
    """Runs per-device under shard_map. q,k,v: [B,H,Tl,D] local blocks.
    `n` is the static ring size (mesh axis size; lax.axis_size is not
    available on every supported jax)."""
    my_rank = lax.axis_index(axis_name)
    b, h, tl, d = q.shape
    q_pos = my_rank * tl + jnp.arange(tl)          # global query positions

    def body(i, carry):
        m, l, o, kb, vb = carry
        # the block we currently hold started at rank (my_rank - i) mod n
        src = jnp.mod(my_rank - i, n)
        k_pos = src * tl + jnp.arange(tl)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kb) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        blk_max = jnp.max(s, axis=-1)                       # [B,H,Tl]
        new_m = jnp.maximum(m, blk_max)
        # guard fully-masked rows (blk_max = -inf)
        new_m_safe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - new_m_safe[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        correction = jnp.exp(
            jnp.where(jnp.isfinite(m), m - new_m_safe, -jnp.inf))
        correction = jnp.where(jnp.isfinite(m), correction, 0.0)
        new_l = l * correction + jnp.sum(p, axis=-1)
        new_o = o * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vb)
        # rotate K/V one step around the ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return new_m, new_l, new_o, kb, vb

    m0 = jnp.full((b, h, tl), -jnp.inf, q.dtype)
    l0 = jnp.zeros((b, h, tl), q.dtype)
    o0 = jnp.zeros((b, h, tl, d), q.dtype)
    m, l, o, _, _ = lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    return o / jnp.maximum(l, 1e-30)[..., None]


def ring_attention(q, k, v, mesh: Mesh, causal: bool = False,
                   axis: str = SEQ_AXIS, scaled: bool = True):
    """Sequence-parallel attention. q,k,v: [B,H,T,D] sharded over T."""
    if axis not in mesh.axis_names:
        # degenerate mesh (seq axis size 1): plain attention
        return _dense_attention(q, k, v, causal, scaled)
    scale = 1.0 / math.sqrt(q.shape[-1]) if scaled else 1.0
    spec = P(None, None, axis, None)
    local = functools.partial(_ring_attention_local, axis_name=axis,
                              causal=causal, scale=scale,
                              n=mesh.shape[axis])
    try:
        fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    except TypeError:  # pre-0.6 jax spells the kwarg check_rep
        fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
    return fn(q, k, v)


def _dense_attention(q, k, v, causal, scaled):
    scale = 1.0 / math.sqrt(q.shape[-1]) if scaled else 1.0
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)
