"""Tensor-parallel sharding recipes.

Reference capability: NONE — SURVEY.md §2.6 records TP as absent in the
reference; per its prescription TP is provided via GSPMD sharding
annotations on the lowered net, not a new runtime: build a param_specs
pytree (same structure as net._params) and hand it to ShardedTrainer.
XLA then partitions the matmuls over the 'model' axis and inserts the
activation all-reduces (Megatron-style column/row parallel pairs)."""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS


def replicated_specs(net):
    import jax

    return jax.tree_util.tree_map(lambda _: P(), net._params)


def alternating_dense_specs(net, axis: str = MODEL_AXIS, axis_size=None):
    """Megatron MLP pattern over a dense stack: even dense layers are
    column-parallel (W [in, out] sharded on out, bias sharded), odd ones
    row-parallel (W sharded on in, bias replicated). XLA inserts one
    all-reduce after each row-parallel matmul. Output layers and any dim
    not divisible by axis_size stay replicated (a small class head does
    not benefit from TP anyway)."""
    from deeplearning4j_tpu.nn.conf.layers import (
        DenseLayer, OUTPUT_LAYER_TYPES)

    def divisible(dim):
        return axis_size is None or dim % axis_size == 0

    specs = []
    col = True  # start column-parallel
    for i, lr in enumerate(net.layers):
        p = net._params[i]
        if isinstance(lr, DenseLayer) and "W" in p \
                and not isinstance(lr, OUTPUT_LAYER_TYPES):
            w_shape = p["W"].shape
            if col and divisible(w_shape[1]):
                s = {"W": P(None, axis)}
                if "b" in p:
                    s["b"] = P(axis)
                col = False
            elif not col and divisible(w_shape[0]):
                s = {"W": P(axis, None)}
                if "b" in p:
                    s["b"] = P()
                col = True
            else:
                s = {k: P() for k in p}
            specs.append(s)
        else:
            specs.append({k: P() for k in p})
    return specs
