"""Continuous profiling (ISSUE 18 tentpole): stack-level attribution
for the host side of the stack.

Every observability layer before this PR says *how much* (metrics),
*when* (traces, time series), or *what memory* (the HBM ledger) — none
says **which code** is burning wall-clock time. Three pieces close
that gap:

1. **Always-on continuous sampler** (`ContinuousProfiler`): a low-rate
   (~19 Hz default — a prime, so it cannot alias against second- or
   10ms-periodic work) wall-clock sampler over ``sys._current_frames()``
   that folds every thread's stack into a bounded ring of collapsed
   stacks (``frame;frame;frame count`` — flamegraph.pl-ready), served
   at ``GET /debug/profile/cpu?window=``. Each sample is attributed to
   a *subsystem* (serving / batcher / replica / decode / etl / prefetch
   / fleet / ckpt / train / ui / telemetry / other) via a thread-role
   registry, the ``dl4j:<subsystem>:<role>`` thread-name convention,
   and module-path heuristics — the collapsed stack's root frame IS the
   subsystem, so flamegraphs group by it and
   ``dl4j_profile_self_seconds_total{subsystem}`` (scrape-only: per-host
   thread populations differ) integrates the same attribution.

2. **On-demand deep capture** (``capture()``): a single-flight
   (`CaptureBusyError` → HTTP 409) high-rate (~199 Hz) sample plus a
   ``jax.profiler.trace()`` device capture, committed into a
   content-addressed artifact directory via the shared ``atomic_save``
   seam — listable and downloadable at ``/debug/profile/captures``.

3. **Fleet federation** lives in fleet/router.py
   (``GET /debug/fleet/profile``): the router fans this module's
   collapsed output from every live worker and prefixes a worker
   frame, one request → one whole-fleet flamegraph.

Disabled contract (the PR-1 rule): under ``telemetry.disable()`` there
is ZERO sampler thread (``start()`` refuses to spawn; a running loop
exits on the next tick) and ``sample_now()`` returns before touching
``sys._current_frames()`` or the registry — CountingStub-asserted in
tests/test_profiler.py.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import sys
import threading
import time
from collections import deque

from deeplearning4j_tpu.telemetry import registry as _registry
from deeplearning4j_tpu.utils.checkpoint import atomic_save

log = logging.getLogger("deeplearning4j_tpu")

DEFAULT_HZ = 19.0            # prime: never aliases periodic work
DEFAULT_BUCKET_SECONDS = 5.0
DEFAULT_CAPACITY = 720       # 1 h of history at the default bucket
DEFAULT_MAX_STACKS = 512     # unique stacks per bucket before folding
DEFAULT_MAX_DEPTH = 48
CAPTURE_HZ = 199.0           # deep-capture rate (also prime)
CAPTURE_MAX_SECONDS = 60.0

SELF_SECONDS_HELP = ("Estimated wall-clock seconds observed per "
                     "subsystem by the continuous profiler "
                     "(samples x sampling period; scrape-only)")

#: the canonical subsystem taxonomy (docs/OBSERVABILITY.md table).
SUBSYSTEMS = ("serving", "batcher", "replica", "decode", "etl",
              "prefetch", "fleet", "ckpt", "train", "ui", "telemetry",
              "other")

# module-path heuristics: first fragment match on the in-package
# relative path, scanned leaf-most frame first (the most specific
# frame wins — a batcher thread parked in queue.get still shows
# serving/batcher.py deeper in its stack)
_MODULE_MAP = (
    ("serving/batcher", "batcher"),
    ("serving/replica", "replica"),
    ("serving/decode", "decode"),
    ("serving/prefill", "decode"),
    ("serving/speculative", "decode"),
    ("serving/prefix_cache", "decode"),
    ("serving/kv_cache", "decode"),
    ("serving/", "serving"),
    ("clustering/", "serving"),
    ("fleet/", "fleet"),
    ("datasets/prefetch", "prefetch"),
    ("datasets/", "etl"),
    ("resilience/", "ckpt"),
    ("telemetry/", "telemetry"),
    ("analysis/", "telemetry"),
    ("ui/", "ui"),
    ("nn/", "train"),
    ("graph/", "train"),
    ("optimize/", "train"),
    ("parallel/", "train"),
    ("autodiff/", "train"),
    ("rl/", "train"),
    ("compilestore", "train"),
)

_PKG_MARKER = "deeplearning4j_tpu" + os.sep

_state = {"profiler": None}
_lock = threading.Lock()


class CaptureBusyError(RuntimeError):
    """A deep capture is already in flight (single-flight contract —
    the HTTP layer maps this to 409)."""


def thread_name(subsystem: str, role: str) -> str:
    """The ``dl4j:<subsystem>:<role>`` naming convention every
    long-lived package thread follows, so wall-clock samples and
    native thread dumps attribute without a registry entry."""
    return f"dl4j:{subsystem}:{role}"


def _rel_path(filename: str) -> str | None:
    """In-package relative path ('serving/batcher.py') or None."""
    idx = filename.rfind(_PKG_MARKER)
    if idx < 0:
        return None
    return filename[idx + len(_PKG_MARKER):].replace(os.sep, "/")


def _frame_label(frame) -> str:
    """'serving.batcher:_coalesce' for package frames,
    'threading:wait' for everything else."""
    code = frame.f_code
    rel = _rel_path(code.co_filename)
    if rel is not None:
        mod = rel[:-3] if rel.endswith(".py") else rel
        mod = mod.replace("/", ".")
    else:
        base = os.path.basename(code.co_filename)
        mod = base[:-3] if base.endswith(".py") else base
    name = code.co_name
    return f"{mod}:{name}".replace(";", "_")


def collapse_frame(frame, max_depth=DEFAULT_MAX_DEPTH) -> str:
    """Fold one thread's stack root-first into the collapsed format
    ('root;...;leaf'). Depth beyond ``max_depth`` folds into a single
    '(deep)' frame at the root so leaf frames survive."""
    labels = []
    while frame is not None:
        labels.append(_frame_label(frame))
        frame = frame.f_back
    labels.reverse()                       # root first
    if len(labels) > max_depth:
        labels = ["(deep)"] + labels[-(max_depth - 1):]
    return ";".join(labels)


def _heuristic_subsystem(frame) -> str | None:
    """Leaf-to-root scan for the first in-package frame's subsystem."""
    while frame is not None:
        rel = _rel_path(frame.f_code.co_filename)
        if rel is not None:
            for fragment, subsystem in _MODULE_MAP:
                if rel.startswith(fragment):
                    return subsystem
        frame = frame.f_back
    return None


def parse_collapsed(text: str) -> dict:
    """Round-trip reader for the collapsed format: 'stack count' lines
    back into a {collapsed: count} dict (merging duplicates)."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        if not stack:
            continue
        out[stack] = out.get(stack, 0) + int(count)
    return out


def render_collapsed(stacks: dict) -> str:
    """{collapsed: count} → 'stack count\\n' lines, largest first."""
    lines = [f"{stack} {int(count)}" for stack, count in
             sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines) + ("\n" if lines else "")


def attribution(stacks: dict) -> dict:
    """Per-subsystem sample counts from a collapsed dict (the root
    frame is the subsystem by construction)."""
    out: dict = {}
    for stack, count in stacks.items():
        subsystem = stack.split(";", 1)[0]
        out[subsystem] = out.get(subsystem, 0) + int(count)
    return out


class ContinuousProfiler:
    """The always-on wall-clock sampler: one ``sys._current_frames()``
    pass per tick, folded into a bounded ring of per-bucket collapsed
    stacks. ``sample_now`` is the only hot entry point and returns
    before touching anything while telemetry is disabled."""

    def __init__(self, hz=DEFAULT_HZ, bucket_seconds=DEFAULT_BUCKET_SECONDS,
                 capacity=DEFAULT_CAPACITY, max_stacks=DEFAULT_MAX_STACKS,
                 max_depth=DEFAULT_MAX_DEPTH):
        self.hz = float(hz)
        self.bucket_seconds = float(bucket_seconds)
        self.capacity = int(capacity)
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self._buckets: deque = deque(maxlen=self.capacity)
        self._roles: dict = {}       # thread ident -> subsystem
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self._samples = 0
        self._counter = None         # lazy scrape-only family

    # -- attribution ---------------------------------------------------------
    def register_thread(self, subsystem: str, role: str = "",
                        ident: int | None = None):
        """Explicitly attribute a thread (defaults to the caller) to a
        subsystem — the registry outranks name parsing and heuristics.
        Threads that cannot be renamed (pool workers) use this."""
        if ident is None:
            ident = threading.get_ident()
        with self._lock:
            self._roles[int(ident)] = str(subsystem)
        return ident

    def unregister_thread(self, ident: int | None = None):
        if ident is None:
            ident = threading.get_ident()
        with self._lock:
            self._roles.pop(int(ident), None)

    def subsystem_of(self, ident, name, frame) -> str:
        """Registry > dl4j:<subsystem>:<role> name > module-path
        heuristics > 'other'."""
        role = self._roles.get(ident)
        if role is not None:
            return role
        if name and name.startswith("dl4j:"):
            parts = name.split(":")
            if len(parts) >= 2 and parts[1]:
                return parts[1]
        found = _heuristic_subsystem(frame)
        return found if found is not None else "other"

    # -- sampling ------------------------------------------------------------
    def sample_now(self):
        """Fold one sample of every live thread's stack into the ring;
        returns the number of threads sampled, or None while telemetry
        is disabled (zero registry calls, zero frame walks)."""
        if not _registry.enabled():
            return None
        period = 1.0 / self.hz
        names = {t.ident: t.name for t in threading.enumerate()
                 if t.ident is not None}
        me = threading.get_ident()
        seconds_by_subsystem: dict = {}
        folded = []
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue                    # never profile the profiler
            subsystem = self.subsystem_of(ident, names.get(ident), frame)
            stack = subsystem + ";" + collapse_frame(frame, self.max_depth)
            folded.append((subsystem, stack))
            seconds_by_subsystem[subsystem] = \
                seconds_by_subsystem.get(subsystem, 0.0) + period
        now = time.monotonic()
        with self._lock:
            bucket = self._buckets[-1] if self._buckets else None
            if (bucket is None or
                    now - bucket["mono"] >= self.bucket_seconds):
                bucket = {"mono": now, "ts": time.time(), "stacks": {}}
                self._buckets.append(bucket)
            stacks = bucket["stacks"]
            for subsystem, stack in folded:
                if stack not in stacks and len(stacks) >= self.max_stacks:
                    stack = subsystem + ";(truncated)"
                stacks[stack] = stacks.get(stack, 0) + 1
            self._samples += 1
        counter = self._counter
        if counter is None:
            counter = _registry.get_registry().counter(
                "dl4j_profile_self_seconds_total", SELF_SECONDS_HELP,
                ("subsystem",))
            counter.local = True    # per-host thread population
            self._counter = counter
        for subsystem, secs in seconds_by_subsystem.items():
            counter.labels(subsystem=subsystem).inc(secs)
        return len(folded)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Start the sampler thread — a no-op while telemetry is
        disabled (the disabled contract is *zero sampler thread*, not
        a parked one). Idempotent."""
        if not _registry.enabled():
            return self
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=thread_name("telemetry", "profiler"))
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _run(self):
        while not self._stop.wait(1.0 / self.hz):
            if not _registry.enabled():
                break               # disable() drains the sampler thread
            try:
                self.sample_now()
            except Exception:
                # a profiler crash must never take the process with it
                log.exception("profile sample failed")
        self._thread = None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def clear(self):
        with self._lock:
            self._buckets.clear()
            self._samples = 0

    # -- reads ---------------------------------------------------------------
    def collapsed(self, window=None) -> dict:
        """Merged {collapsed: count} over the trailing ``window``
        seconds (whole ring when None)."""
        horizon = (time.monotonic() - float(window)
                   if window is not None else None)
        out: dict = {}
        with self._lock:
            for bucket in self._buckets:
                if horizon is not None and bucket["mono"] < horizon:
                    continue
                for stack, count in bucket["stacks"].items():
                    out[stack] = out.get(stack, 0) + count
        return out

    def render(self, window=None) -> str:
        """The GET /debug/profile/cpu payload (collapsed text)."""
        return render_collapsed(self.collapsed(window))

    def describe(self, window=None) -> dict:
        """Sampler config + per-subsystem attribution (JSON reads)."""
        stacks = self.collapsed(window)
        with self._lock:
            buckets = len(self._buckets)
            samples = self._samples
        return {
            "config": {"hz": self.hz,
                       "bucket_seconds": self.bucket_seconds,
                       "capacity": self.capacity,
                       "max_stacks": self.max_stacks,
                       "max_depth": self.max_depth},
            "running": self.running,
            "samples": samples,
            "buckets": buckets,
            "attribution": attribution(stacks),
            "unique_stacks": len(stacks),
        }

    # -- deep capture --------------------------------------------------------
    _capture_lock = threading.Lock()

    def capture(self, seconds=2.0, hz=CAPTURE_HZ, out_dir=None,
                device_trace=True):
        """Single-flight deep capture: ``seconds`` of high-rate
        wall-clock sampling plus (best-effort) a ``jax.profiler.trace``
        device capture, committed as a content-addressed artifact
        directory. Raises CaptureBusyError when one is in flight."""
        if not self._capture_lock.acquire(blocking=False):
            raise CaptureBusyError("a deep capture is already running")
        try:
            return self._capture_locked(
                min(float(seconds), CAPTURE_MAX_SECONDS), float(hz),
                out_dir or capture_dir(), device_trace)
        finally:
            self._capture_lock.release()

    def _capture_locked(self, seconds, hz, root, device_trace):
        os.makedirs(root, exist_ok=True)
        stage = os.path.join(root, f".stage-{os.getpid()}-{id(self):x}")
        shutil.rmtree(stage, ignore_errors=True)
        os.makedirs(stage)
        device_dir = os.path.join(stage, "device")
        trace_error = None
        stacks: dict = {}
        samples = 0

        def _sample_loop():
            nonlocal samples
            period = 1.0 / hz
            me = threading.get_ident()
            # the window starts when sampling starts — not at capture
            # entry, where jax.profiler.trace startup (seconds on a
            # cold backend) would eat it
            t0 = time.monotonic()
            while time.monotonic() - t0 < seconds:
                names = {t.ident: t.name for t in threading.enumerate()
                         if t.ident is not None}
                for ident, frame in sys._current_frames().items():
                    if ident == me:
                        continue
                    subsystem = self.subsystem_of(
                        ident, names.get(ident), frame)
                    stack = (subsystem + ";" +
                             collapse_frame(frame, self.max_depth))
                    stacks[stack] = stacks.get(stack, 0) + 1
                samples += 1
                time.sleep(period)

        def _run_sampling():
            # sample from a helper thread so the CALLER's stack is in
            # the capture — for an HTTP-triggered capture that is the
            # handler thread, and it guarantees a non-empty corpus
            # even in an otherwise idle process
            sampler = threading.Thread(
                target=_sample_loop, daemon=True,
                name=thread_name("telemetry", "capture"))
            sampler.start()
            sampler.join()

        if device_trace:
            try:
                import jax
                with jax.profiler.trace(device_dir):
                    _run_sampling()
            except Exception as exc:      # no device / profiler backend
                trace_error = f"{type(exc).__name__}: {exc}"
                if samples == 0:          # trace died before sampling ran
                    _run_sampling()
        else:
            _run_sampling()

        collapsed_text = render_collapsed(stacks)
        atomic_save(os.path.join(stage, "cpu.collapsed"),
                    lambda tmp: _write_text(tmp, collapsed_text))
        cap_id = "cap_" + hashlib.sha256(
            collapsed_text.encode()).hexdigest()[:12]
        meta = {
            "id": cap_id,
            "created": round(time.time(), 3),
            "seconds": seconds,
            "hz": hz,
            "samples": samples,
            "unique_stacks": len(stacks),
            "attribution": attribution(stacks),
            "device_trace": device_trace and trace_error is None,
            "device_trace_error": trace_error,
        }
        atomic_save(os.path.join(stage, "meta.json"),
                    lambda tmp: _write_text(tmp, json.dumps(
                        meta, indent=2, sort_keys=True)))
        final = os.path.join(root, cap_id)
        shutil.rmtree(final, ignore_errors=True)   # re-capture idempotent
        os.replace(stage, final)
        from deeplearning4j_tpu.telemetry import flight
        flight.record("profile_capture", id=cap_id, seconds=seconds,
                      samples=samples, device_trace=meta["device_trace"])
        return meta


def _write_text(path, text):
    with open(path, "w") as fh:
        fh.write(text)


# -- capture artifact store ---------------------------------------------------

def capture_dir() -> str:
    """Where deep-capture artifacts land: ``DL4J_PROFILE_DIR`` or a
    per-user tmp directory."""
    env = os.environ.get("DL4J_PROFILE_DIR")
    if env:
        return env
    import tempfile
    return os.path.join(tempfile.gettempdir(),
                        f"dl4j-captures-{os.getuid()}")


def list_captures(root=None) -> list:
    """Committed captures, newest first (the staged ``.stage-*`` dirs
    are invisible by construction)."""
    root = root or capture_dir()
    out = []
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return out
    for entry in entries:
        if not entry.startswith("cap_"):
            continue
        meta_path = os.path.join(root, entry, "meta.json")
        try:
            with open(meta_path) as fh:
                meta = json.load(fh)
        except (OSError, ValueError):
            continue
        meta["files"] = sorted(
            f for f in os.listdir(os.path.join(root, entry))
            if os.path.isfile(os.path.join(root, entry, f)))
        out.append(meta)
    out.sort(key=lambda m: m.get("created", 0), reverse=True)
    return out


def read_capture(cap_id, filename, root=None) -> bytes:
    """One artifact file's bytes; raises FileNotFoundError on unknown
    ids and refuses path escapes."""
    root = root or capture_dir()
    if (os.sep in cap_id or "/" in cap_id or ".." in cap_id or
            os.sep in filename or "/" in filename or ".." in filename):
        raise FileNotFoundError(f"{cap_id}/{filename}")
    path = os.path.join(root, cap_id, filename)
    with open(path, "rb") as fh:
        return fh.read()


# -- module-level convenience (the gated entry points) ------------------------

def get_profiler() -> ContinuousProfiler:
    """The process-wide profiler (created lazily). Raw handle — callers
    outside telemetry/ go through the module helpers below, which gate
    on the enabled flag (the dl4jlint telemetry-gate contract)."""
    p = _state["profiler"]
    if p is None:
        with _lock:
            p = _state["profiler"]
            if p is None:
                p = ContinuousProfiler()
                _state["profiler"] = p
    return p


def set_profiler(profiler):
    """Swap the process profiler (tests). Returns the previous one."""
    prev = _state["profiler"]
    _state["profiler"] = profiler
    return prev


def configure(hz=None, bucket_seconds=None, capacity=None,
              max_stacks=None, max_depth=None):
    """Reconfigure the process profiler in place (ring contents are
    preserved on a rate change, dropped on a capacity change)."""
    p = get_profiler()
    if hz is not None:
        p.hz = float(hz)
    if bucket_seconds is not None:
        p.bucket_seconds = float(bucket_seconds)
    if capacity is not None:
        p.capacity = int(capacity)
        with p._lock:
            p._buckets = deque(p._buckets, maxlen=p.capacity)
    if max_stacks is not None:
        p.max_stacks = int(max_stacks)
    if max_depth is not None:
        p.max_depth = int(max_depth)
    return p


def start():
    """Start the continuous sampler (no-op while telemetry is
    disabled — zero sampler thread is the disabled contract)."""
    return get_profiler().start()


def stop(timeout=5.0):
    p = _state["profiler"]
    if p is not None:
        p.stop(timeout)


def sample_now():
    """One sample now (deterministic tests; returns None while
    telemetry is disabled — the gate lives in the profiler itself)."""
    return get_profiler().sample_now()


def register_thread(subsystem, role="", ident=None):
    """Attribute the calling (or given) thread to a subsystem."""
    return get_profiler().register_thread(subsystem, role, ident)


def render(window=None):
    """The GET /debug/profile/cpu payload — read-only, served whether
    or not telemetry is currently enabled (incident reads outlive a
    disable())."""
    return get_profiler().render(window)


def collapsed(window=None):
    """Merged {collapsed: count} over the window (read-only — the
    fleet router's merge input)."""
    return get_profiler().collapsed(window)


def describe(window=None):
    return get_profiler().describe(window)


def capture(seconds=2.0, hz=CAPTURE_HZ, out_dir=None, device_trace=True):
    """Run one single-flight deep capture (raises CaptureBusyError
    when one is already in flight)."""
    return get_profiler().capture(seconds, hz, out_dir, device_trace)


def clear():
    p = _state["profiler"]
    if p is not None:
        p.clear()
