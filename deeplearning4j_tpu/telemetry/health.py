"""Per-layer training-health diagnostics (ISSUE 3 tentpole).

Reference capability: the DL4J training UI's signature per-layer
diagnostics — activation/gradient/update magnitudes and the classic
update:parameter-ratio tuning signal (SURVEY.md §2.5 listeners, §5
observability) — rebuilt for a jitted TPU training stack where a silent
NaN or exploding layer wastes whole pod-hours (arxiv 2001.04206 /
2305.08819: tuning a JIT-compiled stack is blind guesswork without
per-layer numeric health).

Design:

- the statistics are computed INSIDE the already-jitted train step: one
  fused reduction set per layer (grad L2, update L2, new-param L2,
  update:param ratio, non-finite count) riding along with the loss,
  returned as one small ``[L, N_STATS]`` float32 array — no extra
  device dispatch, no added sync;
- the host reads that array ONE STEP BEHIND (``HealthMonitor`` keeps a
  one-deep pending slot): in steady state the previous step's array is
  already materialized, so reading it never stalls the dispatch queue;
- publication goes through the PR-1 MetricsRegistry as ``dl4j_health_*``
  gauges/histograms; with ``telemetry.disable()`` the whole subsystem is
  compiled OUT of the step (``build_plan().collect`` is False), the fit
  loop makes zero registry calls per step, and the jitted step returns
  exactly its pre-health outputs;
- divergence policies: WARN logs + records, HALT raises
  ``DivergenceError`` (after dumping the flight recorder, naming the
  offending layer and step), SKIP_BATCH compiles a keep-old-params gate
  into the step itself (``jnp.where`` on the donated buffers — the skip
  happens on device with zero sync).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import namedtuple
from dataclasses import dataclass, replace

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.telemetry import flight
from deeplearning4j_tpu.telemetry import registry as _registry
from deeplearning4j_tpu.telemetry.registry import get_registry, log_buckets

log = logging.getLogger("deeplearning4j_tpu")

# -- policies ----------------------------------------------------------------

WARN = "warn"
HALT = "halt"
SKIP_BATCH = "skip_batch"
POLICIES = (WARN, HALT, SKIP_BATCH)

STAT_NAMES = ("grad_norm", "update_norm", "param_norm",
              "update_param_ratio", "nonfinite")
N_STATS = len(STAT_NAMES)


class DivergenceError(RuntimeError):
    """Raised by the HALT policy when a step produces non-finite
    gradients (or trips a ratio threshold). Carries the offending step,
    layer names, and the flight-recorder dump path."""

    def __init__(self, message, step=None, layers=(), dump_path=None):
        super().__init__(message)
        self.step = step
        self.layers = tuple(layers)
        self.dump_path = dump_path


@dataclass(frozen=True)
class HealthConfig:
    """Divergence-policy configuration.

    policy: WARN (log + record), HALT (raise DivergenceError), or
        SKIP_BATCH (discard the diverged update on device);
    ratio_max/ratio_min: optional update:param-ratio thresholds (the
        DL4J tuning heuristic says healthy layers sit around 1e-3;
        ``None`` disables the check);
    check_every: process/publish every Nth step (violation latency
        trades against host work on very fast steps);
    dump_dir: where HALT writes the flight-recorder JSONL (default:
        the system temp dir)."""

    policy: str = WARN
    ratio_max: float | None = None
    ratio_min: float | None = None
    check_every: int = 1
    dump_dir: str | None = None

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {self.policy!r}")
        if self.check_every < 1:
            raise ValueError("check_every must be >= 1")


_lock = threading.Lock()
_state = {"enabled": True, "config": HealthConfig()}
_status: dict = {"divergence": None, "loops": {}}


def enabled() -> bool:
    return _state["enabled"]


def enable():
    _state["enabled"] = True


def disable():
    _state["enabled"] = False


def configure(**kw) -> HealthConfig:
    """Update the process-default HealthConfig (and/or the enabled
    flag): ``configure(policy=HALT, ratio_max=10.0)``."""
    if "enabled" in kw:
        _state["enabled"] = bool(kw.pop("enabled"))
    if kw:
        _state["config"] = replace(_state["config"], **kw)
    return _state["config"]


def get_config() -> HealthConfig:
    return _state["config"]


def reset_status():
    """Clear divergence/last-step state (tests, or a supervised restart
    after a diverged run was rolled back)."""
    with _lock:
        _status["divergence"] = None
        _status["loops"] = {}


def note_step(loop, step):
    # under the lock: healthz() serves from the UI-server thread while
    # the fit loop writes here
    with _lock:
        _status["loops"][loop] = {"step": int(step), "ts": time.time()}


# -- build plan (what gets compiled into the step) ---------------------------

BuildPlan = namedtuple("BuildPlan", ("collect", "skip"))
INACTIVE = BuildPlan(False, False)


def _listener_config(listeners):
    """(config, listener) from the first DL4J-style HealthListener among
    ``listeners`` (duck-typed via HEALTH_LISTENER to avoid an import
    cycle with utils.listeners), else the process default."""
    for li in listeners or ():
        if getattr(li, "HEALTH_LISTENER", False):
            return li.config, li
    return _state["config"], None


def build_plan(listeners=()) -> BuildPlan:
    """What the jitted step should compile in. ``collect`` is False
    whenever telemetry or health is disabled — the step then returns
    exactly its pre-health outputs (unchanged signature, zero registry
    calls per step)."""
    collect = _state["enabled"] and _registry.enabled()
    if not collect:
        return INACTIVE
    cfg, _ = _listener_config(listeners)
    return BuildPlan(True, cfg.policy == SKIP_BATCH)


# -- traced statistics (called while building the step HLO) ------------------

def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


def _sumsq(tree):
    leaves = _leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)


def _nonfinite_count(tree):
    leaves = _leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return sum(jnp.sum((~jnp.isfinite(x)).astype(jnp.float32))
               for x in leaves)


def layer_stats(grad, update, new_param):
    """One fused reduction set for one layer -> [N_STATS] float32:
    grad L2, update L2, new-param L2, update:param ratio, non-finite
    count over grad+update+new params (params included so a layer whose
    WEIGHTS went NaN is named even when the relu backprop mask zeroes
    its own gradient). XLA fuses these with the backward pass — they
    add reductions, never a dispatch."""
    g = jnp.sqrt(_sumsq(grad))
    u = jnp.sqrt(_sumsq(update))
    p = jnp.sqrt(_sumsq(new_param))
    ratio = u / jnp.maximum(p, jnp.float32(1e-12))
    bad = (_nonfinite_count(grad) + _nonfinite_count(update)
           + _nonfinite_count(new_param))
    return jnp.stack([g, u, p, ratio, bad])


def zero_stats():
    """Row for a parameter-less layer (keeps row index == layer index)."""
    return jnp.zeros((N_STATS,), jnp.float32)


def loss_stats(loss):
    """The dedicated trailing "loss" row: only the nonfinite column is
    populated. Folding the loss into the SAME array keeps the device
    gate and the host-side accounting looking at one condition — a
    non-finite loss with finite grads (fp32 overflow in the loss
    reduction) is still named, counted, and policy-handled."""
    bad = jnp.sum((~jnp.isfinite(jnp.asarray(loss))).astype(jnp.float32))
    return jnp.stack([jnp.float32(0), jnp.float32(0), jnp.float32(0),
                      jnp.float32(0), bad])


LOSS_ROW_LABEL = "loss"


def with_loss_row(layer_names):
    """Health-row labels for a loop: per-layer labels + the loss row."""
    return list(layer_names) + [LOSS_ROW_LABEL]


def stack_stats(rows):
    if not rows:
        return jnp.zeros((0, N_STATS), jnp.float32)
    return jnp.stack(rows)


def step_ok(health):
    """Traced scalar: True when nothing in the step went non-finite
    (the SKIP_BATCH gate condition). Reads ONLY the health array — the
    loss contributes via its own loss_stats row, so the host-side
    monitor sees exactly the condition the device gated on."""
    return jnp.sum(health[:, STAT_NAMES.index("nonfinite")]) == 0


def keep_if(ok, new_tree, old_tree):
    """SKIP_BATCH gate: keep the new tree where ok, else the old one.
    Compiled into the step — a select per buffer, no host round trip."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree)


# -- instruments -------------------------------------------------------------

RATIO_BUCKETS = log_buckets(1e-8, 100, per_decade=2)

RATIO_HELP = ("Per-layer update:parameter L2-norm ratio (the DL4J tuning "
              "signal; healthy layers sit around 1e-3)")
GRAD_HELP = "Per-layer gradient L2 norm of the last health-checked step"
UPDATE_HELP = "Per-layer update L2 norm of the last health-checked step"
PARAM_HELP = "Per-layer parameter L2 norm after the last checked step"
NONFINITE_HELP = ("NaN/Inf values observed in per-layer grads, updates, "
                  "and post-step params")
VIOLATION_HELP = "Divergence-policy trips by loop, policy, and kind"
SKIPPED_HELP = "Training steps discarded by the SKIP_BATCH policy"
LAST_STEP_HELP = "Most recent health-checked step index per loop"


class HealthInstruments:
    """Per-(loop, layer) bound children, built once per monitor so the
    per-step publish path is list indexing + observe/set — no label
    dict lookups in the loop."""

    __slots__ = ("loop", "ratio", "grad", "update", "param", "nonfinite",
                 "violations", "skipped", "last_step")

    def __init__(self, registry, loop, layer_names):
        self.loop = loop
        ratio_fam = registry.histogram(
            "dl4j_health_update_param_ratio", RATIO_HELP,
            ("loop", "layer"), buckets=RATIO_BUCKETS)
        grad_fam = registry.gauge(
            "dl4j_health_grad_norm", GRAD_HELP, ("loop", "layer"))
        update_fam = registry.gauge(
            "dl4j_health_update_norm", UPDATE_HELP, ("loop", "layer"))
        param_fam = registry.gauge(
            "dl4j_health_param_norm", PARAM_HELP, ("loop", "layer"))
        nonfinite_fam = registry.counter(
            "dl4j_health_nonfinite_total", NONFINITE_HELP,
            ("loop", "layer"))
        self.ratio = [ratio_fam.labels(loop=loop, layer=n)
                      for n in layer_names]
        self.grad = [grad_fam.labels(loop=loop, layer=n)
                     for n in layer_names]
        self.update = [update_fam.labels(loop=loop, layer=n)
                       for n in layer_names]
        self.param = [param_fam.labels(loop=loop, layer=n)
                      for n in layer_names]
        self.nonfinite = [nonfinite_fam.labels(loop=loop, layer=n)
                          for n in layer_names]
        self.violations = registry.counter(
            "dl4j_health_violations_total", VIOLATION_HELP,
            ("loop", "policy", "kind"))
        self.skipped = registry.counter(
            "dl4j_health_skipped_steps_total", SKIPPED_HELP,
            ("loop",)).labels(loop=loop)
        self.last_step = registry.gauge(
            "dl4j_health_last_step", LAST_STEP_HELP,
            ("loop",)).labels(loop=loop)


def health_instruments(loop, layer_names):
    """Bound instrument bundle, or None when telemetry is disabled (the
    monitor then still enforces policies, without registry calls)."""
    if not _registry.enabled():
        return None
    return HealthInstruments(get_registry(), loop, layer_names)


# -- the monitor -------------------------------------------------------------

def _host(arr) -> np.ndarray:
    """Host copy that also works on multi-process replicated outputs
    (read this process's shard — it holds the replicated value)."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    return np.asarray(arr.addressable_data(0))


class HealthMonitor:
    """Host side of the health pipeline for one fit loop.

    ``on_step(step, health)`` stores the new device array and processes
    the PREVIOUS one (one step behind — already materialized in steady
    state, so no dispatch-queue stall). ``flush()`` drains the pending
    slot at the end of the loop; HALT may therefore raise from either.
    """

    def __init__(self, loop, layer_names, config=None, listener=None):
        self.loop = loop
        self.layer_names = list(layer_names)
        self.config = config or _state["config"]
        self.listener = listener
        self.instruments = health_instruments(loop, self.layer_names)
        # optional precision.PrecisionMonitor (ISSUE 4): when the loss
        # scaler's overflow gate already skipped a step on device, the
        # SKIP_BATCH accounting defers to it (one skip, one counter)
        self.precision = None
        self._pending = None
        self._count = 0

    # -- loop-facing ---------------------------------------------------------
    def on_step(self, step, health):
        if health is None:
            return
        prev, self._pending = self._pending, (step, health)
        if prev is not None:
            self._process(*prev)

    def flush(self):
        prev, self._pending = self._pending, None
        if prev is not None:
            self._process(*prev)

    # -- processing ----------------------------------------------------------
    def _process(self, step, arr):
        self._count += 1
        if (self._count - 1) % self.config.check_every:
            return
        a = _host(arr)
        note_step(self.loop, step)
        inst = self.instruments
        cfg = self.config
        bad_layers, ratio_high, ratio_low = [], [], []
        worst_ratio = 0.0
        bad_total = 0.0
        for i, name in enumerate(self.layer_names):
            g, u, p, ratio, bad = (float(a[i, j]) for j in range(N_STATS))
            if bad > 0:
                # classify "nonfinite" ONLY by the device-side count —
                # the same condition the SKIP_BATCH gate compiled in,
                # so host reporting never contradicts what the device
                # did (finite-but-huge grads can overflow the L2 sums
                # to inf without any NaN/Inf in the values themselves)
                bad_layers.append(name)
                bad_total += bad
                if inst is not None:
                    inst.nonfinite[i].inc(bad)
                continue
            if p == 0.0 and g == 0.0 and u == 0.0:
                continue  # parameter-less layer: zero row by construction
            if inst is not None:
                inst.grad[i].set(g)
                inst.update[i].set(u)
                inst.param[i].set(p)
                if np.isfinite(ratio):
                    inst.ratio[i].observe(ratio)
            if not np.isfinite(ratio):
                continue   # overflowed norms: no threshold verdict
            worst_ratio = max(worst_ratio, ratio)
            if cfg.ratio_max is not None and ratio > cfg.ratio_max:
                ratio_high.append((name, ratio))
            if cfg.ratio_min is not None and 0.0 < ratio < cfg.ratio_min:
                ratio_low.append((name, ratio))
        if inst is not None:
            inst.last_step.set(step)
        flight.record("step", loop=self.loop, step=step,
                      worst_ratio=round(worst_ratio, 6),
                      nonfinite=bad_total)
        if self.listener is not None:
            self.listener.onHealthStats(self.loop, step, {
                name: dict(zip(STAT_NAMES, (float(v) for v in a[i])))
                for i, name in enumerate(self.layer_names)})
        if bad_layers:
            self._violate(step, "nonfinite", bad_layers,
                          {"nonfinite_values": bad_total})
        if ratio_high:
            self._violate(step, "ratio_high",
                          [n for n, _ in ratio_high],
                          {"ratios": {n: round(r, 6)
                                      for n, r in ratio_high}})
        if ratio_low:
            self._violate(step, "ratio_low",
                          [n for n, _ in ratio_low],
                          {"ratios": {n: round(r, 9)
                                      for n, r in ratio_low}})

    def _violate(self, step, kind, layers, details):
        cfg = self.config
        inst = self.instruments
        if inst is not None:
            inst.violations.labels(loop=self.loop, policy=cfg.policy,
                                   kind=kind).inc()
        flight.record("health_violation", loop=self.loop, step=step,
                      violation=kind, layers=list(layers),
                      policy=cfg.policy, **details)
        msg = (f"training health violation ({kind}) in loop "
               f"{self.loop!r} at step {step}, layer(s) "
               f"{', '.join(layers)}")
        if cfg.policy == HALT:
            with _lock:
                _status["divergence"] = {
                    "loop": self.loop, "step": int(step), "kind": kind,
                    "layers": list(layers), "ts": time.time()}
            flight.record("divergence", loop=self.loop, step=step,
                          violation=kind, layers=list(layers))
            path = None
            try:
                path = flight.get_recorder().dump(
                    None if cfg.dump_dir is None else os.path.join(
                        cfg.dump_dir,
                        os.path.basename(flight.default_dump_path())))
            except Exception:
                log.exception("flight recorder dump failed")
            raise DivergenceError(
                f"{msg}; policy=HALT"
                + (f"; flight recorder dumped to {path}" if path else ""),
                step=step, layers=layers, dump_path=path)
        if cfg.policy == SKIP_BATCH and kind == "nonfinite":
            # the in-step gate already discarded the update on device
            if self.precision is not None and \
                    self.precision.skipped_at(step):
                # the loss scaler's overflow gate fired on the SAME step
                # and already counted the skip (dl4j_precision_skipped_
                # steps_total) and recorded a `precision` flight event —
                # do not count the one discarded step twice (ISSUE 4)
                log.warning("%s; handled by the dynamic loss scaler "
                            "(scale backed off, step skipped on device)",
                            msg)
                return
            if inst is not None:
                inst.skipped.inc()
            log.warning("%s; policy=SKIP_BATCH — the diverged update was "
                        "discarded on device, training continues", msg)
            return
        # WARN, or a ratio violation under SKIP_BATCH (ratio thresholds
        # are host-side config, so there is nothing to skip on device)
        log.warning("%s; policy=%s (warn-only)", msg, cfg.policy)


def monitor_for(loop, layer_names, listeners=()):
    """The per-fit HealthMonitor, or None when health collection is off
    (health disabled, or telemetry disabled). Call once before the hot
    loop — mirrors telemetry.loop_instruments."""
    if not build_plan(listeners).collect:
        return None
    cfg, listener = _listener_config(listeners)
    return HealthMonitor(loop, layer_names, cfg, listener)


# -- /healthz ----------------------------------------------------------------

_healthz_providers: dict = {}


def register_healthz_provider(name, fn):
    """Add a readiness-detail section to /healthz. ``fn()`` returns a
    JSON-able dict merged under ``payload[name]``; a truthy
    ``"degraded"`` key marks the process degraded (status
    ``"degraded"``, still HTTP 200 — degradation informs operators, it
    does not stop traffic the way divergence/warming do). Used by the
    resilience subsystem for checkpoint staleness + supervisor state."""
    with _lock:   # registration can come from a background writer
        _healthz_providers[name] = fn


def unregister_healthz_provider(name):
    with _lock:
        _healthz_providers.pop(name, None)


def healthz(serving=None):
    """(payload, http_status) for the liveness/readiness endpoint.

    live: the process answers (always True if we got here);
    ready: no recorded divergence AND (if a serving session is
    attached) every registered model's bucket ladder is warmed;
    degraded (ready, 200): a registered provider reports a soft
    condition, e.g. stale checkpoints.
    """
    now = time.time()
    with _lock:   # the fit-loop thread mutates these as we read
        loop_state = dict(_status["loops"])
        div = _status["divergence"]
    loops = {
        loop: {"step": s["step"],
               "last_step_age_seconds": round(now - s["ts"], 3)}
        for loop, s in sorted(loop_state.items())}
    serving_info = None
    ready = div is None
    if serving is not None:
        try:
            models = serving.models()
        except Exception:
            models = []
        if hasattr(serving, "ready"):     # InferenceSession
            warmed = bool(serving.ready())
        else:                             # duck-typed session
            warmed = (all(m.get("warmed") for m in models)
                      if models else True)
        serving_info = {
            "attached": True,
            "warmed": warmed,
            "models": [{"name": m["name"], "version": m["version"],
                        "warmed": m.get("warmed", False)}
                       for m in models]}
        ready = ready and warmed
    status = "diverged" if div is not None else (
        "ok" if ready else "warming")
    payload = {"status": status, "live": True, "ready": ready,
               "loops": loops, "divergence": div, "serving": serving_info}
    degraded = False
    if serving_info is not None and hasattr(serving, "health_details"):
        # replica-set + decode-engine liveness (ISSUE 10 satellite): a
        # dead replica or a wedged decode slot degrades the process —
        # still HTTP 200, capacity is reduced but traffic flows
        try:
            details = serving.health_details() or {}
        except Exception:
            log.exception("serving health_details failed")
            details = {}
        # "sharded" (ISSUE 19) is placement info, not liveness — its
        # rows carry no "degraded" key, so the any() below is a no-op
        # for it by construction
        for section in ("replica_sets", "decoders", "sharded"):
            rows = details.get(section)
            if rows:
                serving_info[section] = rows
                degraded = degraded or any(
                    bool(v.get("degraded")) for v in rows.values())
    with _lock:   # a first-commit registration may race this scrape
        providers = sorted(_healthz_providers.items())
    for name, fn in providers:
        try:
            section = fn()
        except Exception:
            log.exception("healthz provider %r failed", name)
            continue
        if section:
            payload[name] = section
            degraded = degraded or bool(section.get("degraded"))
    if degraded and status == "ok":
        payload["status"] = "degraded"
    return payload, (200 if ready else 503)
