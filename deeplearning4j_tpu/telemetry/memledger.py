"""Device-memory observability: the HBM ownership ledger (ISSUE 14
tentpole).

The stack can attribute every second (ISSUE 10 spans), every FLOP
(costmodel), and every compile (ISSUE 11 ledger) — but before this
module, not a single byte of device memory: ``collect_device_memory``
publishes raw per-device ``bytes_in_use``, and an allocation failure
surfaces as an opaque XLA ``RESOURCE_EXHAUSTED`` with no record of who
owned the HBM. This module is the missing instrument, in three layers:

1. **The claims registry.** Every subsystem that pins device memory
   registers a named, categorized :class:`Claim` — train params /
   updater state / loss-scale state (category ``train``), the paged
   decode KV pools including the speculative draft lane (``kv_cache``),
   serving executables per bucket from the ISSUE-11 ``memory_analysis``
   capture (``executable``), ``DevicePrefetcher`` staged DeviceBatches
   (``prefetch``), ``AsyncCheckpointer`` snapshot clones
   (``checkpoint``), and ``ReplicaSet`` pinned placed-args
   (``replica_args``). Claims reconcile against
   ``device.memory_stats()`` (falling back to live-array accounting on
   backends that report none, e.g. CPU) into
   ``dl4j_device_memory_claimed_bytes{category,device}`` plus an
   explicit ``unattributed`` residual — exported at ``GET
   /debug/memory`` and in the ``/healthz`` ``memory`` section
   (headroom below the configured floor ⇒ degraded, still 200).

2. **OOM forensics.** The instrumented seams (train-step loops,
   ``run_batch``, the decode-engine boundary, prefetch ``device_put``,
   the snapshot clone) catch ``RESOURCE_EXHAUSTED``, emit a flight
   ``oom`` event carrying the requested bytes, the site, and the top-N
   claims at failure, and re-raise a typed :class:`DeviceOomError` —
   an allocation failure now names its neighborhood instead of dying
   anonymously.

3. **Admission-time capacity planning.** ``ModelRegistry`` warmup sums
   the ladder's estimated footprint against live headroom *before*
   compiling anything, and ``DecodeEngine.__init__`` validates its KV
   pool bytes the same way — a structured :class:`CapacityError`
   instead of a mid-ladder OOM (``dl4j_compile_total`` provably flat,
   ledger-asserted in tests). cuDNN (PAPERS.md) is the precedent for
   making the workspace-vs-algorithm memory budget an explicit,
   queryable contract; Dragon-Alpha for pool-based ownership
   accounting in a lean runtime.

Steady-state cost contract (the PR-3/PR-9/PR-11 line): one gauge-set
per training step (``Claim.touch``), and ``telemetry.disable()``
compiles it all out — the loops guard on the claim handle exactly like
they guard on ``loop_instruments`` (CountingStub-asserted,
bit-identical params).
"""

from __future__ import annotations

import itertools
import logging
import os
import re
import threading
import time
import weakref

from deeplearning4j_tpu.telemetry import registry as _registry

log = logging.getLogger("deeplearning4j_tpu")

CLAIMED_HELP = ("Device bytes claimed by each subsystem category in the "
                "HBM ownership ledger (category=unattributed is the "
                "residual against the device's measured bytes_in_use)")

# categories with a fixed meaning (free-form ones are allowed; these are
# the ones the shipped registrars use — docs/OBSERVABILITY.md taxonomy)
CATEGORIES = ("train", "kv_cache", "executable", "prefetch",
              "checkpoint", "replica_args")

_state = {
    "ledger": None,
    # sub-switch under the master telemetry flag (the compile_ledger
    # pattern): lets the bench isolate ledger-on vs ledger-off with
    # the rest of telemetry held constant
    "enabled": True,
    # capacity budget for backends that do not report memory_stats
    # (CPU): headroom() treats it as bytes_limit, with live-array
    # accounting standing in for bytes_in_use
    "budget": None,
    "budget_resolved": False,
    # /healthz degradation floor: headroom below this many bytes marks
    # the memory section degraded (still 200); None = fraction of limit
    "min_headroom_bytes": None,
    "min_headroom_fraction": 0.02,
    "top_n": 8,              # claims named in an oom flight event
    "provider": False,       # /healthz provider registered?
}
_lock = threading.Lock()


def configure(budget_bytes=..., min_headroom_bytes=...,
              min_headroom_fraction=None, top_n=None, enabled=None):
    """Tune the ledger: ``budget_bytes`` is the assumed device capacity
    where the backend reports no ``memory_stats`` (None forgets an
    override and re-reads ``DL4J_DEVICE_BUDGET_BYTES``);
    ``min_headroom_bytes`` / ``min_headroom_fraction`` set the /healthz
    degradation floor; ``top_n`` bounds the claims an ``oom`` flight
    event names; ``enabled`` is the ledger's sub-switch under the
    master telemetry flag (bench isolation)."""
    with _lock:
        if enabled is not None:
            _state["enabled"] = bool(enabled)
        if budget_bytes is not ...:
            _state["budget"] = (None if budget_bytes is None
                                else int(budget_bytes))
            _state["budget_resolved"] = budget_bytes is not None
        if min_headroom_bytes is not ...:
            _state["min_headroom_bytes"] = (
                None if min_headroom_bytes is None
                else int(min_headroom_bytes))
        if min_headroom_fraction is not None:
            _state["min_headroom_fraction"] = float(min_headroom_fraction)
        if top_n is not None:
            _state["top_n"] = int(top_n)


def budget_bytes():
    """The configured capacity assumption for stat-less backends:
    explicit :func:`configure` override > ``DL4J_DEVICE_BUDGET_BYTES``
    > None (capacity unknown — the planner passes)."""
    with _lock:
        if _state["budget_resolved"]:
            return _state["budget"]
    env = os.environ.get("DL4J_DEVICE_BUDGET_BYTES")
    budget = None
    if env:
        try:
            budget = int(float(env))
        except ValueError:
            log.warning("DL4J_DEVICE_BUDGET_BYTES=%r is not a number; "
                        "ignored", env)
    with _lock:
        if not _state["budget_resolved"]:
            _state["budget"] = budget
    return budget


# ---------------------------------------------------------------------------
# typed errors
# ---------------------------------------------------------------------------

class DeviceOomError(RuntimeError):
    """A device allocation failure, enriched at the seam that caught
    it: ``site`` names the instrumented boundary, ``requested_bytes``
    the allocation XLA reported (None when unparseable), ``claims`` the
    top HBM owners at failure (``[{category, name, device, bytes}]``)."""

    def __init__(self, message, site=None, requested_bytes=None,
                 claims=None):
        super().__init__(message)
        self.site = site
        self.requested_bytes = requested_bytes
        self.claims = list(claims or ())


class CapacityError(RuntimeError):
    """Structured admission-time rejection: a prospective allocation
    (`need_bytes` at `site`) exceeds the live device headroom. Raised
    BEFORE any XLA compile / pool allocation — ``detail`` carries the
    planner's per-component breakdown."""

    def __init__(self, message, site=None, need_bytes=None,
                 headroom_bytes=None, detail=None):
        super().__init__(message)
        self.site = site
        self.need_bytes = need_bytes
        self.headroom_bytes = headroom_bytes
        self.detail = dict(detail or {})


# ---------------------------------------------------------------------------
# byte accounting helpers
# ---------------------------------------------------------------------------

def tree_bytes(tree) -> int:
    """Total bytes of a pytree's array leaves. Works for jax arrays,
    numpy arrays, and ShapeDtypeStructs (shape x dtype — the planner's
    eval_shape path); non-array leaves count zero."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
            continue
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            try:
                total += int(np.prod(shape, dtype=np.int64)
                             * np.dtype(dtype).itemsize)
            except Exception:
                pass
    return total


def device_label(device=None) -> str:
    """The ledger's label for a jax device (default: the first local
    device — where unpinned allocations land)."""
    if device is not None:
        return f"{device.platform}:{device.id}"
    try:
        import jax

        d = jax.local_devices()[0]
        return f"{d.platform}:{d.id}"
    except Exception:
        return "unknown:0"


_device_label = device_label


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

class Claim:
    """One subsystem's registered ownership of device bytes. The handle
    is what the owner keeps: ``update(nbytes)`` when the footprint
    changes, ``touch()`` once per step (the one-gauge-set steady-state
    contract), ``release()`` when the memory is handed back."""

    __slots__ = ("category", "name", "device", "bytes", "meta",
                 "created_at", "_ledger", "released")

    def __init__(self, ledger, category, name, nbytes, device, meta):
        self.category = str(category)
        self.name = str(name)
        self.device = device
        self.bytes = int(nbytes)
        self.meta = meta or {}
        self.created_at = time.time()
        self._ledger = ledger
        self.released = False

    def update(self, nbytes=None, tree=None, **meta):
        """Re-state the claim's footprint (and refresh its category
        gauge — one gauge-set)."""
        if tree is not None:
            nbytes = tree_bytes(tree)
        if meta:
            self.meta.update(meta)
        self._ledger.restate(self, int(nbytes) if nbytes is not None
                             else self.bytes)
        return self

    def touch(self):
        """Refresh the (category, device) gauge from the ledger total:
        exactly ONE gauge-set — the per-step steady-state cost."""
        self._ledger.publish_total(self.category, self.device)
        return self

    def release(self):
        self._ledger.release_claim(self)

    def describe(self) -> dict:
        return {"category": self.category, "name": self.name,
                "device": self.device, "bytes": self.bytes,
                "age_seconds": round(time.time() - self.created_at, 3),
                **({"meta": self.meta} if self.meta else {})}


class MemLedger:
    """The process-wide claims table: ``(category, name)`` -> Claim,
    with per-``(category, device)`` running totals so a gauge refresh
    is one dict read + one set."""

    def __init__(self):
        self._claims: dict = {}
        self._totals: dict = {}       # (category, device) -> bytes
        self._lock = threading.Lock()

    # -- mutation ------------------------------------------------------------
    def claim(self, category, name, nbytes, device, meta=None) -> Claim:
        key = (str(category), str(name))
        with self._lock:
            existing = self._claims.get(key)
            if existing is not None:
                self._totals[(existing.category, existing.device)] -= \
                    existing.bytes
                existing.bytes = int(nbytes)
                existing.device = device
                existing.released = False
                if meta:
                    existing.meta.update(meta)
                c = existing
            else:
                c = Claim(self, category, name, nbytes, device, meta)
                self._claims[key] = c
            tkey = (c.category, c.device)
            self._totals[tkey] = self._totals.get(tkey, 0) + c.bytes
        self.publish_total(c.category, c.device)
        return c

    def restate(self, c: Claim, nbytes: int):
        with self._lock:
            if self._claims.get((c.category, c.name)) is not c:
                return                       # already released/replaced
            tkey = (c.category, c.device)
            self._totals[tkey] = \
                self._totals.get(tkey, 0) - c.bytes + nbytes
            c.bytes = nbytes
        self.publish_total(c.category, c.device)

    def release_claim(self, c: Claim):
        with self._lock:
            if self._claims.get((c.category, c.name)) is not c:
                return
            del self._claims[(c.category, c.name)]
            tkey = (c.category, c.device)
            self._totals[tkey] = self._totals.get(tkey, 0) - c.bytes
            c.released = True
        self.publish_total(c.category, c.device)

    def release(self, category, name):
        with self._lock:
            c = self._claims.get((str(category), str(name)))
        if c is not None:
            self.release_claim(c)

    def release_prefix(self, category, name_prefix) -> int:
        """Release every claim in ``category`` whose name starts with
        ``name_prefix`` (rolling-update sweeps). Returns the count."""
        with self._lock:
            hits = [c for (cat, name), c in self._claims.items()
                    if cat == category and name.startswith(name_prefix)]
        for c in hits:
            self.release_claim(c)
        return len(hits)

    # -- reads ---------------------------------------------------------------
    def claims(self, category=None) -> list:
        with self._lock:
            out = list(self._claims.values())
        if category is not None:
            out = [c for c in out if c.category == category]
        return sorted(out, key=lambda c: -c.bytes)

    def get(self, category, name):
        with self._lock:
            return self._claims.get((str(category), str(name)))

    def total(self, category=None, device=None) -> int:
        with self._lock:
            return sum(v for (cat, dev), v in self._totals.items()
                       if (category is None or cat == category)
                       and (device is None or dev == device))

    def top(self, n=None) -> list:
        n = n if n is not None else _state["top_n"]
        return [c.describe() for c in self.claims()[:n]]

    # -- gauge publication ---------------------------------------------------
    def _gauge(self):
        if not _registry.enabled():
            return None
        fam = _registry.get_registry().gauge(
            "dl4j_device_memory_claimed_bytes", CLAIMED_HELP,
            ("category", "device"))
        # scrape-only, like dl4j_device_mem_bytes: device labels are
        # host-specific and would break cross-host aggregation
        fam.local = True
        return fam

    def publish_total(self, category, device):
        """ONE gauge-set: the running (category, device) total. The
        per-step `touch()` lands here; zero registry calls when
        telemetry is disabled."""
        fam = self._gauge()
        if fam is None:
            return
        with self._lock:
            val = self._totals.get((category, device), 0)
        fam.labels(category=category, device=device).set(max(0, val))

    def publish_all(self, census_rows=None):
        """Refresh every (category, device) gauge plus the
        ``unattributed`` residual per device (scrape-time; see
        :func:`refresh_metrics`)."""
        fam = self._gauge()
        if fam is None:
            return
        with self._lock:
            totals = dict(self._totals)
        for (category, device), val in sorted(totals.items()):
            fam.labels(category=category, device=device).set(max(0, val))
        for device, row in (census_rows or {}).items():
            resid = row.get("unattributed")
            if resid is not None:
                fam.labels(category="unattributed",
                           device=device).set(max(0, resid))


def get_memledger() -> MemLedger:
    """The process-wide ledger (created lazily). Raw handle — hot-path
    callers outside ``telemetry/`` must gate on ``enabled()`` (or use
    :func:`claim`, which gates internally): the dl4jlint
    telemetry-gate rule enforces it."""
    led = _state["ledger"]
    if led is None:
        with _lock:
            led = _state["ledger"]
            if led is None:
                led = MemLedger()
                _state["ledger"] = led
    return led


def set_ledger(ledger):
    """Swap the process ledger (tests: counting stubs). Returns the
    previous one."""
    prev = _state["ledger"]
    _state["ledger"] = ledger
    return prev


def enabled() -> bool:
    """The ledger follows the one telemetry switch (PR-1 contract),
    with its own sub-switch for bench isolation."""
    return _registry.enabled() and _state["enabled"]


def claim(category, name, nbytes=None, tree=None, device=None,
          **meta):
    """Register (or re-state) a claim; the gated high-level entry
    point — returns None when telemetry is disabled, so registrars
    call it unconditionally and hot loops guard on the handle (the
    ``loop_instruments`` idiom)."""
    if not enabled():
        return None
    if tree is not None:
        nbytes = tree_bytes(tree)
    dev = device if isinstance(device, str) else _device_label(device)
    _ensure_provider()
    return get_memledger().claim(category, name, int(nbytes or 0), dev,
                                 meta or None)


_owner_tags = itertools.count(1)


def claim_for_owner(owner, category, prefix, nbytes=None, tree=None,
                    **meta):
    """A claim keyed to one OWNER object (a net, a trainer): the name
    is ``<prefix>#<serial>``, memoized on the owner, so two nets
    training through the same loop label hold two claims instead of
    silently re-stating one (which would misattribute the first net's
    bytes to the unattributed residual). The claim is auto-released
    when the owner is garbage-collected — its memory goes with it."""
    if not enabled():
        return None
    attr = f"_memledger_tag_{prefix}"
    tag = getattr(owner, attr, None)
    fresh = tag is None
    if fresh:
        tag = f"{prefix}#{next(_owner_tags)}"
        try:
            setattr(owner, attr, tag)
        except Exception:
            pass
    c = claim(category, tag, nbytes=nbytes, tree=tree, **meta)
    if c is not None and fresh:
        try:
            weakref.finalize(owner, release, category, tag)
        except TypeError:
            pass   # unweakrefable owner: the claim simply persists
    return c


def release(category, name):
    """Drop a claim by key (idempotent; works whether or not telemetry
    is currently enabled — an owner releasing memory must always be
    able to say so)."""
    led = _state["ledger"]
    if led is not None:
        led.release(category, name)


def release_prefix(category, name_prefix) -> int:
    led = _state["ledger"]
    if led is None:
        return 0
    return led.release_prefix(category, name_prefix)


# ---------------------------------------------------------------------------
# census: claims vs the device's own accounting
# ---------------------------------------------------------------------------

def _device_usage():
    """Per-device {label: {"in_use", "limit", "source"}} from
    ``memory_stats()`` where the backend reports it, else from summing
    live jax arrays (CPU fallback — approximate but honest: it counts
    exactly the buffers the process can still reach)."""
    import jax

    out = {}
    try:
        devices = jax.local_devices()
    except Exception:
        return out
    stat_less = []
    for d in devices:
        label = f"{d.platform}:{d.id}"
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            out[label] = {"in_use": int(stats["bytes_in_use"]),
                          "limit": int(stats.get("bytes_limit", 0)) or None,
                          "source": "memory_stats"}
        else:
            stat_less.append(d)
            out[label] = {"in_use": 0, "limit": budget_bytes(),
                          "source": "live_arrays"}
    if stat_less:
        labels = {d: f"{d.platform}:{d.id}" for d in stat_less}
        try:
            for arr in jax.live_arrays():
                try:
                    devs = list(arr.devices())
                except Exception:
                    continue
                if not devs:
                    continue
                label = labels.get(devs[0])
                if label is not None:
                    # sharded arrays: attribute the per-device share
                    out[label]["in_use"] += int(arr.nbytes) // len(devs)
        except Exception:
            log.debug("live-array census failed", exc_info=True)
    return out


def census() -> dict:
    """Reconcile the claims table against the devices' own accounting:
    per device, claimed bytes by category, measured ``in_use``, and the
    ``unattributed`` residual (``in_use - claimed``, floored at 0).
    Scrape-time only — never on a step path."""
    led = get_memledger()
    usage = _device_usage()
    devices: dict = {}
    for c in led.claims():
        row = devices.setdefault(
            c.device, {"claimed": {}, "claimed_bytes": 0})
        row["claimed"][c.category] = \
            row["claimed"].get(c.category, 0) + c.bytes
        row["claimed_bytes"] += c.bytes
    for label, u in usage.items():
        row = devices.setdefault(
            label, {"claimed": {}, "claimed_bytes": 0})
        row["in_use"] = u["in_use"]
        row["limit"] = u["limit"]
        row["source"] = u["source"]
        row["unattributed"] = max(0, u["in_use"] - row["claimed_bytes"])
        if u["limit"]:
            row["headroom"] = max(0, u["limit"] - u["in_use"])
    return {"devices": devices,
            "claims": [c.describe() for c in led.claims()]}


def refresh_metrics():
    """Refresh every claimed-bytes gauge (incl. the unattributed
    residual) — called by the /metrics and /debug/memory handlers so
    scrapes see a live reconciliation, never on a step path."""
    if not _registry.enabled():
        return
    try:
        snap = census()
    except Exception:
        log.debug("memory census failed", exc_info=True)
        return
    get_memledger().publish_all(snap["devices"])


def describe() -> dict:
    """The GET /debug/memory payload: the full census (claims table,
    per-device reconciliation) plus the planner's view (headroom,
    budget, degradation floor). Served whether or not telemetry is
    currently enabled — incident dumps outlive a disable()."""
    snap = census()
    snap["headroom_bytes"] = _headroom_from(snap)
    snap["budget_bytes"] = budget_bytes()
    snap["min_headroom_bytes"] = _min_headroom(snap)
    return snap


# ---------------------------------------------------------------------------
# headroom + /healthz
# ---------------------------------------------------------------------------

def capacity_known(device=None) -> bool:
    """Whether ANY device has a known capacity (memory_stats limit or
    a configured budget) — cheap: no live-array walk. False means the
    planner will admit regardless, so callers can skip footprint
    estimation entirely (unconfigured deployments pay nothing)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return False
    for d in devices:
        if device is not None and f"{d.platform}:{d.id}" != device:
            continue
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and stats.get("bytes_limit"):
            return True
    return budget_bytes() is not None


def headroom(device=None) -> int | None:
    """Free device bytes the planner can admit against: ``bytes_limit
    - bytes_in_use`` where the backend reports stats; on stat-less
    backends the configured budget minus live-array usage. None =
    capacity unknown (the planner passes — a made-up limit would turn
    the planner into a random request killer). ``device`` restricts
    the judgement to one device label — a servable pinned to an empty
    device must not be rejected for a busy neighbor's sake."""
    if not capacity_known(device=device):
        return None   # skip the live-array walk: nothing to learn
    usage = _device_usage()
    if device is not None:
        usage = {k: v for k, v in usage.items() if k == device}
    best = None
    for row in usage.values():
        if not row["limit"]:
            continue
        free = max(0, row["limit"] - row["in_use"])
        best = free if best is None else min(best, free)
    return best


def _headroom_from(snap) -> int | None:
    """Headroom derived from an already-computed census snapshot —
    the scrape paths (describe / healthz_section) must not walk the
    live arrays a second time just to re-learn it."""
    best = None
    for row in snap.get("devices", {}).values():
        if "headroom" in row:
            best = (row["headroom"] if best is None
                    else min(best, row["headroom"]))
    return best


def _min_headroom(snap=None) -> int | None:
    """The degradation floor in bytes: explicit configure() override,
    else ``min_headroom_fraction`` of the smallest known device
    limit."""
    floor = _state["min_headroom_bytes"]
    if floor is not None:
        return floor
    limits = []
    devices = (snap or {}).get("devices") or census()["devices"]
    for row in devices.values():
        if row.get("limit"):
            limits.append(row["limit"])
    if not limits:
        return None
    return int(min(limits) * _state["min_headroom_fraction"])


def _ensure_provider():
    """Register the /healthz ``memory`` section once (first claim)."""
    with _lock:
        if _state["provider"]:
            return
        _state["provider"] = True
    from deeplearning4j_tpu.telemetry import health

    health.register_healthz_provider("memory", healthz_section)


def healthz_section():
    """The /healthz ``memory`` readiness detail: claimed totals, the
    per-device reconciliation, and the headroom judgement — headroom
    below the floor is ``degraded`` (still HTTP 200: low memory
    informs operators and admission control, it does not stop
    traffic)."""
    snap = census()
    hr = _headroom_from(snap)
    floor = _min_headroom(snap)
    led = get_memledger()
    out = {
        "claimed_bytes": led.total(),
        "claims": len(snap["claims"]),
        "devices": {
            label: {k: row[k] for k in
                    ("claimed_bytes", "in_use", "unattributed",
                     "limit", "headroom") if k in row}
            for label, row in snap["devices"].items()},
        "headroom_bytes": hr,
        "min_headroom_bytes": floor,
    }
    if hr is not None and floor is not None and hr < floor:
        out["degraded"] = True
        out["detail"] = (f"device headroom {hr} bytes below the "
                         f"{floor}-byte floor")
    return out


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

# XLA: "RESOURCE_EXHAUSTED: Out of memory allocating N bytes." /
# "... while trying to allocate N bytes"; host MemoryError has no count
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")
_BYTES_RE = re.compile(
    r"(?:allocat\w+\s+|allocate\s+)(\d+)\s*(?:bytes|B)\b")


def is_oom(exc) -> bool:
    """Is this exception a device/host allocation failure? (Typed
    DeviceOomErrors are excluded — already converted.)"""
    if isinstance(exc, DeviceOomError):
        return False
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def requested_bytes(exc) -> int | None:
    m = _BYTES_RE.search(str(exc))
    return int(m.group(1)) if m else None


def oom_error(exc, site, **context) -> DeviceOomError | None:
    """When ``exc`` is an allocation failure: record the flight ``oom``
    event (site, requested bytes, the top-N claims at failure) and
    return the typed :class:`DeviceOomError` for the seam to raise
    (``raise err from exc``) or fail requests with. None when ``exc``
    is not an OOM — the seam re-raises the original. Error path only,
    never steady state."""
    if not is_oom(exc):
        return None
    req = requested_bytes(exc)
    led = _state["ledger"]
    top = led.top() if isinstance(led, MemLedger) else []
    try:
        from deeplearning4j_tpu.telemetry import flight

        flight.record("oom", site=site, requested_bytes=req,
                      error=f"{type(exc).__name__}: {exc}",
                      claims=top, **context)
    except Exception:       # forensics must never mask the failure
        pass
    log.error("device OOM at %s (requested %s bytes); top claims: %s",
              site, req, [(c["category"], c["name"], c["bytes"])
                          for c in top[:3]])
    detail = f" requesting {req} bytes" if req is not None else ""
    return DeviceOomError(
        f"device out of memory at {site}{detail}: "
        f"{type(exc).__name__}: {exc}",
        site=site, requested_bytes=req, claims=top)


def raise_if_oom(exc, site, **context):
    """Seam helper: convert-and-raise when ``exc`` is an OOM, else
    return (the caller re-raises the original)."""
    err = oom_error(exc, site, **context)
    if err is not None:
        raise err from exc


# ---------------------------------------------------------------------------
# admission-time capacity planning
# ---------------------------------------------------------------------------

def plan_capacity(site, need_bytes, detail=None, device=None,
                  per_device=None):
    """Admit or reject a prospective allocation of ``need_bytes`` at
    ``site`` against live headroom. Raises :class:`CapacityError`
    (structured — BEFORE any compile or pool allocation) when headroom
    is known and exceeded; returns the plan dict otherwise. Unknown
    headroom admits: the planner refuses to guess.

    ``per_device`` upgrades the judgement from admitting to PLACING
    (ISSUE 19): a ``{device_label: share_bytes}`` shard layout is
    checked device by device — each device's share against that
    device's own headroom, never the sharded total against any single
    device — and the layout rides the ``capacity_plan`` flight event
    as the placement decision. Rejection carries the full per-device
    breakdown in ``CapacityError.detail["per_device"]``."""
    if per_device:
        return _plan_placement(site, need_bytes, per_device,
                               detail=detail)
    need = int(need_bytes)
    hr = headroom(device=device)
    plan = {"site": site, "need_bytes": need, "headroom_bytes": hr,
            "fits": hr is None or need <= hr,
            **({"detail": dict(detail)} if detail else {})}
    try:
        from deeplearning4j_tpu.telemetry import flight

        flight.record("capacity_plan", **{k: v for k, v in plan.items()
                                          if k != "detail"})
    except Exception:
        pass
    if not plan["fits"]:
        raise CapacityError(
            f"capacity planner rejected {site}: needs {need} bytes, "
            f"only {hr} bytes of device headroom "
            f"(breakdown: {detail or {}})",
            site=site, need_bytes=need, headroom_bytes=hr,
            detail=detail)
    return plan


def _plan_placement(site, need_bytes, per_device, detail=None):
    """The sharded half of :func:`plan_capacity`: judge a shard layout
    (``{device_label: share_bytes}``) against the headroom of exactly
    the mesh's device set. A device with unknown headroom admits its
    share (same refuse-to-guess rule as the scalar path)."""
    need = int(need_bytes)
    layout = {}
    worst = None          # tightest violated device, for the message
    for label, share in sorted(per_device.items()):
        share = int(share)
        hr = headroom(device=label)
        fits = hr is None or share <= hr
        layout[label] = {"share_bytes": share, "headroom_bytes": hr,
                         "fits": fits}
        if not fits and (worst is None
                         or hr - share < worst[2] - worst[1]):
            worst = (label, share, hr)
    plan = {"site": site, "need_bytes": need,
            "sharded": True, "devices": len(layout),
            "fits": worst is None, "per_device": layout,
            **({"detail": dict(detail)} if detail else {})}
    try:
        from deeplearning4j_tpu.telemetry import flight

        flight.record("capacity_plan",
                      **{k: v for k, v in plan.items()
                         if k != "detail"})
    except Exception:
        pass
    if worst is not None:
        label, share, hr = worst
        full = dict(detail or {})
        full["per_device"] = layout
        raise CapacityError(
            f"capacity planner rejected {site}: sharded placement over "
            f"{len(layout)} devices does not fit — {label} needs "
            f"{share} bytes against {hr} bytes of headroom "
            f"(per-device breakdown in detail)",
            site=site, need_bytes=need, headroom_bytes=hr,
            detail=full)
    return plan


def reset_state():
    """Forget claims and configuration (tests)."""
    with _lock:
        _state["ledger"] = None
        _state["enabled"] = True
        _state["budget"] = None
        _state["budget_resolved"] = False
        _state["min_headroom_bytes"] = None
        _state["min_headroom_fraction"] = 0.02
        _state["top_n"] = 8
