"""Declared service-level objectives evaluated by multi-window burn
rate over the time-series ring (ISSUE 16 tentpole, piece 2).

An SLO here is one of two shapes:

- **latency**: "``objective`` of observations of this histogram sample
  finish within ``threshold`` seconds" — the bad fraction over a window
  comes from cumulative bucket-count deltas
  (:func:`timeseries.bad_fraction`);
- **error_rate**: "at most ``1 - objective`` of this counter's traffic
  is bad" — ``bad`` selects the failure samples (substring match on the
  sample key, e.g. ``outcome="transport"``), ``total`` selects the
  denominator.

Evaluation is the SRE multi-window burn rate: burn = bad_fraction /
error_budget, computed over a **fast** and a **slow** window; a breach
fires only when BOTH exceed ``burn_threshold`` (fast alone = noise
spike, slow alone = old news), and recovery requires both to drop back.
Each evaluation emits ``dl4j_slo_burn_rate{slo,window}`` /
``dl4j_slo_healthy{slo}`` gauges and ``dl4j_slo_breaches_total{slo}``;
transitions record ``slo_breach`` / ``slo_recovered`` flight events, and
a registered /healthz provider reports the objectives as a ``slo``
section (degraded-not-503: a burning budget informs operators, it does
not stop traffic).

The evaluator ticks from the time-series sampler's post-sample hook, so
it inherits the sampler's cadence and its disabled contract: while
telemetry is disabled nothing samples, nothing evaluates, zero registry
calls (CountingStub-asserted).

:func:`histogram_burn` is the window-free variant over a live PR-1
Histogram — fleet/rollout.py uses it to judge a canary's burn against
the incumbent's over the mirror histograms.
"""

from __future__ import annotations

import logging
import threading

from deeplearning4j_tpu.telemetry import flight
from deeplearning4j_tpu.telemetry import registry as _registry
from deeplearning4j_tpu.telemetry import timeseries

log = logging.getLogger("deeplearning4j_tpu")

SLO_BURN_HELP = ("SLO burn rate per evaluation window (bad_fraction / "
                 "error_budget; 1.0 = burning exactly the budget, "
                 "sustained >1 on fast AND slow windows = breach)")
SLO_HEALTHY_HELP = "1 while the SLO is within budget, 0 while breached"
SLO_BREACHES_HELP = "Breach transitions (healthy->breached) per SLO"

# SRE-style defaults: a fast window for detection speed, a slow window
# so a single spike cannot page, sized for in-process rings rather than
# the textbook 5m/1h (the ring holds minutes, not hours)
DEFAULT_FAST_WINDOW = 60.0
DEFAULT_SLOW_WINDOW = 300.0
DEFAULT_BURN_THRESHOLD = 1.0


class SloInstruments:
    """Bound SLO gauges/counters (mirrors ServingInstruments: obtained
    per evaluation tick, None when telemetry is disabled)."""

    __slots__ = ("_burn", "_healthy", "_breaches")

    def __init__(self, registry):
        self._burn = registry.gauge(
            "dl4j_slo_burn_rate", SLO_BURN_HELP, ("slo", "window"))
        self._healthy = registry.gauge(
            "dl4j_slo_healthy", SLO_HEALTHY_HELP, ("slo",))
        self._breaches = registry.counter(
            "dl4j_slo_breaches_total", SLO_BREACHES_HELP, ("slo",))

    def burn(self, slo, window):
        return self._burn.labels(slo=slo, window=window)

    def healthy(self, slo):
        return self._healthy.labels(slo=slo)

    def breaches(self, slo):
        return self._breaches.labels(slo=slo)


def slo_instruments():
    """The SLO instrument bundle, or None when telemetry is disabled
    (the zero-cost-when-off contract, gate-listed in dl4jlint)."""
    if not _registry.enabled():
        return None
    return SloInstruments(_registry.get_registry())


class Slo:
    """One declared objective. ``kind`` is ``latency`` (histogram
    sample ``metric`` + ``threshold`` seconds) or ``error_rate``
    (``bad`` sample-key fragments over a ``total`` prefix)."""

    __slots__ = ("name", "kind", "metric", "threshold", "objective",
                 "bad", "total", "fast_window", "slow_window",
                 "burn_threshold")

    def __init__(self, name, kind="latency", metric=None, threshold=None,
                 objective=0.99, bad=(), total=None,
                 fast_window=DEFAULT_FAST_WINDOW,
                 slow_window=DEFAULT_SLOW_WINDOW,
                 burn_threshold=DEFAULT_BURN_THRESHOLD):
        if kind not in ("latency", "error_rate"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if kind == "latency" and (metric is None or threshold is None):
            raise ValueError("latency SLO needs metric= and threshold=")
        if kind == "error_rate" and (not bad or total is None):
            raise ValueError("error_rate SLO needs bad= and total=")
        if not 0.0 < objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        self.name = name
        self.kind = kind
        self.metric = metric
        self.threshold = threshold
        self.objective = float(objective)
        self.bad = (bad,) if isinstance(bad, str) else tuple(bad)
        self.total = total
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.burn_threshold = float(burn_threshold)

    @property
    def budget(self):
        """The error budget: the tolerated bad fraction."""
        return 1.0 - self.objective

    def describe(self):
        return {"name": self.name, "kind": self.kind,
                "metric": self.metric, "threshold": self.threshold,
                "objective": self.objective,
                "fast_window": self.fast_window,
                "slow_window": self.slow_window,
                "burn_threshold": self.burn_threshold}


class SloEvaluator:
    """Holds the declared objectives and their breach state; one
    ``evaluate()`` pass per time-series sample."""

    def __init__(self, sampler=None):
        self._sampler = sampler
        self._slos: dict = {}
        self._status: dict = {}   # name -> last evaluation dict
        self._lock = threading.Lock()
        self._hooked = False

    def _get_sampler(self):
        return self._sampler or timeseries.get_sampler()

    # -- declaration ---------------------------------------------------------
    def declare(self, slo: Slo):
        """Register (or replace) an objective and hook the evaluator
        into the sampler tick + /healthz on first declaration."""
        with self._lock:
            self._slos[slo.name] = slo
            self._status.setdefault(
                slo.name, {"healthy": True, "burn": {}})
        if not self._hooked:
            self._hooked = True
            self._get_sampler().on_sample(self.evaluate)
            # deferred: health imports registry; slo must stay
            # importable from telemetry/__init__ regardless of order
            from deeplearning4j_tpu.telemetry import health
            health.register_healthz_provider("slo", self.healthz_section)
        return slo

    def remove(self, name):
        with self._lock:
            self._slos.pop(name, None)
            self._status.pop(name, None)

    def slos(self):
        with self._lock:
            return list(self._slos.values())

    # -- evaluation ----------------------------------------------------------
    def _bad_fraction(self, slo, window, sampler):
        """Windowed bad fraction for one objective, or None with no
        traffic in the window (no traffic burns no budget)."""
        if slo.kind == "latency":
            bad, total = sampler.bad_fraction(
                slo.metric, slo.threshold, window)
            if bad is None or total == 0:
                return None
            return bad / total
        # error_rate: windowed increases of the selected counters
        pair = sampler._window_pair(window)
        if pair is None:
            return None
        old, new = pair
        bad = tot = 0.0

        def _increase(key):
            return max(new["values"].get(key, 0.0)
                       - old["values"].get(key, 0.0), 0.0)

        for key in new["values"]:
            if not key.startswith(slo.total):
                continue
            inc = _increase(key)
            tot += inc
            if any(frag in key for frag in slo.bad):
                bad += inc
        if tot == 0:
            return None
        return bad / tot

    def evaluate(self):
        """One burn-rate pass over every declared objective. Returns
        {name: result} or None while telemetry is disabled (zero
        registry/flight calls on the disabled path)."""
        if not _registry.enabled():
            return None
        sampler = self._get_sampler()
        inst = slo_instruments()
        results = {}
        for slo in self.slos():
            burns = {}
            for window_name, window in (("fast", slo.fast_window),
                                        ("slow", slo.slow_window)):
                frac = self._bad_fraction(slo, window, sampler)
                burns[window_name] = (
                    None if frac is None
                    else frac / max(slo.budget, 1e-9))
            breached = all(
                b is not None and b > slo.burn_threshold
                for b in burns.values())
            with self._lock:
                st = self._status.setdefault(
                    slo.name, {"healthy": True, "burn": {}})
                was_healthy = st["healthy"]
                # breach needs both windows hot; recovery needs both
                # back under threshold (an inconclusive window — no
                # traffic — holds the current state)
                if was_healthy and breached:
                    st["healthy"] = False
                elif not was_healthy and not breached and all(
                        b is not None and b <= slo.burn_threshold
                        for b in burns.values()):
                    st["healthy"] = True
                st["burn"] = burns
                now_healthy = st["healthy"]
            if inst is not None:
                for window_name, b in burns.items():
                    if b is not None:
                        inst.burn(slo.name, window_name).set(round(b, 6))
                inst.healthy(slo.name).set(1.0 if now_healthy else 0.0)
            if was_healthy and not now_healthy:
                if inst is not None:
                    inst.breaches(slo.name).inc()
                flight.record(
                    "slo_breach", slo=slo.name, slo_kind=slo.kind,
                    burn_fast=burns.get("fast"),
                    burn_slow=burns.get("slow"),
                    burn_threshold=slo.burn_threshold)
                log.warning("SLO %s breached (burn fast=%s slow=%s)",
                            slo.name, burns.get("fast"),
                            burns.get("slow"))
            elif not was_healthy and now_healthy:
                flight.record(
                    "slo_recovered", slo=slo.name,
                    burn_fast=burns.get("fast"),
                    burn_slow=burns.get("slow"))
                log.info("SLO %s recovered", slo.name)
            results[slo.name] = {"healthy": now_healthy, "burn": burns}
        return results

    # -- reads ---------------------------------------------------------------
    def healthz_section(self):
        """The /healthz ``slo`` section: per-objective burn + health,
        ``degraded`` truthy while any objective is breached (still
        HTTP 200 — the burn informs operators, traffic keeps flowing).
        None (section omitted) with nothing declared."""
        with self._lock:
            if not self._slos:
                return None
            objectives = {
                name: {"healthy": st.get("healthy", True),
                       "burn": st.get("burn", {}),
                       **self._slos[name].describe()}
                for name, st in self._status.items()
                if name in self._slos}
        return {"objectives": objectives,
                "degraded": any(not o["healthy"]
                                for o in objectives.values())}


# -- histogram-direct burn (the rollout judge) --------------------------------

def histogram_burn(hist, threshold, objective):
    """Burn rate of a live PR-1 Histogram child against a latency SLO
    (whole-history, no window — callers that need windows go through the
    evaluator). 0.0 with no observations: an idle canary burns nothing."""
    total = hist.count
    if total == 0:
        return 0.0
    good = 0
    for bound, c in zip(hist.buckets, hist.counts):
        good += c
        if bound >= float(threshold) * (1 - 1e-9):
            break   # covering bound reached; everything past it is bad
    bad_fraction = (total - good) / total
    return bad_fraction / max(1.0 - objective, 1e-9)


# -- module-level convenience (the gated entry points) ------------------------

_state = {"evaluator": None}
_lock = threading.Lock()


def get_evaluator() -> SloEvaluator:
    """The process-wide evaluator (created lazily). Raw handle —
    callers outside telemetry/ use the gated helpers below (the
    dl4jlint telemetry-gate contract)."""
    ev = _state["evaluator"]
    if ev is None:
        with _lock:
            ev = _state["evaluator"]
            if ev is None:
                ev = SloEvaluator()
                _state["evaluator"] = ev
    return ev


def set_evaluator(evaluator):
    """Swap the process evaluator (tests). Returns the previous one."""
    prev = _state["evaluator"]
    _state["evaluator"] = evaluator
    return prev


def declare(slo: Slo):
    return get_evaluator().declare(slo)


def remove(name):
    ev = _state["evaluator"]
    if ev is not None:
        ev.remove(name)


def evaluate():
    """One evaluation pass now (None while telemetry is disabled)."""
    return get_evaluator().evaluate()


def healthz_section():
    """The /healthz ``slo`` section (None with nothing declared) —
    read-only; the fleet router's hand-rolled healthz calls this
    directly since it does not use health.healthz()."""
    ev = _state["evaluator"]
    if ev is None:
        return None
    return ev.healthz_section()
