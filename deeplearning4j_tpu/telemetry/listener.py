"""MetricsListener: registry -> TrainingListener/StatsStorage bridge.

Reference capability: StatsListener fed StatsStorage, which the vertx
UI charted (SURVEY.md §2.7). MetricsListener keeps that machinery
working against the new registry: every `frequency` iterations it puts
one record holding the score plus a registry snapshot, so existing
dashboards (ui/server.py charts, FileStatsStorage JSONL consumers)
see telemetry without knowing the registry exists."""

from __future__ import annotations

import time

from deeplearning4j_tpu.telemetry.registry import enabled, get_registry
from deeplearning4j_tpu.utils.listeners import TrainingListener


class MetricsListener(TrainingListener):
    """Put {"session", "iteration", "epoch", "score", "metrics"} records
    into any StatsStorage. `metrics` is the flat registry snapshot
    (counters/gauges/histogram samples); set snapshot=False to record
    score-only rows at high frequency."""

    def __init__(self, storage, frequency=10, sessionId=None,
                 registry=None, snapshot=True):
        self.storage = storage
        self.frequency = max(1, int(frequency))
        self.session = sessionId or f"telemetry_{int(time.time())}"
        self.registry = registry
        self.snapshot = snapshot

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency != 0:
            return
        record = {
            "session": self.session,
            "iteration": iteration,
            "epoch": epoch,
            "timestamp": time.time(),
            "score": model.score(),
        }
        if self.snapshot and enabled():
            reg = self.registry or get_registry()
            record["metrics"] = reg.snapshot()
        self.storage.put(record)
