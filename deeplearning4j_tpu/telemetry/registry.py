"""Process-wide metrics registry: Counter / Gauge / Histogram / Timer.

Reference capability: the observability primitives behind OpProfiler /
PerformanceTracker / StatsListener (SURVEY.md §2.3, §2.7, §5) unified
into one registry the way a production serving stack expects — every
hot loop records through the same named instruments, exporters
(Prometheus text exposition, StatsStorage bridge, multi-host
aggregation) read one snapshot.

Design constraints (ISSUE 1 tentpole):

- zero-overhead when disabled: trainers call `loop_instruments(...)`
  ONCE per fit loop; it checks the module flag and returns None, so a
  disabled loop performs no registry calls per step;
- Histogram uses fixed log-scale buckets with a preallocated count
  list — `observe` is a bisect + two adds, no per-sample allocation;
- Timer doubles as a `jax.profiler.TraceAnnotation` context so the
  host-side span shows up in XPlane device traces (TensorBoard) at the
  same wall-clock position as the device work it covers;
- compile visibility comes from a `jax.monitoring` event listener (the
  jit-cache-miss hook): every backend compile increments
  `dl4j_compile_total` and adds to `dl4j_compile_seconds_total`;
- nothing here touches a device on the record path (`memory_stats` is
  read only when an exporter asks for it).
"""

from __future__ import annotations

import math
import threading
import time
from bisect import bisect_right

# -- module state ------------------------------------------------------------

_state = {"enabled": True, "registry": None}
_lock = threading.Lock()
_compile_hook_installed = False


def enabled() -> bool:
    return _state["enabled"]


def enable():
    _state["enabled"] = True
    _install_compile_hook()
    return get_registry()


def disable():
    _state["enabled"] = False


def get_registry() -> "MetricsRegistry":
    """The process-wide registry (created lazily; compile hook installed
    on first use)."""
    reg = _state["registry"]
    if reg is None:
        with _lock:
            reg = _state["registry"]
            if reg is None:
                reg = MetricsRegistry()
                _state["registry"] = reg
    _install_compile_hook()
    return reg


def set_registry(registry):
    """Swap the process registry (tests: counting stubs). Returns the
    previous registry."""
    prev = _state["registry"]
    _state["registry"] = registry
    return prev


# -- label handling ----------------------------------------------------------

def _label_key(labelnames, labels):
    if sorted(labels) != sorted(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared labelnames "
            f"{sorted(labelnames)}")
    return tuple((k, str(labels[k])) for k in labelnames)


class _Family:
    """One named metric family; unlabeled families hold their values
    directly, labeled ones hand out per-labelset children. `local` marks
    host-specific families (per-device gauges) that exporters render but
    snapshot()/aggregation skip — their label sets differ per host,
    which would break the identical-key-set aggregation contract."""

    kind = "untyped"

    def __init__(self, name, help="", labelnames=()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.local = False
        self._children = {}
        self._lock = threading.Lock()

    def labels(self, **labels):
        key = _label_key(self.labelnames, labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child()
                    self._children[key] = child
        return child

    def children(self):
        """[(labels_tuple, child)] — the unlabeled family yields itself
        under the empty labelset once it has been touched."""
        if self.labelnames:
            # copy under the lock: a /metrics scrape (UI server thread)
            # must not race a training thread's first labels() call
            with self._lock:
                return sorted(self._children.items())
        return [((), self)]

    def reset(self):
        with self._lock:
            self._children.clear()
        self._reset_self()


class Counter(_Family):
    """Monotonic counter. `inc(v)` with v >= 0."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self.value = 0.0

    def _make_child(self):
        return Counter(self.name)

    def _reset_self(self):
        self.value = 0.0

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        self.value += amount


class Gauge(_Family):
    """Last-value gauge."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self.value = 0.0

    def _make_child(self):
        return Gauge(self.name)

    def _reset_self(self):
        self.value = 0.0

    def set(self, value):
        self.value = float(value)

    def inc(self, amount=1.0):
        self.value += amount

    def dec(self, amount=1.0):
        self.value -= amount


def log_buckets(lo, hi, per_decade=4):
    """Fixed log-scale bucket upper bounds covering [lo, hi]."""
    if not (lo > 0 and hi > lo):
        raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
    n = int(math.ceil(math.log10(hi / lo) * per_decade)) + 1
    # 3 significant digits keep the exposition readable; per_decade <= 10
    # keeps rounded bounds strictly increasing
    return tuple(float(f"{lo * 10 ** (i / per_decade):.3g}")
                 for i in range(n))


# seconds: 100 us .. ~1000 s; bytes: 1 KiB .. ~64 GiB
SECONDS_BUCKETS = log_buckets(1e-4, 1e3, per_decade=4)
BYTES_BUCKETS = tuple(float(1 << (10 + 2 * i)) for i in range(14))


class Histogram(_Family):
    """Cumulative histogram over fixed bucket upper bounds (log-scale by
    default). observe() is allocation-free: one bisect into the
    precomputed bounds + integer adds."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets=SECONDS_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"histogram {name}: buckets must be "
                             "strictly increasing")
        # counts[i] = observations <= buckets[i]; counts[-1] = +Inf bucket
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        # bucket_index -> (trace_id, value, wall_ts): the last sampled
        # trace that landed in each bucket (OpenMetrics exemplars —
        # ISSUE 10: a p99 bucket links to a concrete span tree). Lazily
        # allocated; never part of snapshot()/aggregation.
        self.exemplars = None

    def _make_child(self):
        return Histogram(self.name, buckets=self.buckets)

    def _reset_self(self):
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.exemplars = None

    def observe(self, value, exemplar=None):
        idx = bisect_right(self.buckets, value)
        self.counts[idx] += 1
        self.sum += value
        if exemplar is not None:
            if self.exemplars is None:
                self.exemplars = {}
            self.exemplars[idx] = (exemplar, value, time.time())

    @property
    def count(self):
        return sum(self.counts)

    def time(self, annotation=None):
        """A Timer span feeding this histogram (and the XPlane trace)."""
        return Timer(self, annotation or self.name)


class Timer:
    """Span context: wall-clock into a Histogram AND a
    `jax.profiler.TraceAnnotation`, so the host span lands in XPlane
    device traces (TensorBoard trace viewer) alongside the device ops it
    covers. Reusable (one observation per with-block); also usable
    standalone with histogram=None as a pure trace annotation."""

    __slots__ = ("histogram", "name", "exemplar", "_t0", "_ann")

    def __init__(self, histogram, name):
        self.histogram = histogram
        self.name = name
        self.exemplar = None   # trace id attached to the observation
        self._t0 = 0.0
        self._ann = None

    def __enter__(self):
        try:
            import jax

            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:  # profiling unavailable: keep timing
            self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        if self.histogram is not None:
            self.histogram.observe(dt, exemplar=self.exemplar)
        return False


def span(name):
    """Pure TraceAnnotation span (no metric) — host-side phase marker
    for XPlane traces."""
    return Timer(None, name)


# -- registry ----------------------------------------------------------------

class MetricsRegistry:
    """Name -> metric family. Re-registering an existing name returns
    the existing family (and rejects a kind/labelnames mismatch), so
    every module can declare its instruments idempotently."""

    def __init__(self):
        self._metrics: dict = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        fam = self._metrics.get(name)
        if fam is not None:
            if not isinstance(fam, cls) or \
                    fam.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{fam.kind} with labels {fam.labelnames}")
            return fam
        with self._lock:
            fam = self._metrics.get(name)
            if fam is None:
                fam = cls(name, help, labelnames, **kw)
                self._metrics[name] = fam
            return fam

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=SECONDS_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def timer(self, name, help="", labelnames=(),
              buckets=SECONDS_BUCKETS) -> Timer:
        """Timer over a same-named histogram (seconds)."""
        return self.histogram(name, help, labelnames, buckets).time()

    def collect(self):
        """Metric families, name-sorted (exporter entry point). Copied
        under the lock so a concurrent first-time registration cannot
        resize the dict mid-iteration."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self):
        for fam in self.collect():
            fam.reset()

    # -- snapshot (the aggregation/exchange format) --------------------------
    def snapshot(self) -> dict:
        """Flat {sample_name: float} of every sample, histogram buckets
        included — the unit of multi-host aggregation. Keys are
        Prometheus sample names with sorted label sets, so identical
        instrument sets on every host produce identical key order.
        Families marked local (device-memory gauges) are skipped: their
        per-host label sets would defeat cross-host aggregation."""
        out = {}
        for fam in self.collect():
            if fam.local:
                continue
            for labels, child in fam.children():
                base = _sample_name(fam.name, labels)
                if fam.kind == "histogram":
                    acc = 0
                    for b, c in zip(child.buckets, child.counts):
                        acc += c
                        out[_sample_name(fam.name + "_bucket",
                                         labels + (("le", fmt_float(b)),)
                                         )] = float(acc)
                    out[_sample_name(fam.name + "_bucket",
                                     labels + (("le", "+Inf"),))] = \
                        float(child.count)
                    out[_sample_name(fam.name + "_sum", labels)] = \
                        float(child.sum)
                    out[_sample_name(fam.name + "_count", labels)] = \
                        float(child.count)
                else:
                    out[base] = float(child.value)
        return out


def _sample_name(name, labels):
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


def fmt_float(v):
    """Canonical number formatting shared by snapshot keys and the
    Prometheus exposition (integers render bare, le bounds stay short)."""
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# -- the standard instrument set for training loops --------------------------

STEP_HELP = ("Training step wall time in seconds (host dispatch region; "
             "equals device step time in steady state via dispatch-queue "
             "backpressure — no extra sync is added to measure it)")
ETL_HELP = "Seconds the training loop spent waiting for the next batch"
EXAMPLES_HELP = "Examples consumed by training steps"


class LoopInstruments:
    """Bound instruments for one training loop. Obtained once per fit()
    via loop_instruments(); None when telemetry is disabled, so the
    disabled loop body performs zero registry calls."""

    __slots__ = ("step", "etl", "examples", "loop", "_registry",
                 "step_flops")

    def __init__(self, registry, loop):
        self.loop = loop
        self._registry = registry
        self.step = registry.histogram(
            "dl4j_step_seconds", STEP_HELP, ("loop",)).labels(loop=loop)
        self.etl = registry.histogram(
            "dl4j_etl_wait_seconds", ETL_HELP, ("loop",)).labels(loop=loop)
        self.examples = registry.counter(
            "dl4j_examples_total", EXAMPLES_HELP, ("loop",)).labels(
                loop=loop)
        self.step_flops = None   # set via note_flops (costmodel)

    def step_span(self):
        """TraceAnnotation+timer around the step dispatch region."""
        return Timer(self.step, f"dl4j_step/{self.loop}")

    def note_flops(self, flops):
        """Attach the loop's cost-model FLOPs-per-step (ISSUE 10):
        every subsequent record_step refreshes the live dl4j_mfu
        gauge."""
        if flops:
            self.step_flops = float(flops)

    def record_step(self, seconds, examples=0, exemplar=None):
        self.step.observe(seconds, exemplar=exemplar)
        if examples:
            self.examples.inc(examples)
        if self.step_flops:
            from deeplearning4j_tpu.telemetry import costmodel

            costmodel.publish_mfu(self.loop, self.step_flops, seconds,
                                  registry=self._registry)

    def record_etl_wait(self, seconds):
        self.etl.observe(seconds)


def loop_instruments(loop):
    """The per-loop instrument bundle, or None when telemetry is
    disabled. Call once before the hot loop and guard per-step recording
    on the result — that keeps the disabled path at one module-flag
    check per fit() and zero registry calls per step."""
    if not _state["enabled"]:
        return None
    return LoopInstruments(get_registry(), loop)


# -- the standard instrument set for the streaming ETL engine (ISSUE 6) ------

ETL_QUEUE_DEPTH_HELP = ("Decoded batches queued between the ETL worker "
                        "pool and the consumer")
ETL_RING_HELP = ("Occupied slots in the shared-memory batch ring "
                 "(bounded by the ring size; persistently full = "
                 "consumer-bound, empty = decode-bound)")
ETL_DECODED_HELP = "Images decoded by the ETL pipeline"
ETL_PREFETCH_HITS_HELP = ("Device-prefetch queue hits (a batch was "
                          "already staged when the trainer asked)")
ETL_PREFETCH_MISSES_HELP = ("Device-prefetch queue misses (the trainer "
                            "blocked waiting for the producer thread)")
ETL_PREFETCH_DEPTH_HELP = "Batches currently staged by the DevicePrefetcher"


class EtlInstruments:
    """Bound instruments for one ETL pipeline (mirrors LoopInstruments:
    obtained once per iterator/prefetcher, None when telemetry is
    disabled, so a disabled pipeline performs zero registry calls per
    batch)."""

    __slots__ = ("loop", "queue_depth", "ring_occupancy", "decoded",
                 "prefetch_hits", "prefetch_misses", "prefetch_depth")

    def __init__(self, registry, loop):
        self.loop = loop
        self.queue_depth = registry.gauge(
            "dl4j_etl_queue_depth", ETL_QUEUE_DEPTH_HELP,
            ("loop",)).labels(loop=loop)
        self.ring_occupancy = registry.gauge(
            "dl4j_etl_shm_ring_occupancy", ETL_RING_HELP,
            ("loop",)).labels(loop=loop)
        self.decoded = registry.counter(
            "dl4j_etl_decoded_images_total", ETL_DECODED_HELP,
            ("loop",)).labels(loop=loop)
        self.prefetch_hits = registry.counter(
            "dl4j_etl_prefetch_hits_total", ETL_PREFETCH_HITS_HELP,
            ("loop",)).labels(loop=loop)
        self.prefetch_misses = registry.counter(
            "dl4j_etl_prefetch_misses_total", ETL_PREFETCH_MISSES_HELP,
            ("loop",)).labels(loop=loop)
        self.prefetch_depth = registry.gauge(
            "dl4j_etl_prefetch_depth", ETL_PREFETCH_DEPTH_HELP,
            ("loop",)).labels(loop=loop)


def etl_instruments(loop):
    """The per-pipeline ETL instrument bundle, or None when telemetry
    is disabled (same zero-cost-when-off contract as
    loop_instruments)."""
    if not _state["enabled"]:
        return None
    return EtlInstruments(get_registry(), loop)


# -- the standard instrument set for inference serving (ISSUE 2) -------------

SERVING_REQUESTS_HELP = ("Inference requests by terminal outcome "
                         "(ok|timeout_queued|timeout_execute|rejected|"
                         "shed|error|shutdown)")
SERVING_QUEUE_HELP = "Seconds a request waited in the batching queue"
SERVING_EXECUTE_HELP = ("Seconds per coalesced device dispatch (pad + "
                        "execute + split, host-visible)")
SERVING_OCCUPANCY_HELP = ("Real rows / bucket rows of the last coalesced "
                          "dispatch (1.0 = perfectly filled bucket)")
SERVING_DISPATCH_HELP = "Coalesced device dispatches executed"
SERVING_DEPTH_HELP = "Requests currently queued for batching"
SERVING_STEALS_HELP = ("Batches executed by a replica that stole them "
                       "from a sibling's run queue")
SERVING_REPLICA_LOAD_HELP = ("Queued + in-flight batches per replica "
                             "(-1 = replica dead)")
SERVING_SHED_HELP = ("Requests shed by admission control, by priority "
                     "class (HTTP 429 + Retry-After)")
SERVING_TOKENS_HELP = "Tokens emitted by continuous-batching decode"
SERVING_SLOTS_HELP = ("Decode slots currently occupied by in-flight "
                      "sequences")
SERVING_PREFIX_HITS_HELP = ("Decode admissions that adopted cached "
                            "prefix KV pages (prefill skipped for the "
                            "shared prefix)")
SERVING_PREFIX_MISSES_HELP = ("Decode admissions with no cached "
                              "prefix pages to adopt")
DECODE_TTFT_HELP = ("Seconds from decode submit to the request's "
                    "first emitted token")
DECODE_ACCEPTED_HELP = ("Speculative-decode tokens by outcome: "
                        "accepted (emitted via a verify call), "
                        "rejected (drafted but refuted), fallback "
                        "(emitted by plain decode while speculation "
                        "is in acceptance fallback)")
SERVING_KV_OCCUPANCY_HELP = ("Fraction of the paged decode KV pool "
                             "currently reserved (0..1)")


class ServingInstruments:
    """Bound per-model serving instruments (mirrors LoopInstruments:
    obtained once per batcher, None when telemetry is disabled, so a
    disabled serving path performs zero registry calls per request)."""

    __slots__ = ("model", "_requests", "queue_wait", "execute",
                 "occupancy", "dispatch", "depth", "steals",
                 "_replica_load", "_shed", "tokens", "slots",
                 "prefix_hits", "prefix_misses", "ttft", "_accepted",
                 "kv_occupancy")

    def __init__(self, registry, model):
        self.model = model
        self._requests = registry.counter(
            "dl4j_serving_requests_total", SERVING_REQUESTS_HELP,
            ("model", "outcome"))
        self.queue_wait = registry.histogram(
            "dl4j_serving_queue_wait_seconds", SERVING_QUEUE_HELP,
            ("model",)).labels(model=model)
        self.execute = registry.histogram(
            "dl4j_serving_execute_seconds", SERVING_EXECUTE_HELP,
            ("model",)).labels(model=model)
        self.occupancy = registry.gauge(
            "dl4j_serving_batch_occupancy", SERVING_OCCUPANCY_HELP,
            ("model",)).labels(model=model)
        self.dispatch = registry.counter(
            "dl4j_serving_dispatch_total", SERVING_DISPATCH_HELP,
            ("model",)).labels(model=model)
        self.depth = registry.gauge(
            "dl4j_serving_queue_depth", SERVING_DEPTH_HELP,
            ("model",)).labels(model=model)
        self.steals = registry.counter(
            "dl4j_serving_steals_total", SERVING_STEALS_HELP,
            ("model",)).labels(model=model)
        self._replica_load = registry.gauge(
            "dl4j_serving_replica_load", SERVING_REPLICA_LOAD_HELP,
            ("model", "replica"))
        self._shed = registry.counter(
            "dl4j_serving_shed_total", SERVING_SHED_HELP,
            ("model", "priority"))
        self.tokens = registry.counter(
            "dl4j_serving_decode_tokens_total", SERVING_TOKENS_HELP,
            ("model",)).labels(model=model)
        self.slots = registry.gauge(
            "dl4j_serving_decode_slots", SERVING_SLOTS_HELP,
            ("model",)).labels(model=model)
        self.prefix_hits = registry.counter(
            "dl4j_serving_prefix_hits_total", SERVING_PREFIX_HITS_HELP,
            ("model",)).labels(model=model)
        self.prefix_misses = registry.counter(
            "dl4j_serving_prefix_misses_total",
            SERVING_PREFIX_MISSES_HELP, ("model",)).labels(model=model)
        self.ttft = registry.histogram(
            "dl4j_decode_ttft_seconds", DECODE_TTFT_HELP,
            ("model",)).labels(model=model)
        self._accepted = registry.counter(
            "dl4j_decode_accepted_tokens_total", DECODE_ACCEPTED_HELP,
            ("model", "outcome"))
        self.kv_occupancy = registry.gauge(
            "dl4j_serving_kv_page_occupancy",
            SERVING_KV_OCCUPANCY_HELP, ("model",)).labels(model=model)

    def request(self, outcome):
        self._requests.labels(model=self.model, outcome=outcome).inc()

    def replica_load(self, replica):
        return self._replica_load.labels(model=self.model,
                                         replica=replica)

    def shed(self, priority):
        self._shed.labels(model=self.model, priority=priority).inc()

    def accepted(self, outcome, n=1):
        self._accepted.labels(model=self.model, outcome=outcome).inc(n)


def serving_instruments(model):
    """Per-model serving instrument bundle, or None when disabled."""
    if not _state["enabled"]:
        return None
    return ServingInstruments(get_registry(), model)


# -- the fleet-router instrument set (ISSUE 15) ------------------------------

FLEET_REQUESTS_HELP = ("Fleet requests routed, by worker and outcome "
                       "(ok|shed|timeout|client_error|upstream_error|"
                       "transport|no_worker)")
FLEET_WORKER_UP_HELP = ("Router's view of a worker: 1 = routable, "
                        "0 = ejected by the transport breaker or down")
FLEET_RETRIES_HELP = ("Requests re-sent to a surviving worker after a "
                      "transport failure (the client never saw the "
                      "death)")
FLEET_ROLLOUT_STATE_HELP = ("Rollout state machine position: -1 "
                            "rolled_back, 0 idle, 1 canary, 2 "
                            "promoting, 3 complete")
FLEET_HOP_HELP = ("Router→worker hop seconds (forward + worker "
                  "service + response read)")
FLEET_HOP_PHASE_HELP = ("Router→worker hop seconds decomposed by phase "
                        "(queue|execute|worker_other|transit) from the "
                        "workers' Server-Timing header: queue/execute "
                        "are worker-reported, worker_other is worker "
                        "handler time outside both, transit is the "
                        "serialize+network+parse remainder the router "
                        "attributes by subtraction)")
FLEET_MIRROR_HELP = ("Canary mirror comparisons by verdict "
                     "(agree|disagree|error)")
FLEET_CAPTURED_HELP = ("Live requests head-sampled into the traffic-"
                       "capture ring (train-from-traffic)")
FLEET_RESPAWNS_HELP = ("Autopilot respawn attempts of dead spawned "
                       "workers, by worker and outcome "
                       "(ok|failed|gave_up)")
FLEET_TARGET_WORKERS_HELP = ("Autoscaler's current desired fleet size "
                             "(spawn/retire decisions converge the "
                             "actual size toward it)")


class FleetInstruments:
    """Bound fleet-router instruments (mirrors ServingInstruments:
    obtained once per router, None when telemetry is disabled, so a
    disabled router performs zero registry calls per request)."""

    __slots__ = ("_requests", "_worker_up", "retries", "rollout_state",
                 "_hop", "_hop_phase", "_mirror", "captured",
                 "_respawns", "target_workers")

    def __init__(self, registry):
        self._requests = registry.counter(
            "dl4j_fleet_requests_total", FLEET_REQUESTS_HELP,
            ("worker", "outcome"))
        self._worker_up = registry.gauge(
            "dl4j_fleet_worker_up", FLEET_WORKER_UP_HELP, ("worker",))
        self.retries = registry.counter(
            "dl4j_fleet_retries_total", FLEET_RETRIES_HELP)
        self.rollout_state = registry.gauge(
            "dl4j_fleet_rollout_state", FLEET_ROLLOUT_STATE_HELP)
        self._hop = registry.histogram(
            "dl4j_fleet_request_seconds", FLEET_HOP_HELP, ("worker",))
        self._hop_phase = registry.histogram(
            "dl4j_fleet_hop_seconds", FLEET_HOP_PHASE_HELP, ("phase",))
        self._mirror = registry.counter(
            "dl4j_fleet_mirror_total", FLEET_MIRROR_HELP, ("verdict",))
        self.captured = registry.counter(
            "dl4j_fleet_captured_total", FLEET_CAPTURED_HELP)
        self._respawns = registry.counter(
            "dl4j_fleet_respawns_total", FLEET_RESPAWNS_HELP,
            ("worker", "outcome"))
        self.target_workers = registry.gauge(
            "dl4j_fleet_target_workers", FLEET_TARGET_WORKERS_HELP)

    def request(self, worker, outcome):
        self._requests.labels(worker=worker, outcome=outcome).inc()

    def worker_up(self, worker):
        return self._worker_up.labels(worker=worker)

    def hop(self, worker):
        return self._hop.labels(worker=worker)

    def hop_phase(self, phase):
        return self._hop_phase.labels(phase=phase)

    def mirror(self, verdict):
        self._mirror.labels(verdict=verdict).inc()

    def respawn(self, worker, outcome):
        self._respawns.labels(worker=worker, outcome=outcome).inc()


def fleet_instruments():
    """The fleet-router instrument bundle, or None when telemetry is
    disabled (the zero-cost-when-off contract, gate-listed in the
    dl4jlint telemetry-gate rule)."""
    if not _state["enabled"]:
        return None
    return FleetInstruments(get_registry())


# -- compile visibility (jit-cache-miss hook) --------------------------------

COMPILE_HELP = "XLA backend compiles observed in this process"


def _install_compile_hook():
    """Register a jax.monitoring listener once per process: every
    backend compile (a jit cache miss reaching XLA) bumps
    dl4j_compile_total / dl4j_compile_seconds_total. The listener checks
    the enabled flag first, so disabling telemetry silences it."""
    global _compile_hook_installed
    if _compile_hook_installed:
        return
    with _lock:
        if _compile_hook_installed:
            return
        _compile_hook_installed = True
    try:
        import jax.monitoring as monitoring
    except Exception:
        return

    def _on_duration(key, seconds, **kw):
        if not _state["enabled"]:
            return
        reg = _state["registry"]
        if reg is None or not key.endswith("backend_compile_duration"):
            return
        try:
            reg.counter("dl4j_compile_total", COMPILE_HELP).inc()
            reg.counter("dl4j_compile_seconds_total",
                        "Seconds spent in XLA backend compiles").inc(
                            seconds)
        except Exception:
            pass  # stub registries without counter() must not break jit
        try:
            from deeplearning4j_tpu.telemetry import flight

            flight.record("compile", seconds=round(seconds, 6))
        except Exception:
            pass  # the flight recorder must never break jit either
        try:
            # compile-ledger attribution (ISSUE 11): mark this thread
            # so the site live on it (fit-loop note_step / servable
            # warmup) can claim the compile seconds
            from deeplearning4j_tpu.telemetry import compile_ledger

            compile_ledger.note_backend_compile(seconds)
        except Exception:
            pass  # the ledger must never break jit either

    monitoring.register_event_duration_secs_listener(_on_duration)


# -- device memory (read on demand by exporters, never per step) -------------

DEVICE_MEM_HELP = ("Device memory from device.memory_stats(), absent on "
                   "backends that do not report it (e.g. CPU)")
DEVICE_MEM_STATS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                    "largest_free_block_bytes")


def collect_device_memory(registry=None):
    """Refresh dl4j_device_mem_bytes from each local device's
    memory_stats(). The family is registered even when no device reports
    stats (CPU), so the metric name is always present in the exposition;
    samples appear only where the backend provides them."""
    if not _state["enabled"]:
        return
    reg = registry or get_registry()
    gauge = reg.gauge("dl4j_device_mem_bytes", DEVICE_MEM_HELP,
                      ("device", "stat"))
    gauge.local = True  # device ids are host-specific: scrape-only
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for key in DEVICE_MEM_STATS:
            if key in stats:
                gauge.labels(device=f"{d.platform}:{d.id}",
                             stat=key).set(stats[key])
