"""Windowed metric time series: a bounded in-process ring of periodic
registry snapshots (ISSUE 16 tentpole, piece 1).

Every observability layer before this PR is an instantaneous view — a
/metrics scrape says *how much so far*, never *how fast right now* or
*what was p99 over the last minute*. This module adds the time
dimension without any external TSDB:

- a background sampler appends one bounded snapshot per interval for
  the *selected* metric families (name-prefix allowlist — sampling the
  whole registry would make the always-on cost proportional to
  instrument count, not interest);
- counters become **rates** (delta / monotonic-elapsed between the two
  samples bracketing a window);
- log-bucket histograms become **windowed quantiles** (cumulative
  bucket-count deltas over the window, read exactly the way Prometheus
  would read ``increase()`` + ``histogram_quantile``);
- the ring is queryable at ``GET /debug/timeseries`` (ui/server.py and
  the fleet router) and feeds the SLO burn-rate evaluator
  (telemetry/slo.py).

Disabled contract (the PR-1 rule): ``telemetry.disable()`` makes
``sample_now()`` return before touching the registry, so a disabled
process performs ZERO registry calls per tick — and the sampler is
periodic, never per-request, so the request path performs zero
time-series calls whether enabled or not (CountingStub-asserted in
tests/test_fleet_slo.py).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from deeplearning4j_tpu.telemetry import registry as _registry
from deeplearning4j_tpu.telemetry.registry import _sample_name

log = logging.getLogger("deeplearning4j_tpu")

DEFAULT_INTERVAL = 5.0
DEFAULT_CAPACITY = 720          # 1 h of history at the default interval
# the families worth a time dimension out of the box: serving + fleet
# request traffic, training step time, and the SLO layer's own gauges
DEFAULT_PREFIXES = ("dl4j_serving_", "dl4j_fleet_", "dl4j_step_seconds",
                    "dl4j_slo_")

_state = {"sampler": None}
_lock = threading.Lock()


class TimeSeriesSampler:
    """Bounded ring of periodic windowed snapshots. ``sample_now`` is
    the only registry-touching entry point: one pass over the selected
    families, one deque append — no I/O, no device work, and an early
    return (zero registry calls) while telemetry is disabled."""

    def __init__(self, interval=DEFAULT_INTERVAL,
                 capacity=DEFAULT_CAPACITY, prefixes=DEFAULT_PREFIXES):
        self.interval = float(interval)
        self.capacity = int(capacity)
        self.prefixes = tuple(prefixes)
        self._samples: deque = deque(maxlen=self.capacity)
        self._kinds: dict = {}      # sample key -> counter|gauge
        self._bounds: dict = {}     # histogram key -> bucket bounds
        self._callbacks: list = []  # post-sample hooks (SLO evaluator)
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()

    # -- sampling ------------------------------------------------------------
    def sample_now(self):
        """Append one snapshot of the selected families; returns the
        sample dict, or None while telemetry is disabled (zero registry
        calls on the disabled path)."""
        if not _registry.enabled():
            return None
        reg = _registry.get_registry()
        values, hist = {}, {}
        for fam in reg.collect():
            if fam.local or not fam.name.startswith(self.prefixes):
                continue
            for labels, child in fam.children():
                key = _sample_name(fam.name, labels)
                if fam.kind == "histogram":
                    # non-cumulative per-slot counts: deltas stay
                    # per-slot and cumulate only at quantile time
                    hist[key] = (tuple(child.counts), child.sum)
                    self._bounds[key] = child.buckets
                else:
                    values[key] = float(child.value)
                    self._kinds[key] = fam.kind
        sample = {"ts": round(time.time(), 6),
                  "mono": time.monotonic(),
                  "values": values, "hist": hist}
        with self._lock:
            self._samples.append(sample)
        for cb in list(self._callbacks):
            try:
                cb()
            except Exception:
                log.exception("timeseries post-sample callback failed")
        return sample

    def on_sample(self, callback):
        """Run ``callback()`` after every appended sample (the SLO
        evaluator's tick). Idempotent per callback object."""
        if callback not in self._callbacks:
            self._callbacks.append(callback)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Start the background sampling thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="dl4j:telemetry:timeseries")
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.sample_now()
            except Exception:
                # a sampler crash must never take serving down with it
                log.exception("timeseries sample failed")

    def clear(self):
        with self._lock:
            self._samples.clear()

    def __len__(self):
        return len(self._samples)

    # -- windowed reads ------------------------------------------------------
    def _window_pair(self, window=None):
        """(oldest-in-window, newest) samples, or None with <2 samples.
        ``window=None`` spans the whole ring."""
        with self._lock:
            samples = list(self._samples)
        if len(samples) < 2:
            return None
        newest = samples[-1]
        if window is None:
            return samples[0], newest
        horizon = newest["mono"] - float(window)
        oldest = newest
        for s in samples:
            if s["mono"] >= horizon:
                oldest = s
                break
        if oldest is newest:
            oldest = samples[-2]   # degenerate window: last two ticks
        return oldest, newest

    def series(self, key, limit=None):
        """[[wall_ts, value], ...] for one counter/gauge sample key."""
        with self._lock:
            samples = list(self._samples)
        out = [[s["ts"], s["values"][key]] for s in samples
               if key in s["values"]]
        return out[-int(limit):] if limit else out

    def rate(self, key, window=None):
        """Per-second increase of a counter sample over the window
        (None without two samples; clamped at 0 across a reset)."""
        pair = self._window_pair(window)
        if pair is None:
            return None
        old, new = pair
        if key not in old["values"] or key not in new["values"]:
            return None
        dt = new["mono"] - old["mono"]
        if dt <= 0:
            return None
        return max(new["values"][key] - old["values"][key], 0.0) / dt

    def _hist_delta(self, key, window=None):
        """(per-slot count deltas, bounds, total) for one histogram
        sample over the window, or None."""
        pair = self._window_pair(window)
        if pair is None:
            return None
        old, new = pair
        if key not in new["hist"]:
            return None
        new_counts = new["hist"][key][0]
        old_entry = old["hist"].get(key)
        old_counts = old_entry[0] if old_entry else (0,) * len(new_counts)
        if len(old_counts) != len(new_counts):
            old_counts = (0,) * len(new_counts)
        delta = [max(n - o, 0) for n, o in zip(new_counts, old_counts)]
        return delta, self._bounds.get(key, ()), sum(delta)

    def quantile(self, key, q=0.99, window=None):
        """Windowed quantile of a histogram sample: the smallest bucket
        upper bound covering ``q`` of the window's observations (the
        Prometheus ``histogram_quantile(increase(...))`` read). None
        without data in the window."""
        d = self._hist_delta(key, window)
        if d is None or d[2] == 0:
            return None
        delta, bounds, total = d
        target = q * total
        acc = 0
        for bound, c in zip(bounds, delta):
            acc += c
            if acc >= target:
                return bound
        return bounds[-1] if bounds else None

    def bad_fraction(self, key, threshold, window=None):
        """(observations above ``threshold``, total observations) for a
        histogram sample over the window — the latency-SLO read.
        ``threshold`` is quantized UP to the covering bucket bound
        (observations at or under that bound count as good), so a
        threshold between bounds errs toward healthy by at most one
        bucket step. (None, 0) without data."""
        d = self._hist_delta(key, window)
        if d is None:
            return None, 0
        delta, bounds, total = d
        if total == 0:
            return None, 0
        good = 0
        for bound, c in zip(bounds, delta):
            good += c
            if bound >= float(threshold) * (1 - 1e-9):
                break   # this bound covers the threshold; rest is bad
        return total - good, total

    def window_summary(self, window=None):
        """Derived view of the newest window: counter rates, last gauge
        values, histogram p50/p99 + observation rates."""
        pair = self._window_pair(window)
        if pair is None:
            return {"rates": {}, "gauges": {}, "quantiles": {}}
        old, new = pair
        dt = max(new["mono"] - old["mono"], 1e-9)
        rates, gauges, quantiles = {}, {}, {}
        for key, v in new["values"].items():
            if self._kinds.get(key) == "counter":
                r = max(v - old["values"].get(key, 0.0), 0.0) / dt
                rates[key] = round(r, 6)
            else:
                gauges[key] = v
        for key in new["hist"]:
            d = self._hist_delta(key, window)
            if d is None:
                continue
            total = d[2]
            quantiles[key] = {
                "p50": self.quantile(key, 0.5, window),
                "p99": self.quantile(key, 0.99, window),
                "count": total,
                "rate": round(total / dt, 6),
            }
        return {"window_seconds": round(dt, 3), "rates": rates,
                "gauges": gauges, "quantiles": quantiles}

    def describe(self, window=None, name=None):
        """The GET /debug/timeseries payload: sampler config, the
        windowed derived view, and raw counter/gauge series (optionally
        filtered by ``name`` prefix)."""
        with self._lock:
            samples = list(self._samples)
        span = (samples[-1]["mono"] - samples[0]["mono"]
                if len(samples) > 1 else 0.0)
        series = {}
        if samples:
            for key in sorted(samples[-1]["values"]):
                if name and not key.startswith(name):
                    continue
                series[key] = self.series(key)
        summary = self.window_summary(window)
        if name:
            for section in ("rates", "gauges", "quantiles"):
                summary[section] = {
                    k: v for k, v in summary.get(section, {}).items()
                    if k.startswith(name)}
        return {
            "config": {"interval": self.interval,
                       "capacity": self.capacity,
                       "prefixes": list(self.prefixes)},
            "samples": len(samples),
            "span_seconds": round(span, 3),
            "window": summary,
            "series": series,
        }


# -- module-level convenience (the gated entry points) ------------------------

def get_sampler() -> TimeSeriesSampler:
    """The process-wide sampler (created lazily). Raw handle — callers
    outside telemetry/ go through the module helpers below, which gate
    on the enabled flag (the dl4jlint telemetry-gate contract)."""
    s = _state["sampler"]
    if s is None:
        with _lock:
            s = _state["sampler"]
            if s is None:
                s = TimeSeriesSampler()
                _state["sampler"] = s
    return s


def set_sampler(sampler):
    """Swap the process sampler (tests). Returns the previous one."""
    prev = _state["sampler"]
    _state["sampler"] = sampler
    return prev


def configure(interval=None, capacity=None, prefixes=None):
    """Reconfigure the process sampler in place (ring contents are
    preserved on an interval change, dropped on a capacity change)."""
    s = get_sampler()
    if interval is not None:
        s.interval = float(interval)
    if capacity is not None:
        s.capacity = int(capacity)
        with s._lock:
            s._samples = deque(s._samples, maxlen=s.capacity)
    if prefixes is not None:
        s.prefixes = tuple(prefixes)
    return s


def start():
    return get_sampler().start()


def stop(timeout=5.0):
    s = _state["sampler"]
    if s is not None:
        s.stop(timeout)


def sample_now():
    """One snapshot now (deterministic tests; returns None while
    telemetry is disabled — the zero-registry-calls gate lives in the
    sampler itself)."""
    return get_sampler().sample_now()


def on_sample(callback):
    get_sampler().on_sample(callback)


def rate(key, window=None):
    return get_sampler().rate(key, window)


def quantile(key, q=0.99, window=None):
    return get_sampler().quantile(key, q, window)


def bad_fraction(key, threshold, window=None):
    return get_sampler().bad_fraction(key, threshold, window)


def describe(window=None, name=None):
    """The GET /debug/timeseries payload — read-only, served whether or
    not telemetry is currently enabled (incident reads outlive a
    disable())."""
    return get_sampler().describe(window=window, name=name)


def clear():
    s = _state["sampler"]
    if s is not None:
        s.clear()
