"""Compile ledger + recompile forensics (ISSUE 11 tentpole).

PR 9 instrumented *runtime*; compilation stayed a black box: the
``dl4j_compile_total`` counter says a backend compile happened, not
*which site* compiled, *why* (new bucket? dtype flip? donation
mismatch? policy change?), or *what XLA produced*. This module is the
missing register:

- **executable ledger**: every train-step compile (fit / graph /
  sharded step sites) and every serving AOT warmup registers a record —
  site label, abstract argument signature (shapes / dtypes / sharding /
  donation / precision+health policy), compile seconds (attributed from
  the ``jax.monitoring`` backend-compile events the PR-1 hook already
  listens to), HLO fingerprint, cost-model FLOPs — bounded ring, read
  at ``GET /debug/compiles``;
- **recompile forensics**: on a cache miss at a previously-seen site
  the new signature is diffed against the last one and a structured
  *cause* is recorded — ``first_compile``, ``new_bucket`` (serving
  ladder growth), ``shape_change(dim=N)``, ``dtype_change``,
  ``donation_change``, ``policy_change`` (precision policy or health
  build plan compiled into the step), ``sharding_change``, ``rewarm``
  (identical signature rebuilt, e.g. a re-registered servable), or
  ``unknown`` — as a ``dl4j_compile_cause_total{site,cause}`` counter,
  a ``compile_ledger`` flight event, and a ``compile.lower`` span in
  the PR-9 trace tree when the step is inside a sampled trace;
- **HLO audit hookup**: AOT serving executables are audited eagerly at
  warmup (the Compiled object is in hand); train-step records keep a
  weakref + abstract args so ``GET /debug/hlo/<key>`` can lower,
  compile (cached by jax's AOT cache after the first ask), and audit
  on demand — the forensic hot path never pays an extra compile.

Hot-path contract (the PR-1/9 rule): ``note_step`` is called once per
recorded step by the instrumented loops, but its steady-state body is
ONE thread-local read — the ``jax.monitoring`` hook marks the thread
when a backend compile fires, and a step with no pending compile event
returns before touching the ledger, the signature, or anything else.
``telemetry.disable()`` removes the call entirely (the loops guard on
their instrument bundle), so a CountingStub ledger observes ZERO calls
per step and the jitted math is bit-identical.

/healthz gains a ``compile`` section (degraded-not-503, the PR-5/9
convention): sites currently inside a warmup ladder and their progress
fraction, via the standard healthz-provider seam.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict, deque, namedtuple

from deeplearning4j_tpu.telemetry import hlo_audit
from deeplearning4j_tpu.telemetry import registry as _registry

DEFAULT_CAPACITY = 512

CAUSE_HELP = ("Compile-ledger records by step/serving site and "
              "forensic cause (first_compile|new_bucket|"
              "shape_change(dim=N)|shape_change(rank)|dtype_change|"
              "donation_change|policy_change|sharding_change|rewarm|"
              "cache_hit|cache_reject|unknown). cache_hit = the "
              "executable was deserialized from the persistent store "
              "(zero XLA compiles); cache_reject = a corrupt/stale "
              "store entry was dropped and the site recompiled")

_state = {"enabled": True, "ledger": None}
_lock = threading.Lock()
_tls = threading.local()


def enabled() -> bool:
    """Ledger is live: the telemetry master switch AND the ledger flag
    (``telemetry.disable()`` compiles the ledger out with the rest)."""
    return _state["enabled"] and _registry.enabled()


def configure(enabled=None, capacity=None):
    if enabled is not None:
        _state["enabled"] = bool(enabled)
    if capacity is not None:
        get_ledger().resize(int(capacity))


def get_ledger() -> "CompileLedger":
    """The process-wide ledger (created lazily)."""
    led = _state["ledger"]
    if led is None:
        with _lock:
            led = _state["ledger"]
            if led is None:
                led = CompileLedger()
                _state["ledger"] = led
    return led


def set_ledger(ledger):
    """Swap the process ledger (tests: counting stubs). Returns the
    previous ledger."""
    prev = _state["ledger"]
    _state["ledger"] = ledger
    return prev


# ---------------------------------------------------------------------------
# compile-event attribution (fed by the jax.monitoring hook in
# telemetry.registry): backend compiles run synchronously on the
# dispatching thread, so a per-thread buffer attributes them to the
# step/warmup that is live on that thread
# ---------------------------------------------------------------------------

def note_backend_compile(seconds):
    """Called from the PR-1 jit-cache-miss hook: stash this thread's
    compile seconds for the next note_step/record on the same thread.
    Bounded (deque) so a thread nobody ledgers on cannot grow it."""
    if not enabled():
        return
    buf = getattr(_tls, "compiles", None)
    if buf is None:
        buf = _tls.compiles = deque(maxlen=256)
    buf.append(float(seconds))


def consume_backend_compiles():
    """Total backend-compile seconds on this thread since the last
    consume, or None when no compile fired — the note_step fast path."""
    buf = getattr(_tls, "compiles", None)
    if not buf:
        return None
    total = sum(buf)
    buf.clear()
    return total


# ---------------------------------------------------------------------------
# signatures and forensic classification
# ---------------------------------------------------------------------------

# args: tuple of (shape tuple, dtype str) per flattened leaf; donation:
# donated argnums; policy: the caller's compiled-in policy label
# (precision policy + health build plan); sharding: device/mesh label
Signature = namedtuple("Signature", ("args", "donation", "policy",
                                     "sharding"))


def signature_of(args, donation=(), policy=None, sharding=None
                 ) -> Signature:
    """Abstract signature of a concrete argument pytree — exactly the
    identity the jit cache keys on, in hashable/diffable form."""
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    return Signature(
        args=tuple(
            (tuple(getattr(x, "shape", ())),
             str(getattr(x, "dtype", type(x).__name__)))
            for x in leaves),
        donation=tuple(donation or ()),
        policy=str(policy or ""),
        sharding=str(sharding or ""))


def classify(prev, new, bucketed=False):
    """(cause, changed-field list) for a recompile whose signature went
    ``prev`` -> ``new``. ``changed`` names every difference
    ("args[3].shape[0]: 8 -> 16"); ``cause`` is the highest-priority
    one. ``bucketed`` (serving ladders) turns a leading-dim-only shape
    change into ``new_bucket``."""
    if prev is None:
        return "first_compile", []
    changed = []
    shape_dims = []
    dtype_diff = False
    if new.policy != prev.policy:
        changed.append(f"policy: {prev.policy!r} -> {new.policy!r}")
    if new.donation != prev.donation:
        changed.append(
            f"donation: {list(prev.donation)} -> {list(new.donation)}")
    if new.sharding != prev.sharding:
        changed.append(
            f"sharding: {prev.sharding!r} -> {new.sharding!r}")
    arity_changed = len(new.args) != len(prev.args)
    if arity_changed:
        # a different leaf count means the step function's own pytree
        # signature changed — not any one argument's shape; falls
        # through to "unknown" unless a named cause also applies
        changed.append(f"n_args: {len(prev.args)} -> {len(new.args)}")
    else:
        for i, ((ps, pd), (ns, nd)) in enumerate(zip(prev.args,
                                                     new.args)):
            if pd != nd:
                dtype_diff = True
                changed.append(f"args[{i}].dtype: {pd} -> {nd}")
            if ps != ns:
                if len(ps) != len(ns):
                    shape_dims.append(-1)
                else:
                    shape_dims.extend(d for d in range(len(ps))
                                      if ps[d] != ns[d])
                changed.append(
                    f"args[{i}].shape: {list(ps)} -> {list(ns)}")
    if new.policy != prev.policy:
        cause = "policy_change"
    elif dtype_diff:
        cause = "dtype_change"
    elif new.donation != prev.donation:
        cause = "donation_change"
    elif shape_dims:
        dims = sorted(set(shape_dims))
        if bucketed and dims == [0]:
            cause = "new_bucket"
        elif dims[0] < 0:
            cause = "shape_change(rank)"
        else:
            cause = f"shape_change(dim={dims[0]})"
    elif new.sharding != prev.sharding:
        cause = "sharding_change"
    elif changed:
        cause = "unknown"
    else:
        cause = "rewarm"
    return cause, changed


# ---------------------------------------------------------------------------
# the ledger (swappable: set_ledger(CountingStub) in tests)
# ---------------------------------------------------------------------------

def _abstract_args(args):
    """ShapeDtypeStruct pytree for lazy re-lowering (non-array leaves —
    python ints like the step counter — ride through as themselves, so
    nothing pins donated device buffers)."""
    import jax

    def one(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return x
        return jax.ShapeDtypeStruct(shape, dtype)

    return jax.tree_util.tree_map(one, args)


class CompileLedger:
    """Bounded, site-keyed register of compiled executables. All entry
    points are host-side and lock-scoped; nothing here touches a
    device (the lazy audit compiles only when /debug/hlo asks)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._records: OrderedDict = OrderedDict()
        self._sites: dict = {}
        self._lazy: dict = {}
        self._lock = threading.Lock()

    def resize(self, capacity: int):
        with self._lock:
            self.capacity = int(capacity)
            self._trim()

    def _trim(self):
        while len(self._records) > self.capacity:
            key, _ = self._records.popitem(last=False)
            self._lazy.pop(key, None)

    def _site(self, site):
        st = self._sites.get(site)
        if st is None:
            st = self._sites[site] = {
                "last": None, "seen": {}, "fn_ref": None, "seq": 0}
        return st

    # -- recording -----------------------------------------------------------
    def _new_record(self, st, site, sig, cause, changed, kind, seconds,
                    fingerprint, flops, mode="compile", store=None):
        st["seq"] += 1
        # ':' not '#': these keys ride in /debug/hlo/<key> URLs, and a
        # '#' would be stripped client-side as a fragment
        key = f"{site}:{st['seq']}"
        rec = {
            "key": key, "site": site, "seq": st["seq"],
            "ts": round(time.time(), 6), "kind": kind, "cause": cause,
            "mode": mode, "store": store,
            "changed": list(changed),
            "compile_seconds": (round(seconds, 6)
                                if seconds is not None else None),
            "hlo_fingerprint": fingerprint,
            "flops": flops,
            "signature": {
                "n_args": len(sig.args),
                "args": [[list(s), d] for s, d in sig.args[:64]],
                "donation": list(sig.donation),
                "policy": sig.policy,
                "sharding": sig.sharding,
            },
            "audit": None,
        }
        st["seen"][sig] = key
        st["last"] = sig
        self._records[key] = rec
        self._trim()
        return rec

    def observe_step(self, site, jitted, args, sig, seconds=None,
                     window=None):
        """One train-step compile observed at ``site`` (the loops call
        this only after the monitoring hook flagged a backend compile
        on their thread). Returns the new record, or None when the
        compile was a stray (signature already ledgered for this
        function — e.g. a listener's inference executable compiling
        mid-fit)."""
        with self._lock:
            st = self._site(site)
            ref = st["fn_ref"]
            if ref is None or ref() is not jitted:
                # a rebuilt step function starts from empty jit caches:
                # every signature will compile again, and each should
                # be diffed against the site's last, not dropped. The
                # weakref (not a bare id()) makes a GC'd-then-recycled
                # address read as "changed" instead of silently
                # matching — the PR-8 _placed_args lesson
                st["seen"] = {}
                st["fn_ref"] = weakref.ref(jitted)
            if sig in st["seen"]:
                return None
            cause, changed = classify(st["last"], sig, bucketed=False)
            fingerprint = flops = None
            rec = self._new_record(st, site, sig, cause, changed,
                                   "step", seconds, fingerprint, flops)
        # outside the lock: lowering is host-side and cached by jax,
        # but still ~ms — never serialize other sites behind it
        try:
            lowered = jitted.lower(*args)
            rec["hlo_fingerprint"] = hlo_audit.fingerprint(
                lowered.as_text())
            analysis = lowered.cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else None
            if isinstance(analysis, dict):
                rec["flops"] = float(analysis.get("flops", 0.0))
        except Exception:
            pass
        try:
            self._lazy[rec["key"]] = (weakref.ref(jitted),
                                      _abstract_args(args))
        except Exception:
            pass
        self._emit(rec, window)
        return rec

    def observe_store(self, site, jitted, args, sig, cause, mode,
                      seconds=None, fingerprint=None):
        """One store-resolved train-step executable (StoredJit seam):
        a ``cache_hit`` fires no backend-compile event, so the loop's
        ``note_step`` stays silent and the forensic record is written
        here; a ``cache_reject`` records the recompile under its store
        cause (the StoredJit caller has already claimed the compile
        seconds off the thread buffer, so ``note_step`` cannot
        double-record it)."""
        with self._lock:
            st = self._site(site)
            ref = st["fn_ref"]
            if ref is None or ref() is not jitted:
                st["seen"] = {}
                st["fn_ref"] = weakref.ref(jitted)
            store = "hit" if cause == "cache_hit" else "reject"
            rec = self._new_record(st, site, sig, cause, [], "step",
                                   seconds, fingerprint, None,
                                   mode=mode, store=store)
        try:
            self._lazy[rec["key"]] = (weakref.ref(jitted),
                                      _abstract_args(args))
        except Exception:
            pass
        self._emit(rec)
        return rec

    def record_executable(self, site, compiled, sig, seconds=None,
                          bucketed=True, window=None, store=None,
                          mode="compile", fingerprint=None):
        """One AOT-compiled executable (serving warmup seam, hloaudit
        CLI): the Compiled object is in hand, so the audit and the
        optimized-HLO fingerprint are captured eagerly. ``store``/
        ``mode`` carry the executable-store outcome: a ``hit`` is
        recorded as ``cache_hit`` (the rewarm/new-bucket taxonomy
        names *re*compiles — a deserialize is neither), a ``reject``
        as ``cache_reject``. Store hits skip the eager HLO audit —
        parsing the module text would put compile-scale work back on
        the warm path the store exists to remove; /debug/hlo audits
        the retained executable on demand instead."""
        audit = None
        if store != "hit":
            try:
                audit = hlo_audit.audit_compiled(compiled)
            except Exception:
                audit = None
        with self._lock:
            st = self._site(site)
            if store == "hit":
                cause, changed = "cache_hit", []
            elif store == "reject":
                cause, changed = "cache_reject", []
            elif sig in st["seen"]:
                cause, changed = "rewarm", []
                st["last"] = sig
            else:
                cause, changed = classify(st["last"], sig,
                                          bucketed=bucketed)
            rec = self._new_record(
                st, site, sig, cause, changed, "aot", seconds,
                (audit or {}).get("hlo_fingerprint") or fingerprint,
                (audit or {}).get("flops"), mode=mode, store=store)
            rec["audit"] = audit
            if audit is None:
                try:
                    # lazy direct-audit handle (store hits): avals=None
                    # marks "audit the retained executable itself"
                    self._lazy[rec["key"]] = (weakref.ref(compiled),
                                              None)
                except Exception:
                    pass
        self._emit(rec, window)
        return rec

    def _emit(self, rec, window=None):
        """Metric + flight event + (sampled) trace span for one new
        ledger record."""
        if _registry.enabled():
            try:
                fam = _registry.get_registry().counter(
                    "dl4j_compile_cause_total", CAUSE_HELP,
                    ("site", "cause"))
                fam.local = True   # per-host compile history: scrape-only
                fam.labels(site=rec["site"], cause=rec["cause"]).inc()
            except Exception:
                pass  # stub registries must not break a fit loop
        try:
            from deeplearning4j_tpu.telemetry import flight

            flight.record("compile_ledger", key=rec["key"],
                          site=rec["site"], cause=rec["cause"],
                          seconds=rec["compile_seconds"],
                          fingerprint=rec["hlo_fingerprint"])
        except Exception:
            pass
        try:
            from deeplearning4j_tpu.telemetry import tracing

            ctx = tracing.current()
            if ctx is not None and window is not None:
                tracing.emit("compile.lower", ctx, window[0], window[1],
                             site=rec["site"], cause=rec["cause"],
                             key=rec["key"])
        except Exception:
            pass

    # -- reading -------------------------------------------------------------
    def get(self, key):
        with self._lock:
            return self._records.get(key)

    def describe(self, site=None) -> list:
        """Record dicts, newest first (the GET /debug/compiles
        payload). Eager audits are summarized down to their fingerprint
        here — the full audit lives at /debug/hlo/<key>."""
        with self._lock:
            recs = list(self._records.values())
        out = []
        for r in reversed(recs):
            if site is not None and r["site"] != site:
                continue
            r = dict(r)
            r["audited"] = r.pop("audit") is not None or \
                r["key"] in self._lazy
            out.append(r)
        return out

    def causes(self, site=None) -> dict:
        """{cause: count} over the ledger (tests, quick triage)."""
        out: dict = {}
        for r in self.describe(site=site):
            out[r["cause"]] = out.get(r["cause"], 0) + 1
        return out

    def audit(self, key):
        """The HLO audit for one ledgered executable: eager for AOT
        records, computed on demand for step records (lower + compile
        from the stored abstract signature — cached by jax's AOT cache
        after the first ask). None for an unknown key."""
        with self._lock:
            rec = self._records.get(key)
            lazy = self._lazy.get(key)
        if rec is None:
            return None
        if rec["audit"] is not None:
            return rec["audit"]
        if lazy is None:
            return {"error": "no executable retained for this record"}
        fn_ref, avals = lazy
        jitted = fn_ref()
        if jitted is None:
            return {"error": "step function was garbage-collected"}
        try:
            if avals is None:
                # store-hit AOT record: the retained executable is
                # audited directly (no relowering to do)
                audit = hlo_audit.audit_compiled(jitted)
            else:
                audit = hlo_audit.audit_compiled(
                    jitted.lower(*avals).compile())
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}
        with self._lock:
            if key in self._records:
                self._records[key]["audit"] = audit
        return audit

    def clear(self):
        with self._lock:
            self._records.clear()
            self._sites.clear()
            self._lazy.clear()

    def __len__(self):
        return len(self._records)


# ---------------------------------------------------------------------------
# module-level emission API (every entry checks enabled() FIRST: a
# disabled process makes zero ledger-object calls — the CountingStub
# contract the loops' tele-bundle guard already enforces upstream)
# ---------------------------------------------------------------------------

def note_step(site, jitted, args, policy=None, donation=(0, 1, 2),
              window=None):
    """The fit-loop seam (multilayer / graph / sharded): called per
    recorded step; steady state (no backend compile since the last
    step on this thread) is one thread-local read. On a pending
    compile, the signature is built, diffed, and ledgered."""
    if not enabled():
        return None
    seconds = consume_backend_compiles()
    if seconds is None:
        return None
    sig = signature_of(args, donation=donation, policy=policy)
    return get_ledger().observe_step(site, jitted, args, sig,
                                     seconds=seconds, window=window)


def note_store(site, jitted, args, sig, store, mode, seconds=None,
               fingerprint=None):
    """The StoredJit seam (compilestore): a store ``hit`` writes the
    ``cache_hit`` forensic record the silent monitoring hook cannot
    (deserializing fires no backend compile); a ``reject`` claims the
    recompile's thread-local seconds and records ``cache_reject`` —
    so the loop's later ``note_step`` finds an empty buffer and one
    event yields exactly one ledger record."""
    if not enabled():
        return None
    if store == "reject":
        consumed = consume_backend_compiles()
        if consumed is not None:
            seconds = consumed
        cause = "cache_reject"
    else:
        cause = "cache_hit"
    return get_ledger().observe_store(site, jitted, args, sig, cause,
                                      mode, seconds=seconds,
                                      fingerprint=fingerprint)


def record_executable(site, compiled, args_sig, seconds=None,
                      donation=(), policy=None, sharding=None,
                      bucketed=True, store=None, mode="compile",
                      fingerprint=None):
    """The AOT seam (Servable.compile_shape, tools/hloaudit.py):
    ``args_sig`` is the abstract input signature as ((shape, dtype),
    ...) leaves. Backend-compile events pending on this thread are
    consumed and preferred over the caller's wall-clock ``seconds``
    (the wall includes lowering; a cache-hit rebuild has no events and
    keeps the tiny wall, which is the honest number). ``store``/
    ``mode``/``fingerprint`` carry the executable-store outcome when
    the site resolved through compilestore."""
    if not enabled():
        return None
    consumed = consume_backend_compiles()
    if consumed is not None:
        seconds = consumed
    sig = Signature(
        args=tuple((tuple(s), str(d)) for s, d in args_sig),
        donation=tuple(donation or ()),
        policy=str(policy or ""),
        sharding=str(sharding or ""))
    return get_ledger().record_executable(site, compiled, sig,
                                          seconds=seconds,
                                          bucketed=bucketed,
                                          store=store, mode=mode,
                                          fingerprint=fingerprint)


# ---------------------------------------------------------------------------
# /healthz "compile" section: sites currently compiling + warmup-ladder
# progress (degraded-not-503 — a mid-warmup process informs operators,
# it does not leave rotation beyond what serving readiness already says)
# ---------------------------------------------------------------------------

_active: dict = {}
_active_lock = threading.Lock()


class _WarmupScope:
    """Progress handle for one warmup ladder: ``step()`` after each
    compiled shape; context exit clears the site from /healthz."""

    __slots__ = ("site",)

    def __init__(self, site):
        self.site = site

    def step(self):
        with _active_lock:
            st = _active.get(self.site)
            if st is not None:
                st["done"] += 1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        with _active_lock:
            _active.pop(self.site, None)
        return False


class _NullScope:
    __slots__ = ()

    def step(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SCOPE = _NullScope()


def warmup_scope(site, total):
    """Mark ``site`` as compiling its warmup ladder of ``total`` shapes
    for the /healthz compile section. No-op handle when telemetry is
    disabled."""
    if not enabled():
        return NULL_SCOPE
    with _active_lock:
        _active[site] = {"t0": time.time(), "done": 0,
                         "total": int(total)}
    return _WarmupScope(site)


def _healthz_section():
    """The /healthz provider payload: {} (section omitted) unless a
    site is mid-compile right now."""
    with _active_lock:
        snap = {site: dict(st) for site, st in _active.items()}
    if not snap:
        return {}
    now = time.time()
    return {
        "compiling": {site: round(now - st["t0"], 3)
                      for site, st in sorted(snap.items())},
        "warmup": {site: {"done": st["done"], "total": st["total"],
                          "fraction": round(st["done"]
                                            / max(1, st["total"]), 3)}
                   for site, st in sorted(snap.items())},
        "degraded": True,
    }


def _install_healthz_provider():
    from deeplearning4j_tpu.telemetry import health

    health.register_healthz_provider("compile", _healthz_section)


_install_healthz_provider()
