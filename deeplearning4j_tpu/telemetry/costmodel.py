"""XLA cost-model performance attribution (ISSUE 10 tentpole, second
half).

MFU used to exist only as hand-written FLOP formulas in bench.py — the
gap ROADMAP item 4 keeps tripping over: an operator watching /metrics
could see a step get slower but had no authoritative FLOP count to say
*how far from peak* the executable runs, and the analytic formulas can
silently disagree with what XLA actually compiled (the PR-10 audit
caught bench.py's ResNet formula counting multiply-accumulates as one
FLOP — a 2x MFU understatement against a peak quoted in real FLOP/s).

Sources of truth:

- **training steps**: ``jitted.lower(*args).cost_analysis()`` — the
  trace+lower is host-side only (no second XLA compile; jax caches the
  lowering by signature, so repeat calls cost ~1 ms) and its ``flops``
  is the HLO cost model's count for exactly the step that runs;
- **serving executables**: ``compiled.cost_analysis()`` +
  ``compiled.memory_analysis()`` captured at AOT warmup, where the
  Compiled object is already in hand (serving/servable.py).

Published metrics (canonical list in docs/OBSERVABILITY.md):

- ``dl4j_flops_per_step{executable}`` — HLO-cost-model FLOPs of one
  execution of the named executable (training loops use their loop
  label; serving buckets use ``model:v<version>:<shape>``);
- ``dl4j_executable_bytes{executable,kind}`` — compiled-executable
  memory footprint (``argument|output|temp|code``), AOT path only;
- ``dl4j_mfu{executable}`` — live model-FLOP utilization:
  ``flops / (step_seconds * peak_flops)``, refreshed every recorded
  step once the loop's FLOP count is known. Peak FLOP/s comes from
  :func:`peak_flops` (TPU detection, ``DL4J_PEAK_FLOPS`` override,
  :func:`set_peak_flops`); without a known peak the MFU gauge is
  simply not published (a made-up CPU peak would be noise, not
  observability).

Overhead guard: training-loop attribution is *throttled by step time*
(``min_step_seconds``, default 20 ms): a fleet of sub-millisecond unit
-test steps never pays the one-time ~100 ms lower+analyze, while every
flagship workload (ResNet, BERT, LSTM — all ≥ tens of ms/step) is
attributed on its second step. ``configure(min_step_seconds=0)`` forces
attribution everywhere (bench, tests). Failures anywhere in the
analysis degrade to "no metric", never into the training loop.
"""

from __future__ import annotations

import logging
import os
import threading

from deeplearning4j_tpu.telemetry import registry as _registry

log = logging.getLogger("deeplearning4j_tpu")

FLOPS_HELP = ("HLO-cost-model FLOPs for one execution of this "
              "executable (training step or serving bucket), from "
              "XLA cost_analysis() at lower/AOT-warmup time")
BYTES_HELP = ("Compiled-executable memory footprint from "
              "memory_analysis() (kind: argument|output|temp|code)")
MFU_HELP = ("Live model-FLOP utilization: cost-model FLOPs per step / "
            "(step seconds * peak FLOP/s); published once the loop's "
            "executable is attributed and a hardware peak is known")

# TPU v5e bf16 peak (bench.py's V5E_PEAK_BF16); other TPU generations
# fall back to the env override
_TPU_PEAKS = {"v5e": 197e12, "v5litepod": 197e12}

_state = {"min_step_seconds": 0.02, "peak": None, "peak_resolved": False}
_lock = threading.Lock()


def configure(min_step_seconds=None, peak_flops=None):
    """Tune the attribution throttle and/or the hardware peak."""
    if min_step_seconds is not None:
        _state["min_step_seconds"] = float(min_step_seconds)
    if peak_flops is not None:
        set_peak_flops(peak_flops)


def min_step_seconds() -> float:
    return _state["min_step_seconds"]


def set_peak_flops(peak):
    """Override the hardware peak FLOP/s (None forgets the override
    and re-detects on next use)."""
    with _lock:
        _state["peak"] = float(peak) if peak is not None else None
        _state["peak_resolved"] = peak is not None


def peak_flops():
    """Peak FLOP/s for MFU: explicit override > ``DL4J_PEAK_FLOPS`` >
    TPU device-kind detection > None (MFU unpublished)."""
    with _lock:
        if _state["peak_resolved"]:
            return _state["peak"]
    peak = None
    env = os.environ.get("DL4J_PEAK_FLOPS")
    if env:
        try:
            peak = float(env)
        except ValueError:
            log.warning("DL4J_PEAK_FLOPS=%r is not a number; ignored",
                        env)
    if peak is None:
        try:
            import jax

            dev = jax.devices()[0]
            if dev.platform == "tpu":
                kind = getattr(dev, "device_kind", "").lower()
                for tag, p in _TPU_PEAKS.items():
                    if tag in kind:
                        peak = p
                        break
        except Exception:
            peak = None
    with _lock:
        _state["peak"] = peak
        _state["peak_resolved"] = True
    return peak


# ---------------------------------------------------------------------------
# analysis plumbing
# ---------------------------------------------------------------------------

def _first(analysis):
    """cost_analysis() returns a dict (or a 1-list of dicts on older
    jax); normalize."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    return analysis if isinstance(analysis, dict) else None


def _publish_flops(executable, flops, registry=None):
    if not _registry.enabled():
        return
    reg = registry if registry is not None else _registry.get_registry()
    fam = reg.gauge("dl4j_flops_per_step", FLOPS_HELP, ("executable",))
    # scrape-only (like device-memory gauges): WHETHER a host attributed
    # an executable depends on its measured step time, so these families
    # must not join the identical-instrument-set cross-host aggregation
    fam.local = True
    fam.labels(executable=executable).set(flops)


def publish_mfu(executable, flops, seconds, registry=None):
    """Refresh ``dl4j_mfu{executable}`` from one step's wall time.
    No-op without a known hardware peak or a sane measurement."""
    if not _registry.enabled() or not flops or seconds <= 0:
        return None
    peak = peak_flops()
    if not peak:
        return None
    mfu = flops / (seconds * peak)
    reg = registry if registry is not None else _registry.get_registry()
    fam = reg.gauge("dl4j_mfu", MFU_HELP, ("executable",))
    fam.local = True   # see _publish_flops
    fam.labels(executable=executable).set(mfu)
    return mfu


def step_cost(executable, jitted, args, cache=None):
    """Attribute one jitted training step: lower it against ``args``
    (host-side trace only — never a second XLA compile), read the HLO
    cost model, publish ``dl4j_flops_per_step{executable}``, and return
    the per-step FLOPs (None on any failure — attribution must never
    break a fit loop).

    ``cache`` is a caller-owned dict (e.g. an attribute on the net)
    keyed here by the args' shape signature, so refits re-publish from
    the cache instead of re-lowering.

    K-step scanned launches (fitMultiBatch / BertTrainer.train_steps)
    need no normalization: the HLO cost model visits a While/scan body
    exactly ONCE (the trip count is not in the module), so the count
    it returns already IS per-step — measured within 3% of the
    analytic per-step FLOPs for a scanned BERT launch."""
    if not _registry.enabled():
        return None
    try:
        key = _shape_key(args)
    except Exception:
        key = None
    if cache is not None and key is not None and key in cache:
        flops = cache[key]
        if flops:
            _publish_flops(executable, flops)
        return flops
    flops = None
    try:
        analysis = _first(jitted.lower(*args).cost_analysis())
        if analysis is not None:
            flops = float(analysis.get("flops", 0.0))
    except Exception as e:
        log.debug("cost attribution for %r failed: %s", executable, e)
        flops = None
    if cache is not None and key is not None:
        cache[key] = flops
    if flops:
        _publish_flops(executable, flops)
    return flops


def maybe_attribute(tele, executable, jitted, args, owner, steps_seen,
                    dt_step):
    """The fit-loop attribution idiom, shared by the multilayer /
    graph / sharded loops: attribute the loop's step executable on the
    first QUALIFYING steady-state step — step >= 2 (step 1's wall is
    compile-inflated), the loop not yet attributed (``tele.step_flops``
    unset), and ``dt_step`` clearing the throttle; a step that dips
    under the threshold just defers to a later qualifying one. The
    shape-keyed cost cache lives on ``owner`` (the net/trainer), so
    refits re-publish without re-lowering."""
    if tele is None or tele.step_flops is not None or steps_seen < 2 \
            or dt_step < _state["min_step_seconds"]:
        return
    cache = getattr(owner, "_step_cost_cache", None)
    if cache is None:
        cache = owner._step_cost_cache = {}
    tele.note_flops(step_cost(executable, jitted, args, cache=cache))


def attribute_launch(executable, jitted, args, owner, per_step, warm):
    """The scanned-launch attribution idiom, shared by
    ``fitMultiBatch`` and ``BertTrainer.train_steps``: attribute when
    the per-step wall clears the throttle, but publish MFU only for
    ``warm`` launches — the caller knows which walls are honest (a
    first launch compiles inside the timed region; an unmaterialized
    dispatch wall is microseconds), and a dishonest wall must neither
    understate nor overstate the live gauge. Returns the FLOPs (or
    None)."""
    if per_step < _state["min_step_seconds"]:
        return None
    cache = getattr(owner, "_step_cost_cache", None)
    if cache is None:
        cache = owner._step_cost_cache = {}
    flops = step_cost(executable, jitted, args, cache=cache)
    if warm:
        publish_mfu(executable, flops, per_step)
    return flops


def _shape_key(args):
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    return tuple(
        (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", type(x))))
        for x in leaves)


def executable_cost(executable, compiled, registry=None):
    """Attribute one AOT-compiled executable (serving warmup):
    ``cost_analysis()`` -> ``dl4j_flops_per_step{executable}``,
    ``memory_analysis()`` -> ``dl4j_executable_bytes{executable,kind}``.
    Returns the FLOPs (None on failure)."""
    if not _registry.enabled():
        return None
    reg = registry if registry is not None else _registry.get_registry()
    flops = None
    try:
        analysis = _first(compiled.cost_analysis())
        if analysis is not None:
            flops = float(analysis.get("flops", 0.0))
            _publish_flops(executable, flops, registry=reg)
    except Exception as e:
        log.debug("cost_analysis for %r failed: %s", executable, e)
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            fam = reg.gauge("dl4j_executable_bytes", BYTES_HELP,
                            ("executable", "kind"))
            fam.local = True   # see _publish_flops
            for kind, attr in (("argument", "argument_size_in_bytes"),
                               ("output", "output_size_in_bytes"),
                               ("temp", "temp_size_in_bytes"),
                               ("code", "generated_code_size_in_bytes")):
                val = getattr(mem, attr, None)
                if val is not None:
                    fam.labels(executable=executable, kind=kind).set(val)
    except Exception as e:
        log.debug("memory_analysis for %r failed: %s", executable, e)
    return flops
