"""HLO fusion/remat audit: what XLA actually compiled (ISSUE 11
tentpole, part c).

ROADMAP item 4 ("close the MFU gap") names an XLA fusion/remat audit as
the next instrument: the cuDNN paper (PAPERS.md) defines which
primitives must fuse to hit roofline, and an unfused dot or a
rematerialized block is invisible in step-time metrics — the step is
just "slow". This module parses the *optimized* HLO of a compiled
executable (``compiled.as_text()``) into the handful of structural
facts an operator acts on:

- **fusion count** and how many dot/convolution ops were left
  *outside* any fused computation (an unfused dot at a hot site is the
  classic roofline miss);
- **collective ops** (all-reduce / all-gather / reduce-scatter /
  collective-permute / all-to-all) — the sharded-trainer overlap work
  (ROADMAP item 4) needs to know how many and where;
- **remat markers**: ``opt-barrier`` ops and ops whose names carry the
  ``.remat`` suffix jax.checkpoint leaves behind — rematerialization
  trades FLOPs for memory and should be a *decision*, not a surprise;
- **largest buffers** by result-type byte size — the first question
  when ``memory_analysis()`` temp bytes look wrong.

The parser is a line-oriented state machine over HLO text — no XLA
bindings, so it audits a dumped module in a test as happily as a live
Compiled object. Consumers: the compile ledger attaches an audit to
every AOT serving executable at warmup, ``GET /debug/hlo/<key>``
(ui/server.py) serves it per ledgered executable, and
``tools/hloaudit.py`` emits the per-model report committed to
docs/HLO_AUDIT.md.
"""

from __future__ import annotations

import hashlib
import re

COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

# "%name = <type> opcode(..." — the opcode is the first lowercase token
# immediately followed by "(" on the right-hand side (types like
# f32[64,64]{1,0} never touch a "(", tuple types open with "(" before
# any token)
_OPCODE_RE = re.compile(r"\b([a-z][a-zA-Z0-9\-_]*)\(")
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
# computation headers: "%fused_computation.1 (p: f32[..]) -> .. {" /
# "ENTRY %main.5 (...) -> .. {"
_COMP_RE = re.compile(r"^\s*(ENTRY\s+)?%?([\w\.\-]+)\s*\(.*\)\s*->.*\{")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}


def _result_bytes(rhs: str):
    """Byte size of an op line's result when it is a single array: the
    one shape token between '=' and the opcode. Tuple-typed results
    (while-loop carries, multi-output fusions) return 0 — they
    aggregate the whole carried state and would drown every real
    buffer in the largest-buffer ranking."""
    m = _OPCODE_RE.search(rhs)
    head = rhs[:m.start()] if m else rhs
    shapes = _SHAPE_RE.findall(head)
    if len(shapes) != 1:
        return 0, None
    dtype, dims = shapes[0]
    width = _DTYPE_BYTES.get(dtype)
    if width is None:
        return 0, None
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * width, f"{dtype}[{dims}]"


def audit_text(hlo: str) -> dict:
    """Parse one HLO module's text into the audit summary dict. Pure
    and total: malformed lines are skipped, never raised on."""
    fusions = 0
    unfused = {"dot": 0, "convolution": 0}
    fused = {"dot": 0, "convolution": 0}
    collectives = {op: 0 for op in COLLECTIVE_OPS}
    opt_barriers = 0
    remat_ops = 0
    custom_calls = 0
    ops = 0
    computations = 0
    fused_computations = 0
    opcode_hist: dict = {}
    buffers: list = []
    in_fused = False
    for raw in hlo.splitlines():
        line = raw.strip()
        if line == "}":
            in_fused = False
            continue
        comp = _COMP_RE.match(raw)
        if comp is not None:
            computations += 1
            in_fused = "fused" in comp.group(2)
            fused_computations += int(in_fused)
            continue
        if line.startswith("ROOT "):
            # computation roots are instructions too — a fusion's root
            # IS the fused op, and a small module's only dot is often
            # the entry root
            line = line[len("ROOT "):]
        if "=" not in line or not line.startswith("%"):
            continue
        name, _, rhs = line.partition("=")
        m = _OPCODE_RE.search(rhs)
        if m is None:
            continue
        opcode = m.group(1)
        ops += 1
        opcode_hist[opcode] = opcode_hist.get(opcode, 0) + 1
        if opcode == "fusion":
            fusions += 1
        if opcode in unfused:
            (fused if in_fused else unfused)[opcode] += 1
        if opcode in collectives:
            collectives[opcode] += 1
        if opcode == "opt-barrier":
            opt_barriers += 1
        if opcode == "custom-call":
            custom_calls += 1
        if ".remat" in name:
            remat_ops += 1
        nbytes, label = _result_bytes(rhs)
        if nbytes:
            buffers.append((nbytes, label, name.strip().rstrip(" ")))
    buffers.sort(key=lambda b: -b[0])
    top_ops = dict(sorted(opcode_hist.items(),
                          key=lambda kv: (-kv[1], kv[0]))[:12])
    return {
        "ops": ops,
        "computations": computations,
        "fused_computations": fused_computations,
        "fusions": fusions,
        "unfused_dots": unfused["dot"],
        "unfused_convolutions": unfused["convolution"],
        "fused_dots": fused["dot"],
        "fused_convolutions": fused["convolution"],
        "collectives": {**collectives,
                        "total": sum(collectives.values())},
        "remat": {"opt_barriers": opt_barriers, "remat_ops": remat_ops},
        "custom_calls": custom_calls,
        "opcode_histogram": top_ops,
        "largest_buffers": [
            {"bytes": b, "type": t, "op": n}
            for b, t, n in buffers[:5]],
    }


def fingerprint(text: str) -> str:
    """Stable short identity for one HLO/StableHLO module text."""
    return hashlib.sha1(text.encode()).hexdigest()[:12]


def audit_compiled(compiled) -> dict:
    """Audit a live jax Compiled object: ``as_text()`` through
    :func:`audit_text`, plus the cost/memory analyses the executable
    already carries. Degrades field-by-field — a backend without
    ``memory_analysis`` still gets the structural audit."""
    text = compiled.as_text()
    out = audit_text(text)
    out["hlo_fingerprint"] = fingerprint(text)
    out["module_bytes"] = len(text)
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if isinstance(analysis, dict):
            out["flops"] = float(analysis.get("flops", 0.0))
            out["bytes_accessed"] = float(
                analysis.get("bytes accessed", 0.0))
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out["memory"] = {
                kind: getattr(mem, attr)
                for kind, attr in (
                    ("argument_bytes", "argument_size_in_bytes"),
                    ("output_bytes", "output_size_in_bytes"),
                    ("temp_bytes", "temp_size_in_bytes"),
                    ("code_bytes", "generated_code_size_in_bytes"))
                if getattr(mem, attr, None) is not None}
    except Exception:
        pass
    return out
