"""Sampled end-to-end tracing: request/step span trees (ISSUE 10
tentpole).

Metrics (PR 1) say *how slow*; the flight recorder (PR 3) says *what
happened recently* — neither can attribute one slow p99 request or one
low-MFU step to the specific queue, thread, or executable that ate the
time. This module adds the missing request/step dimension:

- **trace/span ids** in W3C ``traceparent`` form (``00-<trace>-<span>-
  <flags>``): incoming HTTP requests join an upstream trace, responses
  carry the header back, so the serving tier composes with external
  tracing infrastructure;
- **head-based sampling**: the keep/drop decision is made ONCE when a
  trace starts (deterministic 1-in-N counter, honoring an upstream
  sampled flag); an unsampled request/step performs no tracer work at
  all — its context is simply ``None`` and every downstream hop guards
  on that;
- **explicit cross-thread propagation**: a ``SpanContext`` is plain
  data. It rides the serving ``_Request``/``_BatchTask``/decode request
  objects across the batcher/replica/decode threads, the
  ``DevicePrefetcher`` producer, the ``EtlWorkerPool`` *work order*
  (across the fork boundary — workers ship span records back with their
  batches and the parent materializes them), and the async-checkpoint
  ``Snapshot``. Within one thread the current context lives in a
  ``contextvars.ContextVar`` (:func:`current` / :func:`use`);
- **bounded ring**: finished spans append to a deque (no I/O, no device
  work); ``GET /debug/traces`` on the UI server exports JSONL, newest
  trace first, filterable by trace id;
- **exemplars**: hot-path histograms (``dl4j_step_seconds``, serving
  queue-wait/execute) attach the sampled trace id to the bucket the
  observation landed in, so a p99 bucket in Prometheus links to a
  concrete span tree (OpenMetrics exemplar exposition).

Disabled contract (the PR-1 rule, extended): ``telemetry.disable()``
— or ``tracing.configure(enabled=False)`` — makes every entry point
return ``None``/``NULL`` before touching the tracer object, so a
CountingStub tracer observes ZERO calls per step and per request, and
the jitted math is untouched either way (spans only ever wrap host
code).

Quick use::

    from deeplearning4j_tpu.telemetry import tracing

    tracing.configure(sample_rate=1.0)        # default 0.01 (1 in 100)
    span = tracing.start_trace("http.predict")
    with span:                                # sets the current context
        ...                                   # downstream hops nest
    print(tracing.get_tracer().dump_jsonl())  # or GET /debug/traces
"""

from __future__ import annotations

import itertools
import json
import random
import re
import threading
import time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar

from deeplearning4j_tpu.telemetry import registry as _registry

DEFAULT_CAPACITY = 4096
DEFAULT_SAMPLE_RATE = 0.01

_state = {"enabled": True, "tracer": None, "interval": None}
_lock = threading.Lock()
_head_counter = itertools.count()
_current: ContextVar = ContextVar("dl4j_trace_ctx", default=None)

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def enabled() -> bool:
    """Tracing is live: the telemetry master switch AND the tracing
    flag (``telemetry.disable()`` compiles tracing out with the rest)."""
    return _state["enabled"] and _registry.enabled()


def configure(sample_rate=None, capacity=None, enabled=None):
    """Set the head-sampling rate (0 disables sampling, 1 keeps every
    trace), ring capacity, and/or the tracing flag."""
    if enabled is not None:
        _state["enabled"] = bool(enabled)
    if sample_rate is not None:
        rate = float(sample_rate)
        if rate <= 0.0:
            _state["interval"] = 0
        else:
            _state["interval"] = max(1, round(1.0 / min(rate, 1.0)))
    if capacity is not None:
        get_tracer().resize(int(capacity))


def sample_interval() -> int:
    """Current 1-in-N head-sampling interval (0 = never sample)."""
    iv = _state["interval"]
    if iv is None:
        iv = max(1, round(1.0 / DEFAULT_SAMPLE_RATE))
        _state["interval"] = iv
    return iv


def _head_sampled() -> bool:
    iv = sample_interval()
    if iv == 0:
        return False
    return next(_head_counter) % iv == 0


# ---------------------------------------------------------------------------
# contexts and spans
# ---------------------------------------------------------------------------

class SpanContext:
    """Immutable (trace_id, span_id) pair — the unit of propagation.
    Plain data on purpose: it pickles into ETL work orders and rides
    request objects across threads."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id})"

    def __reduce__(self):
        return (SpanContext, (self.trace_id, self.span_id))


def _as_ctx(parent):
    """Normalize a parent handle: SpanContext, Span, or an
    (trace_id, span_id) tuple (the picklable work-order form)."""
    if parent is None:
        return None
    if isinstance(parent, SpanContext):
        return parent
    if isinstance(parent, Span):
        return parent.ctx()
    if isinstance(parent, (tuple, list)) and len(parent) == 2:
        return SpanContext(parent[0], parent[1])
    return None


class Span:
    """A live span; context manager that makes it the current context.
    ``__exit__`` records it into the tracer ring (status ``error`` when
    the body raised)."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "start", "attrs", "status", "_token")

    def __init__(self, tracer, name, trace_id, span_id, parent_id,
                 attrs=None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = time.perf_counter()
        self.attrs = dict(attrs or {})
        self.status = "ok"
        self._token = None

    def ctx(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def set_attr(self, **attrs):
        self.attrs.update(attrs)
        return self

    def __bool__(self):
        return True

    def __enter__(self):
        self._token = _current.set(self.ctx())
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc_type is not None:
            self.status = "error"
            self.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        self.tracer.finish(self)
        return False


class _NullSpan:
    """The not-sampled/disabled stand-in: falsy, no-op context manager,
    no tracer calls ever."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def ctx(self):
        return None

    def traceparent(self):
        return None

    def set_attr(self, **attrs):
        return self

    def __bool__(self):
        return False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL = _NullSpan()


# ---------------------------------------------------------------------------
# the tracer (swappable: set_tracer(CountingStub) in tests)
# ---------------------------------------------------------------------------

class Tracer:
    """Bounded ring of finished span records. ``finish``/``emit`` are
    the only hot-path entry points: one dict build + one deque append,
    no I/O, no device work."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._spans: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        # os-seeded; ids only need uniqueness, not reproducibility
        self._rand = random.Random()

    def resize(self, capacity: int):
        with self._lock:
            self.capacity = int(capacity)
            self._spans = deque(self._spans, maxlen=self.capacity)

    def new_trace_id(self) -> str:
        return f"{self._rand.getrandbits(128):032x}"

    def new_span_id(self) -> str:
        return f"{self._rand.getrandbits(64):016x}"

    def start_span(self, name, trace_id=None, parent_id=None,
                   attrs=None) -> Span:
        if trace_id is None:
            trace_id = self.new_trace_id()
        return Span(self, name, trace_id, self.new_span_id(), parent_id,
                    attrs)

    def finish(self, span: Span, end=None):
        self._append({
            "trace_id": span.trace_id, "span_id": span.span_id,
            "parent_id": span.parent_id, "name": span.name,
            "start": round(span.start, 6),
            "end": round(end if end is not None else time.perf_counter(),
                         6),
            "ts": round(time.time(), 6),
            "status": span.status,
            # copied: a ring record must not alias a dict the caller
            # might still mutate (set_attr after exit) while an export
            # thread iterates it
            "attrs": dict(span.attrs)})

    def emit(self, name, trace_id, parent_id, start, end, attrs=None,
             status="ok") -> str:
        """Record an already-finished span with explicit timestamps
        (retroactive phases: queue waits measured at dispatch time).
        Returns the new span id so children can parent to it."""
        span_id = self.new_span_id()
        self._append({
            "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent_id, "name": name,
            "start": round(float(start), 6), "end": round(float(end), 6),
            "ts": round(time.time(), 6), "status": status,
            "attrs": dict(attrs or {})})
        return span_id

    def _append(self, record):
        with self._lock:
            self._spans.append(record)

    def spans(self, trace_id=None) -> list:
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        return out

    def trace_ids(self) -> list:
        """Distinct trace ids in the ring, oldest first."""
        seen: dict = {}
        for s in self.spans():
            seen.setdefault(s["trace_id"], None)
        return list(seen)

    def clear(self):
        with self._lock:
            self._spans.clear()

    def __len__(self):
        return len(self._spans)

    def dump_jsonl(self, trace_id=None) -> str:
        return "\n".join(json.dumps(s) for s in
                         self.spans(trace_id)) + "\n"


def get_tracer() -> Tracer:
    """The process-wide tracer (created lazily)."""
    tr = _state["tracer"]
    if tr is None:
        with _lock:
            tr = _state["tracer"]
            if tr is None:
                tr = Tracer()
                _state["tracer"] = tr
    return tr


def set_tracer(tracer):
    """Swap the process tracer (tests: counting stubs). Returns the
    previous tracer."""
    prev = _state["tracer"]
    _state["tracer"] = tracer
    return prev


# ---------------------------------------------------------------------------
# module-level emission API (every entry checks enabled() FIRST, so a
# disabled process makes zero tracer-object calls — the CountingStub
# contract)
# ---------------------------------------------------------------------------

def current() -> SpanContext | None:
    """The calling thread's current span context (None when tracing is
    disabled or the caller is not inside a sampled trace)."""
    if not enabled():
        return None
    return _current.get()


def current_ids():
    """(trace_id, span_id) of the current context, or None — the
    picklable form that rides ETL work orders across the fork."""
    ctx = current()
    if ctx is None:
        return None
    return (ctx.trace_id, ctx.span_id)


@contextmanager
def use(ctx):
    """Make ``ctx`` (SpanContext, (trace_id, span_id) tuple, or None)
    the current context for the block — the explicit cross-thread
    handoff (prefetcher producer, replica workers)."""
    ctx = _as_ctx(ctx)
    if ctx is None:
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def parse_traceparent(header):
    """W3C traceparent -> (trace_id, parent_span_id, sampled) or None
    on anything malformed (never raises: headers are attacker input)."""
    if not header or not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(flags, 16) & 1)


def start_trace(name, traceparent=None, **attrs):
    """Head-sampled trace root. With an upstream ``traceparent`` the
    upstream decision wins (sampled flag set -> trace, cleared -> drop);
    otherwise the local 1-in-N sampler decides. Returns a started
    :class:`Span` (use as a context manager) or None when not sampled.
    """
    if not enabled():
        return None
    trace_id = parent_id = None
    if traceparent is not None:
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            trace_id, parent_id, sampled = parsed
            if not sampled:
                return None
        elif not _head_sampled():
            return None
    elif not _head_sampled():
        return None
    return get_tracer().start_span(name, trace_id=trace_id,
                                   parent_id=parent_id, attrs=attrs)


def trace_or_span(name, **attrs):
    """A child span of the current context when one exists (nested
    fits under an ElasticTrainer root, for example), else a
    head-sampled new trace. Returns :data:`NULL` (falsy no-op) when
    disabled or not sampled, so ``with``/truthiness both work."""
    if not enabled():
        return NULL
    ctx = _current.get()
    if ctx is not None:
        return get_tracer().start_span(name, trace_id=ctx.trace_id,
                                       parent_id=ctx.span_id, attrs=attrs)
    return start_trace(name, **attrs) or NULL


def span(name, parent=None, **attrs):
    """Child span context manager under ``parent`` (default: the
    current context). :data:`NULL` when there is no parent — the hot
    path's guard is one falsy check, zero tracer calls."""
    if not enabled():
        return NULL
    ctx = _as_ctx(parent) if parent is not None else _current.get()
    if ctx is None:
        return NULL
    return get_tracer().start_span(name, trace_id=ctx.trace_id,
                                   parent_id=ctx.span_id, attrs=attrs)


def emit(name, parent, start, end, status="ok", **attrs):
    """Record a finished span with explicit perf_counter timestamps
    under ``parent`` (SpanContext / (tid, sid) tuple). Returns the span
    id or None. The retroactive-phase workhorse: queue waits and
    execute windows are measured first, spanned after."""
    if not enabled():
        return None
    ctx = _as_ctx(parent)
    if ctx is None:
        return None
    return get_tracer().emit(name, ctx.trace_id, ctx.span_id, start,
                             end, attrs=attrs, status=status)


def export_jsonl(trace_id=None) -> str:
    """The span ring as JSONL (the GET /debug/traces payload) —
    read-only, works whether or not tracing is currently enabled (an
    incident dump must outlive a mid-incident disable())."""
    return get_tracer().dump_jsonl(trace_id=trace_id)


def ingest(record):
    """Materialize a span record produced in another PROCESS (ETL
    workers ship these back beside their batches). The record supplies
    trace_id/parent_id/name/start/end/attrs; a fresh span id is
    assigned here."""
    if not enabled() or not isinstance(record, dict):
        return None
    try:
        return get_tracer().emit(
            record["name"], record["trace_id"], record.get("parent_id"),
            record["start"], record["end"],
            attrs=record.get("attrs"), status=record.get("status", "ok"))
    except (KeyError, TypeError):
        return None
