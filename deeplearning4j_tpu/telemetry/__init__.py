"""Unified telemetry: process-wide metrics registry, phase tracing,
multi-host aggregation, Prometheus exposition (ISSUE 1 tentpole;
SURVEY.md §5 observability — the TPU-native OpProfiler /
PerformanceTracker / StatsListener replacement).

Quick use::

    from deeplearning4j_tpu import telemetry
    telemetry.enable()                       # on by default
    net.fit(data, 3)                         # hot loops self-instrument
    print(telemetry.prometheus.render())     # or GET /metrics on UIServer
    agg = telemetry.aggregate_snapshot()     # cross-host min/max/mean/sum

Disabling (`telemetry.disable()`) removes every per-step registry call
from the training loops — they check the flag once per fit()."""

from deeplearning4j_tpu.telemetry import aggregate, prometheus
from deeplearning4j_tpu.telemetry.aggregate import aggregate_snapshot
from deeplearning4j_tpu.telemetry.listener import MetricsListener
from deeplearning4j_tpu.telemetry.registry import (
    BYTES_BUCKETS, Counter, ETL_HELP, Gauge, Histogram, LoopInstruments,
    MetricsRegistry, SECONDS_BUCKETS, STEP_HELP, ServingInstruments, Timer,
    collect_device_memory, disable, enable, enabled, get_registry,
    log_buckets, loop_instruments, serving_instruments, set_registry, span)

__all__ = [
    "BYTES_BUCKETS", "Counter", "ETL_HELP", "Gauge", "Histogram",
    "LoopInstruments", "MetricsListener", "MetricsRegistry",
    "SECONDS_BUCKETS", "STEP_HELP", "ServingInstruments", "Timer",
    "aggregate", "aggregate_snapshot", "collect_device_memory", "disable",
    "enable", "enabled", "get_registry", "log_buckets", "loop_instruments",
    "prometheus", "serving_instruments", "set_registry", "span",
]
