"""Unified telemetry: process-wide metrics registry, phase tracing,
multi-host aggregation, Prometheus exposition (ISSUE 1 tentpole;
SURVEY.md §5 observability — the TPU-native OpProfiler /
PerformanceTracker / StatsListener replacement), plus training-health
diagnostics and the flight recorder (ISSUE 3): per-layer stats computed
inside the jitted step, divergence policies (WARN / HALT raising
DivergenceError / SKIP_BATCH), a bounded event ring dumped on
divergence or via GET /debug/flightrecorder, and GET /healthz.

Quick use::

    from deeplearning4j_tpu import telemetry
    telemetry.enable()                       # on by default
    telemetry.health.configure(policy="halt", ratio_max=10.0)
    net.fit(data, 3)                         # hot loops self-instrument
    print(telemetry.prometheus.render())     # or GET /metrics on UIServer
    agg = telemetry.aggregate_snapshot()     # cross-host min/max/mean/sum
    telemetry.flight.dump("/tmp/flight.jsonl")

ISSUE 10 adds sampled end-to-end tracing (`telemetry.tracing`:
request/step span trees with W3C traceparent propagation, exported at
GET /debug/traces, exemplars on latency histograms) and XLA cost-model
attribution (`telemetry.costmodel`: dl4j_flops_per_step /
dl4j_executable_bytes / a live dl4j_mfu gauge from cost_analysis() at
step-lower / AOT-warmup time).

ISSUE 11 adds compile-side observability: `telemetry.compile_ledger`
(an executable ledger keyed by step/serving site with recompile
forensics — structured causes diffed from argument signatures, compile
seconds off the jax.monitoring hook, HLO fingerprints, exported at
GET /debug/compiles) and `telemetry.hlo_audit` (fusion / unfused-dot /
collective / remat / largest-buffer audit of each ledgered
executable's optimized HLO, at GET /debug/hlo/<key> and
tools/hloaudit.py).

ISSUE 14 adds device-memory observability: `telemetry.memledger` —
the HBM ownership ledger (every memory-pinning subsystem registers a
categorized claim, reconciled against device.memory_stats() into
dl4j_device_memory_claimed_bytes plus an unattributed residual at
GET /debug/memory and /healthz), OOM forensics (typed DeviceOomError +
flight `oom` events naming site / requested bytes / top claims at the
train, serving, decode, prefetch, and snapshot seams), and
admission-time capacity planning (structured CapacityError before any
compile or pool allocation).

ISSUE 16 adds the time dimension and the fleet view:
`telemetry.timeseries` (a bounded ring of periodic windowed snapshots —
counters become rates, histograms become windowed p50/p99 — at
GET /debug/timeseries) and `telemetry.slo` (declared latency /
error-rate objectives evaluated by SRE-style multi-window burn rate
over the ring: dl4j_slo_* metrics, slo_breach/slo_recovered flight
events, a degraded-not-503 /healthz `slo` section, and a
histogram-direct burn judge the rollout controller uses on canaries).

ISSUE 18 adds stack-level attribution: `telemetry.profiler` — an
always-on ~19Hz wall-clock sampler over sys._current_frames() folding
every thread's stack into a bounded ring of collapsed stacks
(flamegraph-ready at GET /debug/profile/cpu, subsystem-attributed via
the dl4j:<subsystem>:<role> thread-name convention + module-path
heuristics, scrape-only dl4j_profile_self_seconds_total), single-flight
deep captures (POST /debug/profile/capture: high-rate sample +
jax.profiler.trace artifacts, content-addressed, atomic_save-committed)
and fleet-merged flamegraphs at GET /debug/fleet/profile.

Disabling (`telemetry.disable()`) removes every per-step registry call
from the training loops — they check the flag once per fit() — and
compiles the health stats OUT of the jitted step (pre-health output
structure, bit-identical math); the same switch means zero tracer
calls per step and per request, and zero compile-ledger calls per
step."""

from deeplearning4j_tpu.telemetry import (
    aggregate, compile_ledger, costmodel, flight, health, hlo_audit,
    memledger, profiler, prometheus, slo, timeseries, tracing)
from deeplearning4j_tpu.telemetry.memledger import (
    CapacityError, DeviceOomError)
from deeplearning4j_tpu.telemetry.aggregate import aggregate_snapshot
from deeplearning4j_tpu.telemetry.flight import FlightRecorder
from deeplearning4j_tpu.telemetry.health import (
    DivergenceError, HealthConfig, HealthMonitor)
from deeplearning4j_tpu.telemetry.listener import MetricsListener
from deeplearning4j_tpu.telemetry.registry import (
    BYTES_BUCKETS, Counter, ETL_HELP, EtlInstruments, FleetInstruments,
    Gauge, Histogram, LoopInstruments, MetricsRegistry, SECONDS_BUCKETS,
    STEP_HELP, ServingInstruments, Timer, collect_device_memory, disable,
    enable, enabled, etl_instruments, fleet_instruments, get_registry,
    log_buckets, loop_instruments, serving_instruments, set_registry,
    span)

__all__ = [
    "BYTES_BUCKETS", "CapacityError", "Counter", "DeviceOomError",
    "DivergenceError", "ETL_HELP",
    "EtlInstruments", "FleetInstruments", "FlightRecorder", "Gauge",
    "HealthConfig",
    "HealthMonitor", "Histogram", "LoopInstruments", "MetricsListener",
    "MetricsRegistry", "SECONDS_BUCKETS", "STEP_HELP",
    "ServingInstruments", "Timer", "aggregate", "aggregate_snapshot",
    "collect_device_memory", "compile_ledger", "costmodel", "disable",
    "enable", "enabled", "etl_instruments", "fleet_instruments",
    "flight", "get_registry",
    "health", "hlo_audit", "log_buckets", "loop_instruments",
    "memledger", "profiler", "prometheus", "serving_instruments",
    "set_registry", "slo", "span", "timeseries", "tracing",
]
