"""Multi-host metric aggregation: one gather, min/max/mean/sum per
sample.

Reference capability: the reference's StatsStorage aggregated per-worker
stats through the parameter-server transport (SURVEY.md §2.6/§2.7); on
a TPU pod the equivalent is a single
`jax.experimental.multihost_utils.process_allgather` of the flat
snapshot vector — every process computes the identical aggregate with
no extra round trips, and process 0 can serve/persist it.

Contract: every process must hold the SAME instrument set (same metric
names, labels, bucket layouts) — true for the built-in instruments,
which are declared identically by the SPMD program on every host. A
key-set mismatch is detected (via a key-fingerprint lane in the same
gather) and raised, not silently mis-joined."""

from __future__ import annotations

import zlib

import numpy as np

from deeplearning4j_tpu.telemetry.registry import get_registry


def aggregate_snapshot(snapshot=None, registry=None) -> dict:
    """{sample_name: {"min","max","mean","sum","hosts"}} across all
    processes. Single-process (or no distributed runtime): a local-only
    aggregate with hosts=1 — the same shape, so callers never branch."""
    if snapshot is None:
        snapshot = (registry or get_registry()).snapshot()
    keys = sorted(snapshot)
    fingerprint = zlib.crc32("\n".join(keys).encode())
    vals = np.asarray([float(snapshot[k]) for k in keys], np.float64)
    lanes = np.concatenate([[np.float64(fingerprint)], vals])

    n_hosts = 1
    try:
        import jax

        n_hosts = jax.process_count()
    except Exception:
        pass
    if n_hosts > 1:
        from jax.experimental import multihost_utils

        gathered = np.asarray(
            multihost_utils.process_allgather(lanes))  # [P, 1 + N]
        if not np.all(gathered[:, 0] == float(fingerprint)):
            raise ValueError(
                "metric snapshots differ across hosts (key-set "
                "fingerprints disagree) — every process must register "
                "the same instruments before aggregating")
        table = gathered[:, 1:]
    else:
        table = vals[None, :]

    out = {}
    for i, k in enumerate(keys):
        col = table[:, i]
        out[k] = {"min": float(col.min()), "max": float(col.max()),
                  "mean": float(col.mean()), "sum": float(col.sum()),
                  "hosts": int(table.shape[0])}
    return out
