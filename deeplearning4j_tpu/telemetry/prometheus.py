"""Prometheus text-exposition (format 0.0.4) for a MetricsRegistry.

Reference capability: the scrape surface DL4J never had — the vertx UI
(SURVEY.md §2.7) served humans; a production TPU serving stack is
scraped by Prometheus. `render()` is served from the existing UI
server's `/metrics` route (ui/server.py) and refreshes on-demand system
gauges (device memory) before rendering, so nothing polls devices in
the background."""

from __future__ import annotations

from deeplearning4j_tpu.telemetry.registry import (
    collect_device_memory, enabled, fmt_float, get_registry)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s):
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s):
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labels_text(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


def render(registry=None, collect_system=True) -> str:
    """The whole registry in Prometheus text exposition. With
    collect_system, on-demand gauges (device memory) refresh first."""
    reg = registry or get_registry()
    if collect_system and enabled():
        collect_device_memory(reg)
    lines = []
    for fam in reg.collect():
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for labels, child in fam.children():
            if fam.kind == "histogram":
                acc = 0
                for bound, c in zip(child.buckets, child.counts):
                    acc += c
                    lt = _labels_text(labels + (("le", fmt_float(bound)),))
                    lines.append(f"{fam.name}_bucket{lt} {acc}")
                lt = _labels_text(labels + (("le", "+Inf"),))
                lines.append(f"{fam.name}_bucket{lt} {child.count}")
                lines.append(f"{fam.name}_sum{_labels_text(labels)} "
                             f"{fmt_float(child.sum)}")
                lines.append(f"{fam.name}_count{_labels_text(labels)} "
                             f"{child.count}")
            else:
                lines.append(f"{fam.name}{_labels_text(labels)} "
                             f"{fmt_float(child.value)}")
    return "\n".join(lines) + "\n"


def parse(text) -> dict:
    """Parse a text exposition back to {sample_name: float} (tests /
    round-trip verification; sample_name includes the label set)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        out[name] = float(value)
    return out
