"""Prometheus text-exposition (format 0.0.4) for a MetricsRegistry.

Reference capability: the scrape surface DL4J never had — the vertx UI
(SURVEY.md §2.7) served humans; a production TPU serving stack is
scraped by Prometheus. `render()` is served from the existing UI
server's `/metrics` route (ui/server.py) and refreshes on-demand system
gauges (device memory) before rendering, so nothing polls devices in
the background."""

from __future__ import annotations

from deeplearning4j_tpu.telemetry.registry import (
    collect_device_memory, enabled, fmt_float, get_registry)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s):
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s):
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labels_text(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


def _exemplar_text(child, idx):
    """OpenMetrics exemplar suffix for one bucket, or '' (ISSUE 10:
    latency buckets carry the trace id of the last sampled observation
    that landed in them, so a p99 bucket links to a span tree at
    GET /debug/traces)."""
    ex = getattr(child, "exemplars", None)
    if not ex or idx not in ex:
        return ""
    trace_id, value, ts = ex[idx]
    return (f' # {{trace_id="{_escape_label(str(trace_id))}"}} '
            f"{fmt_float(value)} {ts:.3f}")


def render(registry=None, collect_system=True, exemplars=False,
           name_prefix=None) -> str:
    """The whole registry in Prometheus text exposition. With
    collect_system, on-demand gauges (device memory) refresh first.
    ``exemplars=True`` appends OpenMetrics-style exemplar suffixes to
    histogram bucket lines (``/metrics?exemplars=1`` — an explicit
    debug opt-in: this exposition is 0.0.4, not full OpenMetrics, so
    the suffix is never served to an unsuspecting scraper; parse()
    tolerates both forms). ``name_prefix`` keeps only families whose
    name starts with it (``/metrics?name=<prefix>`` — a selective
    scraper like the fleet router's poll thread skips rendering and
    parsing the families it never reads)."""
    reg = registry or get_registry()
    if collect_system and enabled():
        collect_device_memory(reg)
    lines = []
    for fam in reg.collect():
        if name_prefix and not fam.name.startswith(name_prefix):
            continue
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for labels, child in fam.children():
            if fam.kind == "histogram":
                acc = 0
                for i, (bound, c) in enumerate(zip(child.buckets,
                                                   child.counts)):
                    acc += c
                    lt = _labels_text(labels + (("le", fmt_float(bound)),))
                    ex = _exemplar_text(child, i) if exemplars else ""
                    lines.append(f"{fam.name}_bucket{lt} {acc}{ex}")
                lt = _labels_text(labels + (("le", "+Inf"),))
                ex = (_exemplar_text(child, len(child.buckets))
                      if exemplars else "")
                lines.append(f"{fam.name}_bucket{lt} {child.count}{ex}")
                lines.append(f"{fam.name}_sum{_labels_text(labels)} "
                             f"{fmt_float(child.sum)}")
                lines.append(f"{fam.name}_count{_labels_text(labels)} "
                             f"{child.count}")
            else:
                lines.append(f"{fam.name}{_labels_text(labels)} "
                             f"{fmt_float(child.value)}")
    return "\n".join(lines) + "\n"


_UNESCAPE = {"\\": "\\", '"': '"', "n": "\n"}


def _scan_labels(line, start):
    """Parse ``{k="v",...}`` starting at ``line[start] == '{'``.
    Returns (labels_tuple_with_unescaped_values, index_after_brace).
    Handles label values containing spaces, commas, braces, and the
    exposition escapes (\\\\, \\", \\n)."""
    labels = []
    i = start + 1
    n = len(line)
    while i < n and line[i] != "}":
        eq = line.index("=", i)
        key = line[i:eq].strip()
        i = eq + 1
        if i >= n or line[i] != '"':
            raise ValueError(f"malformed label value in {line!r}")
        i += 1
        buf = []
        while i < n and line[i] != '"':
            c = line[i]
            if c == "\\" and i + 1 < n:
                buf.append(_UNESCAPE.get(line[i + 1],
                                         "\\" + line[i + 1]))
                i += 2
            else:
                buf.append(c)
                i += 1
        if i >= n:
            raise ValueError(f"unterminated label value in {line!r}")
        labels.append((key, "".join(buf)))
        i += 1                       # closing quote
        if i < n and line[i] == ",":
            i += 1
    if i >= n:
        raise ValueError(f"unterminated label set in {line!r}")
    return tuple(labels), i + 1


def parse(text) -> dict:
    """Parse a text exposition back to {sample_name: float} (tests /
    round-trip verification; sample_name includes the label set, in the
    same canonical form ``MetricsRegistry.snapshot()`` emits — label
    values are unescaped).

    Hardened against the cases a naive ``rpartition(" ")`` mis-handles:
    label values containing spaces or escape sequences (the value/name
    boundary is found by scanning the quoted label set, not by
    splitting on the last space), multiple blanks between sample and
    value, and an optional trailing timestamp."""
    from deeplearning4j_tpu.telemetry.registry import _sample_name

    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            name = line[:brace]
            labels, end = _scan_labels(line, brace)
            rest = line[end:].split()
            key = _sample_name(name, labels)
        else:
            parts = line.split()
            key, rest = parts[0], parts[1:]
        if not rest:
            raise ValueError(f"sample line has no value: {line!r}")
        # rest may be [value] or [value, timestamp]
        out[key] = float(rest[0])
    return out
