"""FlightRecorder: a bounded ring buffer of structured runtime events.

Reference capability: the post-mortem side of DL4J's training UI — when
a run diverges or a serving process dies, the question is always "what
were the last N steps / requests / compiles doing?". DL4J answered it
with StatsStorage history; on a TPU pod the answer must be cheap enough
to be always-on (one deque append per event, no device work, no I/O)
and dumpable the instant something goes wrong (ISSUE 3 tentpole).

Event sources wired in this PR:

- ``step``: per-step health summaries from telemetry.health monitors
  (loop, step, worst update:param ratio, non-finite count);
- ``compile``: every XLA backend compile seen by the jax.monitoring
  hook (telemetry.registry);
- ``serving``: one summary per DynamicBatcher request (request id,
  model, outcome, queue wait) and model register/warmup events;
- ``health_violation`` / ``divergence``: policy trips from
  telemetry.health, naming the offending layer and step.

The buffer dumps as JSONL on divergence (telemetry.health HALT), on
demand via ``GET /debug/flightrecorder`` (ui/server.py), via ``dump()``,
or — opt-in — on any uncaught exception (``install_excepthook()``).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import tempfile
import threading
import time
from collections import deque

DEFAULT_CAPACITY = 4096

_state = {"enabled": True, "recorder": None}
_lock = threading.Lock()


def enabled() -> bool:
    return _state["enabled"]


def enable():
    _state["enabled"] = True
    return get_recorder()


def disable():
    _state["enabled"] = False


def _json_default(v):
    """Dump-time coercion for numpy scalars/arrays riding in events."""
    if hasattr(v, "item"):
        return v.item()
    if hasattr(v, "tolist"):
        return v.tolist()
    return str(v)


class FlightRecorder:
    """Thread-safe ring buffer of event dicts. ``record`` is the only
    hot-path entry point: one flag check, one dict build, one deque
    append — no I/O, no device touch, bounded memory."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._events: deque = deque(maxlen=self.capacity)
        self._seq = itertools.count(1)
        self._lock = threading.Lock()

    def record(self, kind: str, **fields):
        if not _state["enabled"]:
            return None
        # ts (wall) orders events ACROSS processes — the fleet router's
        # /debug/fleet/flight merge-sorts worker rings by it; mono is
        # the drift-free intra-process clock for interval arithmetic
        evt = {"seq": next(self._seq), "ts": round(time.time(), 6),
               "mono": round(time.monotonic(), 6), "kind": kind}
        evt.update(fields)
        with self._lock:
            self._events.append(evt)
        return evt

    def events(self, kind: str | None = None) -> list:
        with self._lock:
            evts = list(self._events)
        if kind is not None:
            evts = [e for e in evts if e["kind"] == kind]
        return evts

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        return len(self._events)

    # -- dumping -------------------------------------------------------------
    def dump_jsonl(self) -> str:
        """The whole buffer as JSONL, oldest event first."""
        return "\n".join(json.dumps(e, default=_json_default)
                         for e in self.events()) + "\n"

    def dump(self, path: str | None = None) -> str:
        """Write the buffer as JSONL and return the path (default:
        ``<tmpdir>/dl4j_flight_<pid>.jsonl``)."""
        if path is None:
            path = default_dump_path()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(self.dump_jsonl())
        return path


def default_dump_path() -> str:
    return os.path.join(tempfile.gettempdir(),
                        f"dl4j_flight_{os.getpid()}.jsonl")


def get_recorder() -> FlightRecorder:
    """The process-wide recorder (created lazily)."""
    rec = _state["recorder"]
    if rec is None:
        with _lock:
            rec = _state["recorder"]
            if rec is None:
                rec = FlightRecorder()
                _state["recorder"] = rec
    return rec


def record(kind: str, **fields):
    """Module-level convenience: record into the process recorder."""
    if not _state["enabled"]:
        return None
    return get_recorder().record(kind, **fields)


def dump(path: str | None = None) -> str:
    return get_recorder().dump(path)


# -- crash dump (opt-in) -----------------------------------------------------

_prev_excepthook = None


def install_excepthook():
    """Dump the flight recorder on any uncaught exception, then delegate
    to the previous hook. Idempotent."""
    global _prev_excepthook
    if _prev_excepthook is not None:
        return
    _prev_excepthook = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            record("crash", error=f"{exc_type.__name__}: {exc}")
            path = get_recorder().dump()
            print(f"[dl4j] flight recorder dumped to {path}",
                  file=sys.stderr)
        except Exception:
            pass
        _prev_excepthook(exc_type, exc, tb)

    sys.excepthook = _hook


def uninstall_excepthook():
    global _prev_excepthook
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None
