"""K-means clustering (reference: deeplearning4j-nearestneighbors-parent's
org.deeplearning4j.clustering.kmeans.KMeansClustering, SURVEY.md §2.7).
Lloyd iterations as jitted device ops — assignment is one big argmin over
a distance matrix (MXU-friendly), update is a segment mean."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


class KMeansClustering:
    def __init__(self, k, maxIterations=100, distance="euclidean", seed=0):
        self.k = int(k)
        self.maxIterations = maxIterations
        self.seed = seed
        self.centers = None

    @staticmethod
    def setup(k, maxIterations=100, distanceFunction="euclidean", seed=0):
        return KMeansClustering(k, maxIterations, distanceFunction, seed)

    def applyTo(self, points):
        x = jnp.asarray(np.asarray(points, np.float32))
        n = x.shape[0]
        rng = np.random.default_rng(self.seed)
        centers = x[rng.choice(n, self.k, replace=False)]

        @jax.jit
        def step(centers):
            d = (jnp.sum(x * x, 1)[:, None]
                 - 2 * x @ centers.T + jnp.sum(centers * centers, 1)[None])
            assign = jnp.argmin(d, axis=1)
            one_hot = jax.nn.one_hot(assign, self.k, dtype=x.dtype)
            counts = jnp.maximum(one_hot.sum(0), 1.0)
            new_centers = (one_hot.T @ x) / counts[:, None]
            # keep empty clusters where they were
            empty = (one_hot.sum(0) == 0)[:, None]
            return jnp.where(empty, centers, new_centers), assign

        assign = None
        for _ in range(self.maxIterations):
            new_centers, assign = step(centers)
            if bool(jnp.allclose(new_centers, centers, atol=1e-6)):
                centers = new_centers
                break
            centers = new_centers
        self.centers = np.asarray(centers)
        return np.asarray(assign)
