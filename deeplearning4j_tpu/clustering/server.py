"""Nearest-neighbors HTTP server.

Reference capability: deeplearning4j-nearestneighbors-parent's
nearestneighbor-server module (SURVEY.md §2.7 — "VPTree search + a small
REST server module"). A stdlib ThreadingHTTPServer replaces the
reference's Play/vertx stack; endpoints mirror the reference's JSON API:

    POST /knn      {"ndarray": [...point...], "k": 5}
    POST /knnnew   {"ndarray": [...new point...], "k": 5}  (same here:
                   the reference distinguishes indexed vs new points)
    GET  /status
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def _json(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        srv = self.server.nn
        if self.path == "/status":
            self._json(200, {"status": "ok",
                             "numPoints": int(srv.points.shape[0]),
                             "dim": int(srv.points.shape[1])})
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        srv = self.server.nn
        if self.path not in ("/knn", "/knnnew"):
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            k = int(req.get("k", 1))
            point = np.asarray(req["ndarray"], np.float32)
            results = srv.query(point, k)
            self._json(200, {"results": results})
        except Exception as e:  # noqa: BLE001 — surface as JSON error
            self._json(400, {"error": str(e)})


class NearestNeighborsServer:
    """Serve k-NN queries over a VPTree-indexed point set."""

    def __init__(self, points, labels=None, distance="euclidean"):
        from deeplearning4j_tpu.clustering import VPTree

        self.points = np.asarray(points, np.float32)
        self.labels = list(labels) if labels is not None else None
        self.tree = VPTree(self.points, distance=distance)
        self._httpd = None
        self._thread = None
        self.port = None

    def query(self, point, k):
        idxs, dists = self.tree.search(point, k)
        out = []
        for i, d in zip(idxs, dists):
            row = {"index": int(i), "distance": float(d)}
            if self.labels is not None:
                row["label"] = self.labels[int(i)]
            out.append(row)
        return out

    def start(self, port=9200):
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.nn = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="dl4j:serving:clustering")
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            # serve_forever returns after shutdown(); join so stop()
            # means stopped and worker errors can't outlive the server
            if self._thread is not None:
                self._thread.join(timeout=5.0)
            self._thread = None
        return self
