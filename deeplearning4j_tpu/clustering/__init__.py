from deeplearning4j_tpu.clustering.vptree import VPTree  # noqa: F401
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering  # noqa: F401,E501
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne  # noqa: F401,E501
from deeplearning4j_tpu.clustering.server import NearestNeighborsServer  # noqa: F401,E501
