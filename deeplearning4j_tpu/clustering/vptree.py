"""VP-tree exact nearest-neighbor search.

Reference capability: deeplearning4j-nearestneighbors
org.deeplearning4j.clustering.vptree.VPTree (SURVEY.md §2.7): vantage-point
tree over a point set with euclidean/cosine distance; search(k) returns the
k nearest. Host-side recursive structure (tree search is pointer-chasing,
not MXU work); distance batches use numpy vectorization."""

from __future__ import annotations

import numpy as np


class _Node:
    __slots__ = ("index", "radius", "inside", "outside")

    def __init__(self, index):
        self.index = index
        self.radius = 0.0
        self.inside = None
        self.outside = None


class VPTree:
    def __init__(self, points, distance="euclidean", seed=0):
        self.points = np.asarray(points, np.float64)
        self.distance = distance
        if distance == "cosine":
            norms = np.linalg.norm(self.points, axis=1, keepdims=True)
            self._unit = self.points / np.maximum(norms, 1e-12)
        rng = np.random.default_rng(seed)
        self.root = self._build(list(range(len(self.points))), rng)

    def _dist_many(self, idx, others):
        if self.distance == "cosine":
            return 1.0 - self._unit[others] @ self._unit[idx]
        diff = self.points[others] - self.points[idx]
        return np.sqrt(np.sum(diff * diff, axis=1))

    def _dist_point(self, q, others):
        if self.distance == "cosine":
            qn = q / max(np.linalg.norm(q), 1e-12)
            return 1.0 - self._unit[others] @ qn
        diff = self.points[others] - q
        return np.sqrt(np.sum(diff * diff, axis=1))

    def _build(self, indices, rng):
        if not indices:
            return None
        vp = indices[rng.integers(len(indices))]
        rest = [i for i in indices if i != vp]
        node = _Node(vp)
        if not rest:
            return node
        d = self._dist_many(vp, rest)
        node.radius = float(np.median(d))
        inside = [rest[i] for i in range(len(rest))
                  if d[i] <= node.radius]
        outside = [rest[i] for i in range(len(rest))
                   if d[i] > node.radius]
        node.inside = self._build(inside, rng)
        node.outside = self._build(outside, rng)
        return node

    def search(self, query, k):
        """Returns (indices, distances) of the k nearest points."""
        q = np.asarray(query, np.float64)
        best: list[tuple[float, int]] = []  # sorted (dist, idx), len<=k

        def visit(node):
            if node is None:
                return
            d = float(self._dist_point(q, [node.index])[0])
            if len(best) < k:
                best.append((d, node.index))
                best.sort()
            elif d < best[-1][0]:
                best[-1] = (d, node.index)
                best.sort()
            tau = best[-1][0] if len(best) == k else np.inf
            if d <= node.radius:
                visit(node.inside)
                if d + tau > node.radius:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau <= node.radius:
                    visit(node.inside)

        visit(self.root)
        idxs = [i for _, i in best]
        dists = [d for d, _ in best]
        return idxs, dists
