"""t-SNE embedding.

Reference capability: org.deeplearning4j.plot.BarnesHutTsne
(deeplearning4j-manifold; SURVEY.md §2.7 domain libs). The reference
approximates the repulsive forces with a Barnes-Hut quad/sp-tree on the
HOST because exact t-SNE is O(N²) per iteration in scalar code; on TPU
the O(N²) pairwise kernels are two dense matmul-shaped reductions that
the MXU eats directly, so this implementation keeps EXACT gradients and
jits the entire gradient-descent loop (lax.scan) into one executable —
idiomatic XLA rather than a tree-code translation. The BarnesHutTsne
builder surface (theta, perplexity, learningRate, momentum, maxIter) is
kept; theta is accepted for config parity.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def _pairwise_sq_dists(x):
    s = jnp.sum(jnp.square(x), axis=1)
    d = s[:, None] + s[None, :] - 2.0 * (x @ x.T)
    return jnp.maximum(d, 0.0)


def _binary_search_betas(d, perplexity, iters=40):
    """Per-point precision (beta = 1/2sigma²) matching the target
    perplexity, fully vectorized: 40 bisection steps over all rows at
    once (reference: the per-row host loop in BarnesHutTsne.computeGaussianPerplexity)."""
    n = d.shape[0]
    log_u = jnp.log(perplexity)
    eye = jnp.eye(n, dtype=bool)

    def entropy(beta):
        p = jnp.where(eye, 0.0, jnp.exp(-d * beta[:, None]))
        sum_p = jnp.maximum(jnp.sum(p, axis=1), 1e-12)
        h = jnp.log(sum_p) + beta * jnp.sum(d * p, axis=1) / sum_p
        return h, p / sum_p[:, None]

    def body(carry, _):
        beta, lo, hi = carry
        h, _ = entropy(beta)
        too_high = h > log_u          # entropy too high -> raise beta
        new_lo = jnp.where(too_high, beta, lo)
        new_hi = jnp.where(too_high, hi, beta)
        new_beta = jnp.where(
            too_high,
            jnp.where(jnp.isinf(new_hi), beta * 2.0,
                      (beta + new_hi) / 2.0),
            (new_lo + beta) / 2.0)
        return (new_beta, new_lo, new_hi), 0.0

    beta0 = jnp.ones((n,))
    lo0 = jnp.zeros((n,))
    hi0 = jnp.full((n,), jnp.inf)
    (beta, _, _), _ = lax.scan(body, (beta0, lo0, hi0), None, length=iters)
    _, p = entropy(beta)
    return p


class BarnesHutTsne:
    """Builder-compatible t-SNE (exact gradients, whole loop jitted)."""

    class Builder:
        # setter name -> constructor kwarg; typos fail loudly
        _KEYS = {k: k for k in (
            "numDimension", "perplexity", "theta", "learningRate",
            "momentum", "finalMomentum", "maxIter", "stopLyingIteration",
            "switchMomentumIteration", "seed", "usePca")}
        _KEYS["setMaxIter"] = "maxIter"

        def __init__(self):
            self._kw = {}

        def __getattr__(self, item):
            if item.startswith("_"):
                raise AttributeError(item)
            if item not in self._KEYS:
                raise AttributeError(
                    f"unknown BarnesHutTsne setting {item!r} "
                    f"(known: {sorted(self._KEYS)})")

            def setter(v):
                self._kw[self._KEYS[item]] = v
                return self

            return setter

        def build(self):
            return BarnesHutTsne(**self._kw)

    def __init__(self, numDimension=2, perplexity=30.0, theta=0.5,
                 learningRate=200.0, momentum=0.5, finalMomentum=0.8,
                 maxIter=1000, stopLyingIteration=100,
                 switchMomentumIteration=250, seed=42, usePca=False):
        self.numDimension = int(numDimension)
        self.perplexity = float(perplexity)
        self.theta = theta                  # parity knob (exact gradients)
        self.learningRate = float(learningRate)
        self.momentum = float(momentum)
        self.finalMomentum = float(finalMomentum)
        self.maxIter = int(maxIter)
        self.stopLyingIteration = int(stopLyingIteration)
        self.switchMomentumIteration = int(switchMomentumIteration)
        self.seed = int(seed)
        self.usePca = usePca
        self._embedding = None

    def fit(self, x):
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        if self.usePca and x.shape[1] > 50:
            xc = x - x.mean(0)
            _, _, vt = np.linalg.svd(xc, full_matrices=False)
            x = xc @ vt[:50].T
        perp = min(self.perplexity, (n - 1) / 3.0)

        d = _pairwise_sq_dists(jnp.asarray(x))
        p_cond = _binary_search_betas(d, perp)
        p = (p_cond + p_cond.T) / (2.0 * n)
        p = jnp.maximum(p, 1e-12)

        key = jax.random.key(self.seed)
        y0 = 1e-4 * jax.random.normal(key, (n, self.numDimension))
        eye = jnp.eye(n, dtype=bool)

        lying, lr = 12.0, self.learningRate
        m0, m1 = self.momentum, self.finalMomentum
        stop_lying = self.stopLyingIteration
        switch_mom = self.switchMomentumIteration

        def grad_kl(y, p_eff):
            dy = _pairwise_sq_dists(y)
            num = jnp.where(eye, 0.0, 1.0 / (1.0 + dy))   # student-t
            q = jnp.maximum(num / jnp.sum(num), 1e-12)
            w = (p_eff - q) * num                         # [N, N]
            # grad_i = 4 * sum_j w_ij (y_i - y_j)
            return 4.0 * (jnp.sum(w, axis=1, keepdims=True) * y - w @ y)

        def body(carry, it):
            y, vel, gains = carry
            p_eff = jnp.where(it < stop_lying, p * lying, p)
            g = grad_kl(y, p_eff)
            # DL4J/van-der-Maaten gain adaptation
            gains = jnp.where(jnp.sign(g) != jnp.sign(vel),
                              gains + 0.2, gains * 0.8)
            gains = jnp.maximum(gains, 0.01)
            mom = jnp.where(it < switch_mom, m0, m1)
            vel = mom * vel - lr * gains * g
            y = y + vel
            y = y - jnp.mean(y, axis=0)
            return (y, vel, gains), 0.0

        @jax.jit
        def run(y0):
            init = (y0, jnp.zeros_like(y0), jnp.ones_like(y0))
            (y, _, _), _ = lax.scan(body, init,
                                    jnp.arange(self.maxIter))
            return y

        self._embedding = np.asarray(run(y0))
        return self

    def getData(self) -> np.ndarray:
        if self._embedding is None:
            raise RuntimeError("call fit() first")
        return self._embedding

    def saveAsFile(self, labels, path):
        """Reference: BarnesHutTsne.saveAsFile(labels, path) — one
        'x y ... label' line per point."""
        emb = self.getData()
        with open(path, "w") as f:
            for i in range(emb.shape[0]):
                coords = " ".join(f"{v:.6f}" for v in emb[i])
                f.write(f"{coords} {labels[i]}\n")
