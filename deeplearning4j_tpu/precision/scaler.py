"""Dynamic loss scaling, compiled INTO the jitted train step.

The classic fp16 recipe (also the insurance policy for bf16): multiply
the loss by a large power-of-two scale before the backward pass so small
gradients survive the narrow mantissa/exponent, divide the gradients by
the same scale before the updater, and — when any gradient came back
non-finite — discard the step on device and halve the scale. After
``growth_interval`` consecutive good steps the scale doubles back.

Everything here is traced math: the scaler state is a tiny pytree of
device scalars donated through the step like the params, the finite
check is one fused reduction riding with the gradients (like the PR 3
health row), and the skip reuses the keep-old-params ``jnp.where`` gate
— an overflow step costs ZERO extra host syncs. The host observes the
state one step behind through ``precision.monitor_for`` (dl4j_precision_*
metrics + flight-recorder ``precision`` events).

Scales are powers of two throughout, so ``scaled_loss / scale`` and
``grad / scale`` are exact in every binary float format.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_tm = jax.tree_util.tree_map

# state keys (all 0-d device arrays):
#   scale      f32  current loss scale
#   good_steps i32  consecutive finite steps since the last change
#   overflows  i32  cumulative non-finite (skipped) steps
STATE_KEYS = ("scale", "good_steps", "overflows")

MAX_SCALE = 2.0 ** 31
MIN_SCALE = 1.0


class DynamicLossScaler:
    """Built once per net from its Policy; all methods are traced."""

    def __init__(self, init_scale=2.0 ** 15, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000):
        self.init_scale = float(init_scale)
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.dynamic = True

    @classmethod
    def for_policy(cls, policy):
        """The scaler a Policy asks for, or None (no scaling)."""
        if not policy.scaling_enabled:
            return None
        if policy.loss_scaling == "dynamic":
            return cls(policy.init_scale, policy.growth_factor,
                       policy.backoff_factor, policy.growth_interval)
        return FixedLossScaler(float(policy.loss_scaling))

    def init_state(self) -> dict:
        return {"scale": jnp.float32(self.init_scale),
                "good_steps": jnp.int32(0),
                "overflows": jnp.int32(0)}

    # -- traced step math ----------------------------------------------------
    def scale_loss(self, loss, state):
        return loss.astype(jnp.float32) * state["scale"]

    def unscale(self, grads, state):
        inv = (jnp.float32(1.0) / state["scale"]).astype(jnp.float32)
        return _tm(lambda g: g * inv.astype(g.dtype), grads)

    @staticmethod
    def all_finite(tree):
        """One fused boolean: every leaf of ``tree`` is finite. This is
        the gate condition — it must see the gradients BEFORE the
        updater touches them."""
        leaves = [x for x in jax.tree_util.tree_leaves(tree)
                  if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)]
        if not leaves:
            return jnp.bool_(True)
        return jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]).all()

    def next_state(self, state, finite) -> dict:
        grown = (state["good_steps"] + 1) >= self.growth_interval
        scale_ok = jnp.where(
            grown,
            jnp.minimum(state["scale"] * self.growth_factor, MAX_SCALE),
            state["scale"])
        scale = jnp.where(
            finite, scale_ok,
            jnp.maximum(state["scale"] * self.backoff_factor, MIN_SCALE))
        good = jnp.where(finite & ~grown, state["good_steps"] + 1, 0)
        overflows = state["overflows"] + jnp.where(finite, 0, 1).astype(
            jnp.int32)
        return {"scale": scale.astype(jnp.float32),
                "good_steps": good.astype(jnp.int32),
                "overflows": overflows}


class FixedLossScaler(DynamicLossScaler):
    """Constant scale: still unscales, still finite-checks and skips
    overflow steps, never adjusts."""

    def __init__(self, scale):
        super().__init__(init_scale=scale)
        self.dynamic = False

    def next_state(self, state, finite):
        return {"scale": state["scale"],
                "good_steps": jnp.where(finite, state["good_steps"] + 1,
                                        0).astype(jnp.int32),
                "overflows": state["overflows"] + jnp.where(
                    finite, 0, 1).astype(jnp.int32)}
