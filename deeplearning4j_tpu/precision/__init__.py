"""Precision subsystem (ISSUE 4 tentpole): policy-based mixed-precision
training and int8 post-training quantization for serving.

Training::

    conf = (NeuralNetConfiguration.Builder()
            .precision("bf16_mixed")        # fp32 master, bf16 compute,
            .list() ... .build())           # dynamic loss scaling
    net = MultiLayerNetwork(conf).init()
    net.fit(data, epochs)                   # scaler compiled into the step

Serving::

    from deeplearning4j_tpu.precision import quantize
    qsv = quantize(net, calibration_batches, example_shape=(784,))
    session.register("model_int8", qsv, warmup=True)   # unchanged route

See docs/PRECISION.md for semantics and the PTQ recipe.
"""

from deeplearning4j_tpu.precision.monitor import (
    PrecisionInstruments, PrecisionMonitor, monitor_for)
from deeplearning4j_tpu.precision.policy import (
    NAMED_POLICIES, Policy, cast_floating, named_policy, resolve_policy)
from deeplearning4j_tpu.precision.quantize import (
    QuantizedServable, dequantize_array, quantize, quantize_array,
    quantize_params)
from deeplearning4j_tpu.precision.scaler import (
    DynamicLossScaler, FixedLossScaler)

__all__ = [
    "DynamicLossScaler", "FixedLossScaler", "NAMED_POLICIES", "Policy",
    "PrecisionInstruments", "PrecisionMonitor", "QuantizedServable",
    "cast_floating", "dequantize_array", "monitor_for", "named_policy",
    "quantize", "quantize_array", "quantize_params", "resolve_policy",
]
